"""Multi-step dispatch (`--steps-per-call`): one fori_loop program over
stacked batches must be numerically identical to N separate step calls.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.data import StackedBatches
from fm_spark_tpu.sparse import (
    make_field_sparse_multistep,
    make_field_sparse_sgd_step,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B, N = 5, 64, 4, 32, 4


def _batches(rng, n_batches):
    out = []
    for _ in range(n_batches):
        out.append((
            rng.integers(0, BUCKET, size=(B, F)).astype(np.int32),
            rng.normal(size=(B, F)).astype(np.float32),
            rng.integers(0, 2, B).astype(np.float32),
            np.ones((B,), np.float32),
        ))
    return out


@pytest.mark.parametrize("host_dedup", [False, True],
                         ids=["plain", "host_dedup"])
def test_multistep_matches_per_step(rng, host_dedup):
    from fm_spark_tpu.ops.scatter import dedup_aux

    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    cfg = dict(learning_rate=0.2, lr_schedule="inv_sqrt", optimizer="sgd")
    if host_dedup:
        cfg.update(sparse_update="dedup", host_dedup=True)
    config = TrainConfig(**cfg)
    batches = _batches(rng, 2 * N)
    if host_dedup:
        batches = [(*b, dedup_aux(b[0])) for b in batches]

    params_s = spec.init(jax.random.key(0))
    params_m = jax.tree_util.tree_map(jnp.copy, params_s)

    step = make_field_sparse_sgd_step(spec, config)
    for i, b in enumerate(batches):
        args = jax.tree_util.tree_map(jnp.asarray, tuple(b))
        params_s, loss_s = step(params_s, jnp.int32(i), *args)

    mstep = make_field_sparse_multistep(spec, config, N)
    for call in range(2):
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(np.stack(xs, axis=0)),
            *[tuple(b) for b in batches[call * N: (call + 1) * N]],
        )
        params_m, loss_m = mstep(
            params_m, jnp.int32(call * N), jnp.int32(N), *stacked
        )
    np.testing.assert_allclose(float(loss_m), float(loss_s), rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_m["vw"][f]), np.asarray(params_s["vw"][f]),
            rtol=1e-5, atol=1e-7, err_msg=f"field {f}",
        )


def test_multistep_partial_tail(rng):
    """m < N executes exactly m steps; trailing stacked slices are inert."""
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.2, optimizer="sgd")
    batches = _batches(rng, N)
    params_s = spec.init(jax.random.key(1))
    params_m = jax.tree_util.tree_map(jnp.copy, params_s)
    step = make_field_sparse_sgd_step(spec, config)
    m = 2
    for i, b in enumerate(batches[:m]):
        params_s, _ = step(params_s, jnp.int32(i),
                           *map(jnp.asarray, b))
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.asarray(np.stack(xs, axis=0)),
        *[tuple(b) for b in batches],
    )
    mstep = make_field_sparse_multistep(spec, config, N)
    params_m, _ = mstep(params_m, jnp.int32(0), jnp.int32(m), *stacked)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_m["vw"][f]), np.asarray(params_s["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )


def test_multistep_ffm(rng):
    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step

    config = TrainConfig(learning_rate=0.2, optimizer="sgd")
    batches = _batches(rng, N)
    params_s = spec.init(jax.random.key(2))
    params_m = jax.tree_util.tree_map(jnp.copy, params_s)
    step = make_field_ffm_sparse_sgd_step(spec, config)
    for i, b in enumerate(batches):
        params_s, _ = step(params_s, jnp.int32(i), *map(jnp.asarray, b))
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.asarray(np.stack(xs, axis=0)),
        *[tuple(b) for b in batches],
    )
    mstep = make_field_sparse_multistep(spec, config, N)
    params_m, _ = mstep(params_m, jnp.int32(0), jnp.int32(N), *stacked)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_m["vw"][f]), np.asarray(params_s["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )


@pytest.mark.parametrize("compact", [False, True],
                         ids=["plain", "compact_aux"])
def test_multistep_deepfm(rng, compact):
    """The DeepFM roll (VERDICT r3 #6): optax state threads through the
    fori carry — params AND adam moments must match N separate calls
    (with and without the stacked compact host aux riding the call)."""
    from fm_spark_tpu.ops.scatter import compact_aux
    from fm_spark_tpu.sparse import (
        make_field_deepfm_multistep,
        make_field_deepfm_sparse_step,
    )

    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(8, 8), init_std=0.1,
    )
    cfg = dict(learning_rate=0.05, lr_schedule="inv_sqrt",
               optimizer="adam", reg_factors=1e-3,
               reg_linear=1e-4, reg_bias=1e-4)
    if compact:
        cfg.update(sparse_update="dedup", host_dedup=True,
                   compact_cap=B)
    config = TrainConfig(**cfg)
    batches = _batches(rng, 2 * N)
    if compact:
        batches = [(*b, compact_aux(b[0], B)) for b in batches]

    params_s = spec.init(jax.random.key(3))
    params_m = jax.tree_util.tree_map(jnp.copy, params_s)

    step = make_field_deepfm_sparse_step(spec, config)
    opt_s = step.init_opt_state(params_s)
    for i, b in enumerate(batches):
        params_s, opt_s, loss_s = step(params_s, opt_s, jnp.int32(i),
                                       *map(jnp.asarray, b))

    mstep = make_field_deepfm_multistep(spec, config, N)
    opt_m = mstep.init_opt_state(params_m)
    for call in range(2):
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.asarray(np.stack(xs, axis=0)),
            *[tuple(b) for b in batches[call * N: (call + 1) * N]],
        )
        params_m, opt_m, loss_m = mstep(
            params_m, opt_m, jnp.int32(call * N), jnp.int32(N), *stacked
        )
    np.testing.assert_allclose(float(loss_m), float(loss_s), rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_m["vw"][f]), np.asarray(params_s["vw"][f]),
            rtol=1e-5, atol=1e-7, err_msg=f"field {f}",
        )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        jax.device_get(params_m["mlp"]), jax.device_get(params_s["mlp"]),
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        jax.device_get(opt_m), jax.device_get(opt_s),
    )


def test_multistep_deepfm_partial_tail(rng):
    from fm_spark_tpu.sparse import (
        make_field_deepfm_multistep,
        make_field_deepfm_sparse_step,
    )

    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(8,), init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.05, optimizer="adam")
    batches = _batches(rng, N)
    params_s = spec.init(jax.random.key(4))
    params_m = jax.tree_util.tree_map(jnp.copy, params_s)
    step = make_field_deepfm_sparse_step(spec, config)
    opt_s = step.init_opt_state(params_s)
    m = 2
    for i, b in enumerate(batches[:m]):
        params_s, opt_s, _ = step(params_s, opt_s, jnp.int32(i),
                                  *map(jnp.asarray, b))
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.asarray(np.stack(xs, axis=0)),
        *[tuple(b) for b in batches],
    )
    mstep = make_field_deepfm_multistep(spec, config, N)
    opt_m = mstep.init_opt_state(params_m)
    params_m, opt_m, _ = mstep(params_m, opt_m, jnp.int32(0),
                               jnp.int32(m), *stacked)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_m["vw"][f]), np.asarray(params_s["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )
    # The adam count must have advanced exactly m times.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7),
        jax.device_get(opt_m), jax.device_get(opt_s),
    )


def test_stacked_batches_wrapper(rng):
    from fm_spark_tpu.data import Batches

    ids = rng.integers(0, 16, size=(64, 3)).astype(np.int32)
    src = Batches(ids, np.ones((64, 3), np.float32),
                  rng.integers(0, 2, 64).astype(np.float32),
                  batch_size=16, seed=0)
    ref = Batches(ids, np.ones((64, 3), np.float32),
                  rng.integers(0, 2, 64).astype(np.float32),
                  batch_size=16, seed=0)
    stacked = StackedBatches(src, 3)
    got = stacked.next_batch()
    assert got[0].shape == (3, 16, 3)
    for j in range(3):
        np.testing.assert_array_equal(got[0][j], ref.next_batch()[0])


@pytest.mark.slow
def test_cli_steps_per_call_smoke():
    """fmtpu train --steps-per-call 4 runs end-to-end (single device)."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(__file__))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "fm_spark_tpu.cli",
         "train", "--config", "criteo1tb_fm_r64", "--synthetic", "4096",
         "--steps", "14", "--batch-size", "512",
         "--strategy", "field_sparse", "--steps-per-call", "4",
         "--sparse-update", "dedup", "--host-dedup", "--prefetch", "2",
         "--test-fraction", "0.2", "--log-every", "4"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert '"eval"' in proc.stdout or "auc" in proc.stdout


def test_stacked_batches_total_bounds_source_consumption(rng):
    """The tail stack pads with copies instead of over-reading the
    source — the checkpoint cursor stays exact for finite runs."""
    class Counting:
        def __init__(self):
            self.n = 0

        def next_batch(self):
            self.n += 1
            return (np.full((4, 2), self.n, np.int32),
                    np.ones((4, 2), np.float32),
                    np.zeros((4,), np.float32),
                    np.ones((4,), np.float32))

    src = Counting()
    stacked = StackedBatches(src, 4, total=6)
    s1 = stacked.next_batch()
    assert src.n == 4 and s1[0].shape == (4, 4, 2)
    s2 = stacked.next_batch()
    assert src.n == 6, "tail must take only the remainder"
    # Padding slices are copies of the last real batch.
    np.testing.assert_array_equal(s2[0][2], s2[0][1])
    np.testing.assert_array_equal(s2[0][3], s2[0][1])
    with pytest.raises(StopIteration):
        stacked.next_batch()


def test_cli_steps_per_call_rejects_wrong_strategy():
    from fm_spark_tpu import cli

    with pytest.raises(SystemExit, match="steps-per-call"):
        cli.main([
            "train", "--config", "criteo_kaggle_fm_r32", "--synthetic",
            "1024", "--steps", "4", "--batch-size", "256",
            "--steps-per-call", "2",
        ])


def test_cli_sharded_steps_per_call(tmp_path):
    """Round 4: --steps-per-call on the SHARDED field_sparse step (the
    8-fake-device env) — the fori rides inside the shard_map; windowed
    log cadence; compact_device composes; host aux rejected."""
    import dataclasses

    from fm_spark_tpu import cli
    from fm_spark_tpu import configs as configs_lib

    small = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"], name="msh",
        strategy="field_sparse", bucket=64, num_fields=5, rank=4,
    )
    configs_lib.CONFIGS["msh"] = small
    try:
        assert cli.main([
            "train", "--config", "msh", "--synthetic", "2048",
            "--steps", "10", "--batch-size", "256",
            "--steps-per-call", "4", "--log-every", "3",
            "--compact-device", "--compact-cap", "256",
            "--sparse-update", "dedup_sr",
            "--collective-dtype", "bfloat16", "--score-sharded",
        ]) == 0
        with pytest.raises(SystemExit, match="compact-device"):
            cli.main([
                "train", "--config", "msh", "--synthetic", "1024",
                "--steps", "4", "--batch-size", "256",
                "--steps-per-call", "2", "--host-dedup",
                "--compact-cap", "256", "--sparse-update", "dedup",
            ])
        # --ckpt-sharded with the sharded roll: the windowed periodic
        # save must write the SHARDED layout (round-4 review repro: it
        # used to write canonical, breaking the sharded resume).
        ckpt = str(tmp_path / "ck")
        base = ["train", "--config", "msh", "--synthetic", "2048",
                "--batch-size", "256", "--steps-per-call", "2",
                "--ckpt-sharded", "--checkpoint-dir", ckpt,
                "--checkpoint-every", "2", "--log-every", "2"]
        assert cli.main([*base, "--steps", "4"]) == 0
        assert cli.main([*base, "--steps", "8"]) == 0  # resumes from 4
    finally:
        del configs_lib.CONFIGS["msh"]
    # DeepFM sharded roll (optax carry through the outer-jit fori).
    dsmall = dataclasses.replace(
        configs_lib.CONFIGS["criteo1tb_deepfm"], name="mshd",
        strategy="field_sparse", bucket=64, num_fields=5, rank=4,
        mlp_dims=(8, 8),
    )
    configs_lib.CONFIGS["mshd"] = dsmall
    try:
        assert cli.main([
            "train", "--config", "mshd", "--synthetic", "2048",
            "--steps", "8", "--batch-size", "256",
            "--steps-per-call", "4", "--log-every", "3",
        ]) == 0
    finally:
        del configs_lib.CONFIGS["mshd"]


@pytest.mark.slow
def test_cli_steps_per_call_deepfm_smoke():
    """DeepFM --steps-per-call runs end-to-end with windowed cadences
    (VERDICT r3 #6: the opt state rides the fori carry)."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(__file__))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "fm_spark_tpu.cli",
         "train", "--config", "criteo1tb_deepfm", "--synthetic", "4096",
         "--steps", "14", "--batch-size", "512",
         "--strategy", "field_sparse", "--steps-per-call", "4",
         "--prefetch", "2", "--test-fraction", "0.2",
         "--log-every", "3"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # Windowed log cadence: boundaries at multiples of 3 inside each
    # 4-step window -> logs at 4, 8, 12, 14.
    assert '"step": 4' in proc.stdout and '"step": 14' in proc.stdout
