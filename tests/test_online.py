"""Continuous-learning loop tests (ISSUE 13): the time-ordered
train/eval protocol, the maximize-mode drift sentry, and the
coordinated rollback through the checkpoint chain.

The load-bearing contracts:

- a planted label-flip drift fires the sentry at the FIRST drifted
  eval day, the offending day's save is demoted (durable tombstone,
  ``last_good`` republished at the pre-drift save) and the weights
  roll back while the step axis keeps advancing (no step reuse);
- the sentry's trailing window is DURABLE (saved in each checkpoint's
  ``extra``) and a killed run replays its missed eval on resume, so a
  crash can never skip a drift check;
- ``quality_eval`` ledger records land with their own leg namespace
  and sentinel cohorts;
- ``cli train --online --optimizer ftrl`` runs the whole protocol end
  to end, and a serving follower on the same chain never loads the
  demoted generation.
"""

import json
import os

import numpy as np
import pytest

from fm_spark_tpu import models, online
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.data import synthetic_ctr
from fm_spark_tpu.resilience import faults, watchdog
from fm_spark_tpu.resilience.divergence import DivergenceDetected
from fm_spark_tpu.train import FMTrainer, TrainConfig
from fm_spark_tpu.utils.logging import EventLog, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    watchdog.clear()
    yield
    faults.clear()
    watchdog.clear()


def _days(n_days=8, n=4096, features=256, drift_day=None, seed=3):
    ids, vals, labels = synthetic_ctr(n, features, 4, seed=seed)
    days = online.split_days(ids, vals, labels, n_days)
    if drift_day is not None:
        days = [(i, v, (1.0 - l).astype(np.float32)
                 if k >= drift_day else l)
                for k, (i, v, l) in enumerate(days)]
    return days


def _trainer(features=256, optimizer="ftrl", batch=128):
    spec = models.FMSpec(num_features=features, rank=4, init_std=0.05)
    cfg = TrainConfig(num_steps=0, batch_size=batch, learning_rate=0.1,
                      lr_schedule="constant", optimizer=optimizer,
                      log_every=10_000)
    tr = FMTrainer(spec, cfg)
    tr.logger._stream = None
    return tr


def test_split_days_is_temporal_and_validates():
    ids, vals, labels = synthetic_ctr(100, 64, 4, seed=0)
    days = online.split_days(ids, vals, labels, 4)
    assert sum(len(d[2]) for d in days) == 100
    assert np.array_equal(np.concatenate([d[0] for d in days]), ids)
    with pytest.raises(ValueError, match=">= 2 days"):
        online.split_days(ids, vals, labels, 1)


def test_drift_guard_requires_max_mode(tmp_path):
    from fm_spark_tpu.resilience.divergence import DivergenceGuard

    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    with pytest.raises(ValueError, match="max"):
        online.run_online(tr, _days(), ck,
                          sentry=DivergenceGuard(mode="min"))
    ck.close()


def test_label_flip_drift_demotes_and_rolls_back(tmp_path):
    """The headline protocol: AUC collapses at the first drifted eval
    day, the sentry fires, the drifted day's save is demoted with a
    durable tombstone, last_good republishes at the pre-drift save,
    the weights roll back, and the step axis continues past the
    tombstoned frontier (no step number reuse)."""
    journal = EventLog(str(tmp_path / "health.jsonl"))
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False, journal=journal)
    days = _days(drift_day=5)
    summary = online.run_online(
        tr, days, ck, sentry=online.drift_guard(journal=journal),
        journal=journal)
    assert summary["rollbacks"] == 1
    assert summary["demoted_steps"]
    rolled = [d for d in summary["days"] if d["rolled_back"]]
    assert rolled and rolled[0]["eval_day"] == 5  # first drifted day
    # Chain state: demoted steps tombstoned, pointer never vouches for
    # a vetoed step, and the final tip is a fresh post-rollback save.
    stones = ck.tombstoned_steps()
    assert set(summary["demoted_steps"]) <= stones
    assert summary["last_good"] not in stones
    assert summary["final_step"] > max(stones)
    evs = [e.get("event") for e in read_events(
        str(tmp_path / "health.jsonl"))]
    for wanted in ("divergence_detected", "generation_demoted",
                   "last_good_republished", "online_rollback",
                   "quality_eval"):
        assert wanted in evs
    ck.close()
    journal.close()


def test_no_drift_means_no_rollback(tmp_path):
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    summary = online.run_online(tr, _days(n_days=5), ck,
                                sentry=online.drift_guard())
    assert summary["rollbacks"] == 0
    assert ck.tombstoned_steps() == set()
    assert summary["last_good"] == summary["final_step"]
    ck.close()


def test_kill_between_save_and_eval_replays_the_drift_check(tmp_path):
    """The crash window that could silently skip a drift verdict: the
    run dies AFTER the drifted day's save commits, BEFORE its eval
    runs. The resumed run must REPLAY the missed eval from durable
    sentry state (the checkpoint's ``extra``) and still fire the
    sentry — bit-identically to the uninterrupted run."""
    days = _days(drift_day=5)
    journal = EventLog(str(tmp_path / "health.jsonl"))

    # Attempt 1: die at the 6th eval (eval day 6 == the drift check
    # ... occurrence 5 is eval day 5, the first drifted one) — kill
    # exactly AT the drifted eval, before it can judge.
    faults.activate("online_eval@5=error")
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False, journal=journal)
    with pytest.raises(faults.FaultInjected):
        online.run_online(tr, days, ck,
                          sentry=online.drift_guard(journal=journal),
                          journal=journal)
    ck.close()
    faults.clear()

    # Attempt 2 (the resume): fresh trainer + checkpointer over the
    # same chain; the replayed eval must fire the sentry and demote.
    tr2 = _trainer()
    ck2 = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                       async_save=False, journal=journal)
    summary = online.run_online(
        tr2, days, ck2, sentry=online.drift_guard(journal=journal),
        journal=journal)
    assert summary["rollbacks"] == 1
    rolled = [d for d in summary["days"] if d["rolled_back"]]
    assert rolled and rolled[0]["eval_day"] == 5
    assert set(summary["demoted_steps"]) <= ck2.tombstoned_steps()
    ck2.close()
    journal.close()


def test_online_eval_watchdog_phase_bounds_a_hang(tmp_path):
    """The ``online_eval`` watchdog phase (KNOWN_PHASES): a hang inside
    the day-eval pass becomes a structured HangDetected instead of a
    silently stalled drift sentry."""
    faults.activate("online_eval@1=hang:0.3")
    watchdog.configure({"online_eval": 0.05}, action="raise")
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    with pytest.raises(watchdog.HangDetected, match="online_eval"):
        online.run_online(tr, _days(n_days=4), ck,
                          sentry=online.drift_guard())
    ck.close()


def test_rollback_budget_exhaustion_propagates(tmp_path):
    """Persistent drift is a data/model problem: when the sentry's
    rollback budget is spent, the verdict PROPAGATES (after demoting —
    the bad model still must not serve)."""
    days = _days(drift_day=4, n_days=8)
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    sentry = online.drift_guard(max_rollbacks=0)
    with pytest.raises(DivergenceDetected):
        online.run_online(tr, days, ck, sentry=sentry)
    # The demotion still happened before the propagation.
    assert ck.tombstoned_steps()
    ck.close()


def test_quality_eval_ledger_records_and_cohorts(tmp_path):
    from fm_spark_tpu.obs.ledger import (
        PerfLedger,
        measurement_fingerprint,
    )

    ledger = PerfLedger(str(tmp_path / "ledger.jsonl"))
    fp = measurement_fingerprint(variant="quality/test/ftrl",
                                 model="fm", batch=128, n_chips=1)
    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    summary = online.run_online(
        tr, _days(n_days=5), ck, sentry=online.drift_guard(),
        ledger=ledger, leg="quality/test/ftrl", fingerprint=fp,
        run_id="r-test")
    recs = ledger.records(kind="quality_eval")
    assert len(recs) == summary["days_trained"]
    assert all(r["leg"] == "quality/test/ftrl" for r in recs)
    assert all(isinstance(r.get("value"), float) for r in recs)
    assert all("sentinel" in r for r in recs)
    # Cohort isolation: bench-kind queries never see quality rows.
    assert ledger.records(kind="bench_leg") == []
    ck.close()


def test_online_requires_provenance_fields(tmp_path):
    from fm_spark_tpu.obs.ledger import PerfLedger

    tr = _trainer()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=10**9,
                      async_save=False)
    with pytest.raises(ValueError, match="provenance"):
        online.run_online(tr, _days(n_days=4), ck,
                          ledger=PerfLedger(str(tmp_path / "l.jsonl")))
    ck.close()


def test_cli_online_end_to_end_with_serving_follower(tmp_path, capsys):
    """ISSUE 13 acceptance: ``cli train --online --optimizer ftrl`` on
    synthetic time-ordered days — per-day AUC in the ledger as
    ``quality_eval``, the injected label-flip fires the sentry, the
    bad generation is demoted — and a serving follower on the same
    chain is journal-asserted to SKIP the demoted generation and serve
    the (post-rollback) good tip."""
    from fm_spark_tpu import cli

    ck_dir = tmp_path / "ck"
    ledger_path = tmp_path / "ledger.jsonl"
    rc = cli.main([
        "train", "--config", "movielens_fm_r8", "--synthetic", "4096",
        "--online", "--online-days", "8", "--drift-inject", "5",
        "--optimizer", "ftrl", "--batch-size", "128", "--lr", "0.1",
        "--steps", "0", "--checkpoint-dir", str(ck_dir),
        "--quality-ledger", str(ledger_path), "--log-every", "10000",
        "--test-fraction", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    summary = json.loads(
        [ln for ln in out.splitlines() if '"online"' in ln][-1]
    )["online"]
    assert summary["rollbacks"] >= 1 and summary["demoted_steps"]
    recs = [json.loads(ln) for ln in open(ledger_path)]
    assert {r["kind"] for r in recs} == {"quality_eval"}
    assert all(r["leg"].startswith("quality/") for r in recs)

    # A serving follower over the SAME chain: restores the published
    # tip, skips every tombstoned generation (journal-asserted), and
    # the artifact auditor agrees nothing demoted was ever installed.
    import jax

    from fm_spark_tpu import configs as configs_lib
    from fm_spark_tpu.resilience.chaos import audit_serve_events
    from fm_spark_tpu.serve import PredictEngine, ReloadFollower
    from fm_spark_tpu.train import make_optimizer

    cfg = configs_lib.get_config("movielens_fm_r8", optimizer="ftrl",
                                 batch_size=128, learning_rate=0.1)
    spec = models.FMSpec(num_features=4096, rank=8, init_std=0.01)
    init = spec.init(jax.random.key(cfg.seed))
    opt_ex = make_optimizer(cfg.train_config()).init(init)
    journal = EventLog(str(tmp_path / "serve_health.jsonl"))
    eng = PredictEngine(spec, init, nnz=2, buckets=(8,),
                        latency_budget_ms=0.0, journal=journal)
    eng.warmup()
    fol = ReloadFollower(eng, str(ck_dir), poll_s=0.05,
                         journal=journal, params_example=init,
                         opt_state_example=opt_ex)
    try:
        assert fol.poll_once() == "swapped"
        ck = Checkpointer(str(ck_dir), save_every=10**9,
                          async_save=False)
        stones = ck.tombstoned_steps()
        ck.close()
        assert stones, "drift run left no tombstones"
        assert eng.generation().step == summary["last_good"]
        assert eng.generation().step not in stones
        events = read_events(str(tmp_path / "serve_health.jsonl"))
        assert audit_serve_events(events,
                                  tombstoned_steps=stones) == []
    finally:
        fol.stop()
        eng.close()
        journal.close()
