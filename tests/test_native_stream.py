"""Native-rate streaming ingest (ISSUE 6): the C++ chunk parse must be
indistinguishable from the per-line Python path — record stream, cursor,
quarantine accounting, error text, and kill-and-resume behavior all
bit-identical — while parsing orders of magnitude faster.

Three layers of assurance:

1. **Differential fuzz** — ~5k synthetic lines per dataset mixing clean
   rows with every RecordGuard corruption class (plus the nasty middle
   ground: rows Python's ``int()``/``float()`` accept but the strict
   native grammar routes back through the oracle), streamed through
   both paths batch-by-batch with full array/cursor/dead-letter
   comparison at every step.
2. **Protocol drills** — cross-path checkpoint restore (a cursor written
   by one parser resumes on the other), Prefetcher producer-thread
   error surfacing, the ingest fault points on the chunk path.
3. **The SIGKILL drill with native ingest** — kill a native-ingest
   training run mid-epoch, resume natively, and the concatenated record
   stream and loss curve equal a pure-Python golden run's.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time
from unittest import mock

import numpy as np
import pytest

from fm_spark_tpu import native
from fm_spark_tpu.data.native_stream import (
    NativeStreamBatches,
    make_stream_batches,
    native_stream_supported,
)
from fm_spark_tpu.data.stream import (
    BadRecord,
    IngestAborted,
    RecordGuard,
    ShardReader,
    StreamBatches,
    line_parser,
)
from fm_spark_tpu.resilience import faults
from fm_spark_tpu.utils.logging import read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    not (native.stream_parse_available("criteo")
         and native.stream_parse_available("avazu")
         and native.stream_parse_available("libsvm")),
    reason=f"native chunk parsers unavailable: {native.build_error()}",
)


# ------------------------------------------------------- line generators


def _criteo_lines(rng, n):
    """Clean Criteo TSV rows + every corruption class, ~10:1."""
    from fm_spark_tpu.data.criteo import NUM_CAT, NUM_INT

    dirty = [
        b"\x00garbage \xff\xfe",                      # binary noise
        b"1\tonly\tthree\tcols",                      # wrong column count
        b"",                                          # blank (skip)
        b"   \t  ",                                   # whitespace-only
        b"x" + b"\t1" * (NUM_INT + NUM_CAT),          # non-integer label
        b"1\tfoo" + b"\t1" * (NUM_INT + NUM_CAT - 1),  # bad count token
        b"1" + b"\t2" * (NUM_INT + NUM_CAT) + b"\t",  # trailing extra col
        # Python-parseable, outside the strict native grammar — must
        # come back bit-identical through the oracle fallback:
        b"+1" + b"\t3" * (NUM_INT + NUM_CAT),          # '+' label
        b"1\t+7" + b"\t4" * (NUM_INT + NUM_CAT - 1),   # '+' count token
        b"1\t" + b"1" * 21 + b"\t5" * (NUM_INT + NUM_CAT - 1),  # 21-digit
        b"1\t-abc" + b"\t6" * (NUM_INT + NUM_CAT - 1),  # '-junk' = NEG_KEY
    ]
    out = []
    for i in range(n):
        if i % 10 == 3 and i // 10 < len(dirty) * 40:
            out.append(dirty[(i // 10) % len(dirty)])
            continue
        cols = [b"1" if rng.random() < 0.3 else b"0"]
        for _ in range(NUM_INT):
            cols.append(b"" if rng.random() < 0.1
                        else str(int(rng.integers(0, 5000))).encode())
        for _ in range(NUM_CAT):
            cols.append(b"" if rng.random() < 0.1
                        else b"%06x" % int(rng.integers(0, 4000)))
        out.append(b"\t".join(cols))
    return out


def _avazu_lines(rng, n):
    dirty = [
        b"\x00garbage",
        b"1,2,3",                                     # wrong column count
        b"",
        b"id,click,hour" + b",h" * 21,                # header-shaped mid-file
        b"1,1,14bad103" + b",t" * 21,                 # non-digit hour
        b"1,0,14134108" + b",t" * 21,                 # month 13
        b"1,0,14103208" + b",t" * 21,                 # day 32
        b"1,0,1410" + b",t" * 21,                     # hour too short
        b"1,0,+1102108" + b",t" * 21,                 # '+' date: Python-ok
    ]
    out = []
    for i in range(n):
        if i % 10 == 4 and i // 10 < len(dirty) * 40:
            out.append(dirty[(i // 10) % len(dirty)])
            continue
        day = int(rng.integers(21, 29))
        hh = int(rng.integers(0, 24))
        cols = [str(10_000_000 + i).encode(),
                b"1" if rng.random() < 0.2 else b"0",
                f"1410{day:02d}{hh:02d}".encode()]
        cols += [b"%05x" % int(rng.integers(0, 3000)) for _ in range(21)]
        out.append(b",".join(cols))
    return out


def _libsvm_lines(rng, n, num_features=512, max_nnz=6):
    dirty = [
        b"# a full-line comment",                     # skip, not a record
        b"",
        b"1:2.5 3:1",                                  # missing label
        b"abc 1:2",                                    # unparseable label
        b"1 2:3:4",                                    # malformed pair
        b"1 :5",                                       # empty idx
        b"1 5:",                                       # empty val
        b"1 -3:1",                                     # negative idx
        b"0 0:1",                                      # one-based 0 -> -1
        b"1 9999:1",                                   # id out of bucket
        b"1 " + b" ".join(b"%d:1" % (i + 1) for i in range(9)),  # nnz > S
        b"1 2:inf",                                    # non-finite value
        b"inf 2:1",                                    # non-finite label
        b"1e999 2:1",                                  # overflow label
        # Python-parseable, native-REPARSE — oracle fallback must agree:
        b"+1.5 2:1.25",
        b"1 1_0:2.5",                                  # int('1_0') == 10
        b"1 3:1_0.5",                                  # float('1_0.5')
        b"1",                                          # zero-nnz row: valid
        b"1 4:1e2  # trailing comment",
    ]
    out = []
    for i in range(n):
        if i % 8 == 2 and i // 8 < len(dirty) * 40:
            out.append(dirty[(i // 8) % len(dirty)])
            continue
        nnz = int(rng.integers(1, max_nnz + 1))
        idx = rng.choice(num_features, size=nnz, replace=False) + 1
        pairs = b" ".join(b"%d:%s" % (int(ix), f"{v:.6g}".encode())
                          for ix, v in zip(idx, rng.normal(size=nnz)))
        out.append(b"%d %s" % (i % 2, pairs))
    return out


def _write_shards(tmp_path, lines, n_shards=3, name="shard{}.txt",
                  header=None, crlf_every=0, unterminated=False):
    paths = []
    per = (len(lines) + n_shards - 1) // n_shards
    for s in range(n_shards):
        part = lines[s * per: (s + 1) * per]
        p = str(tmp_path / name.format(s))
        with open(p, "wb") as f:
            if header is not None and s == 0:
                f.write(header + b"\n")
            for j, line in enumerate(part):
                term = b"\r\n" if crlf_every and j % crlf_every == 1 \
                    else b"\n"
                f.write(line + term)
            if unterminated and s == n_shards - 1:
                f.write(b"0 1:1" if name.endswith(".svm")
                        else part[0] if part else b"")
        paths.append(p)
    return paths


# ------------------------------------------------- differential equivalence


def _pair(paths, dataset, tmp_path, tag, batch_size, max_nnz,
          num_features, bucket=0, chunk_py=97, chunk_nat=311,
          max_bad_frac=1.0, header_prefix=None):
    """(python_batches, native_batches) over the same shards with
    separate quarantine dirs, deliberately different chunk sizes (the
    cursor must not care where chunk boundaries fall)."""
    gp = RecordGuard("quarantine", quarantine_dir=str(tmp_path / f"qp{tag}"),
                     max_bad_frac=max_bad_frac)
    gn = RecordGuard("quarantine", quarantine_dir=str(tmp_path / f"qn{tag}"),
                     max_bad_frac=max_bad_frac)
    py = StreamBatches(
        ShardReader(paths, chunk_bytes=chunk_py,
                    header_prefix=header_prefix),
        line_parser(dataset, bucket), batch_size, max_nnz, guard=gp,
        num_features=num_features)
    nat = NativeStreamBatches(
        ShardReader(paths, chunk_bytes=chunk_nat,
                    header_prefix=header_prefix),
        dataset, batch_size, max_nnz, guard=gn,
        num_features=num_features, bucket=bucket)
    return py, nat


def _assert_equivalent(py, nat, n_batches):
    for i in range(n_batches):
        a, b = py.next_batch(), nat.next_batch()
        for name, x, y in zip(("ids", "vals", "labels", "weights"), a, b):
            np.testing.assert_array_equal(
                x, y, err_msg=f"batch {i} {name} diverged")
        assert py.state() == nat.state(), f"cursor diverged at batch {i}"
    assert py.guard.counters() == nat.guard.counters()
    kp = [(e["path"], e["lineno"], e["reason"], e["line"])
          for e in read_events(py.guard.dead_letter_path)
          if e["event"] == "bad_record"]
    kn = [(e["path"], e["lineno"], e["reason"], e["line"])
          for e in read_events(nat.guard.dead_letter_path)
          if e["event"] == "bad_record"]
    assert kp == kn, "dead-letter journals diverged"


@needs_native
def test_differential_fuzz_criteo(tmp_path, rng):
    from fm_spark_tpu.data.criteo import NUM_FIELDS

    bucket = 1 << 14
    lines = _criteo_lines(rng, 5000)
    paths = _write_shards(tmp_path, lines, name="s{}.tsv", crlf_every=7)
    py, nat = _pair(paths, "criteo", tmp_path, "c", 256, NUM_FIELDS,
                    NUM_FIELDS * bucket, bucket=bucket)
    # ~1.3 epochs: crosses every shard seam and the epoch rewind.
    _assert_equivalent(py, nat, 24)
    assert py.guard.n_bad > 100  # the corruption classes actually fired
    assert nat.state()["epoch"] >= 1


@needs_native
def test_differential_fuzz_avazu(tmp_path, rng):
    from fm_spark_tpu.data.avazu import NUM_FIELDS

    bucket = 1 << 13
    lines = _avazu_lines(rng, 5000)
    paths = _write_shards(tmp_path, lines, name="s{}.csv",
                          header=b"id,click,hour" + b",h" * 21)
    py, nat = _pair(paths, "avazu", tmp_path, "a", 256, NUM_FIELDS,
                    NUM_FIELDS * bucket, bucket=bucket,
                    header_prefix=b"id,")
    _assert_equivalent(py, nat, 24)
    assert py.guard.n_bad > 100
    assert nat.state()["epoch"] >= 1


@needs_native
def test_differential_fuzz_libsvm(tmp_path, rng):
    lines = _libsvm_lines(rng, 5000)
    paths = _write_shards(tmp_path, lines, name="s{}.svm", crlf_every=5)
    py, nat = _pair(paths, "libsvm", tmp_path, "l", 256, 6, 512)
    _assert_equivalent(py, nat, 30)
    assert py.guard.n_bad > 100
    assert nat.state()["epoch"] >= 1


@needs_native
def test_unterminated_final_line_and_tiny_chunks(tmp_path):
    """A shard whose last line has no newline, read through chunk sizes
    down to 1 byte — offsets must stay byte-exact."""
    p = str(tmp_path / "u.svm")
    with open(p, "wb") as f:
        f.write(b"1 1:1.0\n0 2:1.0\r\n1 3:2.5")  # unterminated final line
    for chunk in (1, 3, 64, 1 << 16):
        nat = NativeStreamBatches(ShardReader([p], chunk_bytes=chunk),
                                  "libsvm", 2, 2, num_features=16)
        py = StreamBatches(ShardReader([p], chunk_bytes=5),
                           line_parser("libsvm"), 2, 2, num_features=16)
        for _ in range(3):
            a, b = py.next_batch(), nat.next_batch()
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, y)
            assert py.state() == nat.state()


@needs_native
def test_strict_policy_raises_identical_badrecord(tmp_path):
    lines = [b"1 1:1.0", b"garbage line", b"0 2:1.0"]
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    msgs = []
    for cls, kwargs in ((StreamBatches,
                         dict(parse=line_parser("libsvm"))),
                        (NativeStreamBatches, dict(dataset="libsvm"))):
        src = (cls(ShardReader(paths), kwargs.get("parse"), 4, 2,
                   num_features=16) if cls is StreamBatches else
               cls(ShardReader(paths), "libsvm", 4, 2, num_features=16))
        with pytest.raises(BadRecord) as ei:
            src.next_batch()
        msgs.append(str(ei.value))
    assert msgs[0] == msgs[1]
    assert "s0.svm:2" in msgs[0]


@needs_native
def test_breaker_aborts_on_native_path(tmp_path):
    lines = [b"1 1:1.0"] * 20 + [b"garbage"] * 40 + [b"0 2:1.0"] * 20
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"),
                        max_bad_frac=0.2, window=32, min_records=16)
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 8, 2,
                              guard=guard, num_features=16)
    with pytest.raises(IngestAborted, match="max_bad_frac"):
        for _ in range(12):
            nat.next_batch()
    aborted = [e for e in read_events(guard.dead_letter_path)
               if e["event"] == "ingest_aborted"]
    assert len(aborted) == 1


# --------------------------------------------------- cross-path checkpoints


@needs_native
def test_cursor_cross_restores_between_python_and_native(tmp_path, rng):
    """A checkpoint cursor written by either ingest path resumes on the
    other with a bit-identical continuation — the operational guarantee
    behind flipping --native-ingest on an existing run."""
    lines = _libsvm_lines(rng, 600)
    paths = _write_shards(tmp_path, lines, name="s{}.svm")

    def fresh(kind, tag):
        guard = RecordGuard("quarantine",
                            quarantine_dir=str(tmp_path / f"q{tag}"))
        if kind == "py":
            return StreamBatches(ShardReader(paths, chunk_bytes=53),
                                 line_parser("libsvm"), 32, 6, guard=guard,
                                 num_features=512)
        return NativeStreamBatches(ShardReader(paths, chunk_bytes=201),
                                   "libsvm", 32, 6, guard=guard,
                                   num_features=512)

    for src_kind, dst_kind in (("py", "native"), ("native", "py")):
        src = fresh(src_kind, f"s_{src_kind}")
        for _ in range(5):
            src.next_batch()
        state = src.state()
        want = [src.next_batch() for _ in range(8)]
        dst = fresh(dst_kind, f"d_{dst_kind}")
        dst.restore(state)
        got = [dst.next_batch() for _ in range(8)]
        for a, b in zip(want, got):
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, y)
        assert src.state() == dst.state()


# ------------------------------------------------------- prefetcher drills


@needs_native
def test_prefetcher_surfaces_producer_exceptions_not_hang(tmp_path):
    """Producer-thread failures mid-chunk must surface on the consumer
    as the original BadRecord / IngestAborted, promptly."""
    from fm_spark_tpu.data import Prefetcher

    lines = [b"1 1:1.0"] * 40 + [b"garbage"] * 60
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    # strict: BadRecord out of the producer thread.
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 8, 2,
                              num_features=16)
    with Prefetcher(nat, depth=2) as pf:
        t0 = time.time()
        with pytest.raises(BadRecord, match=r"s0\.svm:41"):
            for _ in range(12):
                pf.next_batch()
        assert time.time() - t0 < 30
    # breaker: IngestAborted out of the producer thread.
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"),
                        max_bad_frac=0.2, window=32, min_records=16)
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 8, 2,
                              guard=guard, num_features=16)
    with Prefetcher(nat, depth=2) as pf:
        with pytest.raises(IngestAborted):
            for _ in range(12):
                pf.next_batch()


@needs_native
def test_prefetcher_state_restore_through_native_batch_boundary(tmp_path,
                                                                rng):
    """Prefetcher.state() is the cursor of the last CONSUMED batch; a
    restore from it onto a fresh native source (restore-then-wrap, per
    the Prefetcher contract) replays exactly the unseen batches."""
    from fm_spark_tpu.data import Prefetcher

    lines = [b"%d %d:1.5 %d:0.5" % (j % 2, j + 1, j + 2)
             for j in range(400)]
    paths = _write_shards(tmp_path, lines, name="s{}.svm")

    def fresh():
        return NativeStreamBatches(ShardReader(paths, chunk_bytes=173),
                                   "libsvm", 32, 6, num_features=512)

    golden_src = fresh()
    golden = [golden_src.next_batch() for _ in range(10)]

    src = fresh()
    pf = Prefetcher(src, depth=3)
    for i in range(4):
        batch = pf.next_batch()
        for x, y in zip(golden[i], batch):
            np.testing.assert_array_equal(x, y)
    state = pf.state()
    pf.close()

    resumed = fresh()
    resumed.restore(state)
    with Prefetcher(resumed, depth=3) as pf2:
        for i in range(4, 10):
            batch = pf2.next_batch()
            for x, y in zip(golden[i], batch):
                np.testing.assert_array_equal(x, y)


# ------------------------------------------------------------ fault points


@needs_native
def test_ingest_corrupt_fault_takes_policy_path_on_native_chunk(tmp_path):
    lines = [b"1 1:1.0"] * 10
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "q"))
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 4, 2,
                              guard=guard, num_features=16)
    faults.activate("ingest_corrupt@1=error")
    try:
        nat.next_batch()
    finally:
        faults.clear()
    # The chunk's first record went through quarantine with the injected
    # reason; everything else parsed.
    assert guard.n_bad == 1
    events = read_events(guard.dead_letter_path)
    assert any("ingest_corrupt" in e["reason"] for e in events)

    # strict: the same injection raises BadRecord.
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 4, 2,
                              num_features=16)
    faults.activate("ingest_corrupt@1=error")
    try:
        with pytest.raises(BadRecord):
            nat.next_batch()
    finally:
        faults.clear()
    # A leading blank line is never the fault's victim (the per-record
    # path skips blanks BEFORE its inject point): the first REAL record
    # takes the hit.
    paths2 = _write_shards(tmp_path, [b"", b"   ", b"1 1:1.0", b"0 2:1.0"],
                           n_shards=1, name="b{}.svm")
    guard = RecordGuard("quarantine", quarantine_dir=str(tmp_path / "qb"))
    nat = NativeStreamBatches(ShardReader(paths2), "libsvm", 2, 2,
                              guard=guard, num_features=16)
    faults.activate("ingest_corrupt@1=error")
    try:
        nat.next_batch()
    finally:
        faults.clear()
    events = read_events(guard.dead_letter_path)
    assert len(events) == 1 and events[0]["lineno"] == 3


@needs_native
def test_ingest_fault_device_loss_and_truncate_propagate(tmp_path):
    lines = [b"1 1:1.0"] * 10
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 4, 2,
                              num_features=16)
    faults.activate("ingest_corrupt@1=device_loss")
    try:
        with pytest.raises(faults.InjectedDeviceLoss):
            nat.next_batch()
    finally:
        faults.clear()
    nat = NativeStreamBatches(ShardReader(paths), "libsvm", 4, 2,
                              num_features=16)
    faults.activate("ingest_truncate@1=error")
    try:
        with pytest.raises(faults.FaultInjected):
            nat.next_batch()
    finally:
        faults.clear()


# ------------------------------------------------------- factory / fallback


@needs_native
def test_factory_picks_native_and_falls_back(tmp_path):
    lines = [b"1 1:1.0", b"0 2:1.0"]
    paths = _write_shards(tmp_path, lines, n_shards=1, name="s{}.svm")
    got = make_stream_batches(ShardReader(paths), "libsvm", 2, 2,
                              num_features=16)
    assert isinstance(got, NativeStreamBatches)
    # .so absent -> silent fallback under "auto", hard error under True.
    with mock.patch.object(native, "stream_parse_available",
                           lambda dataset: False):
        got = make_stream_batches(ShardReader(paths), "libsvm", 2, 2,
                                  num_features=16)
        assert isinstance(got, StreamBatches)
        assert not isinstance(got, NativeStreamBatches)
        with pytest.raises(RuntimeError, match="native ingest requested"):
            make_stream_batches(ShardReader(paths), "libsvm", 2, 2,
                                num_features=16, native_ingest=True)
    # Fixed-field formats need max_nnz >= field count to be expressible.
    assert not native_stream_supported("criteo", max_nnz=10, bucket=1 << 10)
    assert native_stream_supported("criteo", max_nnz=39, bucket=1 << 10)


# ------------------------------------------- acceptance: SIGKILL drill


_KILL_CHILD = """
import json, os, sys

sys.path.insert(0, {repo!r})
from fm_spark_tpu import models
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.data.stream import ShardReader
from fm_spark_tpu.data.native_stream import NativeStreamBatches
from fm_spark_tpu.train import FMTrainer, TrainConfig

shard_dir, ck_dir, tap_path, steps = sys.argv[1:5]
paths = sorted(os.path.join(shard_dir, f) for f in os.listdir(shard_dir))


class Tap:
    def __init__(self, source, path):
        self._source = source
        self._f = open(path, "a")

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        self._f.write(",".join(str(int(x)) for x in ids[w > 0][:, 0]))
        self._f.write("\\n")
        self._f.flush()
        return ids, vals, labels, w

    def state(self):
        return self._source.state()

    def restore(self, s):
        self._source.restore(s)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
config = TrainConfig(num_steps=int(steps), batch_size=16,
                     learning_rate=0.1, lr_schedule="constant",
                     log_every=1)
ck = Checkpointer(ck_dir, save_every=4, async_save=False)
batches = Tap(NativeStreamBatches(ShardReader(paths, chunk_bytes=64),
                                  "libsvm", 16, 3, num_features=128),
              tap_path)
trainer = FMTrainer(spec, config)
trainer.fit(batches, checkpointer=ck)
ck.close()
print(json.dumps({{"done": trainer.step_count}}), flush=True)
"""


class _Tap:
    def __init__(self, source, path):
        self._source = source
        self._path = path

    def next_batch(self):
        ids, vals, labels, w = self._source.next_batch()
        with open(self._path, "a") as f:
            f.write(",".join(str(int(x)) for x in ids[w > 0][:, 0]))
            f.write("\n")
        return ids, vals, labels, w

    def state(self):
        return self._source.state()

    def restore(self, s):
        self._source.restore(s)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next_batch()


@needs_native
def test_sigkill_native_ingest_resume_matches_python_golden(tmp_path):
    """ISSUE 6 acceptance: SIGKILL a NATIVE-ingest run mid-epoch, resume
    natively from the checkpoint, and the record stream, cursor, and
    loss curve are bit-identical to an uninterrupted PURE-PYTHON run —
    exactly-once, across parsers."""
    from fm_spark_tpu import models
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    shard_dir = tmp_path / "shards"
    shard_dir.mkdir()
    paths = []
    j = 0
    for s in range(3):
        p = str(shard_dir / f"shard{s}.svm")
        with open(p, "w") as f:
            for _ in range(32):
                f.write(f"{j % 2} {j + 1}:1.5 {j + 2}:0.5\n")
                j += 1
        paths.append(p)
    steps = 24

    spec = models.FMSpec(num_features=128, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=steps, batch_size=16,
                         learning_rate=0.1, lr_schedule="constant",
                         log_every=1)

    # Golden: uninterrupted PYTHON-path run over the same stream.
    golden_tap = str(tmp_path / "tap_golden.txt")
    golden_src = StreamBatches(ShardReader(paths, chunk_bytes=64),
                               line_parser("libsvm"), 16, 3,
                               num_features=128)
    golden = FMTrainer(spec, config)
    golden.fit(_Tap(golden_src, golden_tap))

    # Native child SIGKILLed mid-epoch 3 (checkpoints every 4 steps).
    script = tmp_path / "child.py"
    script.write_text(_KILL_CHILD.format(repo=REPO))
    ck_dir = str(tmp_path / "ck")
    kill_tap = str(tmp_path / "tap_kill.txt")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(script), str(shard_dir), ck_dir, kill_tap,
         str(steps)],
        stdout=subprocess.PIPE, text=True, cwd=REPO, env=env,
    )
    try:
        deadline = time.time() + 240
        for line in proc.stdout:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("step", 0) >= 13 or "done" in rec:
                break
            assert time.time() < deadline, "child never reached step 13"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        proc.stdout.close()
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL

    # Resume NATIVELY in-process from the killed run's checkpoint chain.
    resume_tap = str(tmp_path / "tap_resume.txt")
    ck = Checkpointer(ck_dir, save_every=4, async_save=False)
    resume_src = NativeStreamBatches(ShardReader(paths, chunk_bytes=1 << 16),
                                     "libsvm", 16, 3, num_features=128)
    resumed = FMTrainer(spec, config)
    resumed.fit(_Tap(resume_src, resume_tap), checkpointer=ck)
    ck.close()

    assert resumed.step_count == golden.step_count == steps
    assert resumed.loss_history == golden.loss_history
    np.testing.assert_array_equal(np.asarray(golden.params["v"]),
                                  np.asarray(resumed.params["v"]))
    assert resume_src.state() == golden_src.state()

    golden_lines = open(golden_tap).read().splitlines()
    kill_lines = open(kill_tap).read().splitlines()
    resume_lines = open(resume_tap).read().splitlines()
    restored_step = steps - len(resume_lines)
    assert 0 < restored_step < steps
    assert restored_step % 4 == 0
    assert kill_lines[:restored_step] == golden_lines[:restored_step]
    assert resume_lines == golden_lines[restored_step:]


# ------------------------------------------------------- build script


def test_build_native_check_mode(tmp_path):
    """tools/build_native.py --check rebuilds with the pinned flags and
    diffs exported symbols; skips cleanly when no compiler exists."""
    if shutil.which("g++") is None:
        pytest.skip("no g++ on PATH")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "build_native.py"),
         "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "symbol check OK" in proc.stdout


def test_build_native_expected_symbols_cover_bindings():
    """Every symbol the ctypes layer binds is registered in the build
    script's expected-symbol list (the --check contract)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "build_native_tool", os.path.join(REPO, "tools", "build_native.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for sym in ("fm_parse_criteo_rows", "fm_parse_avazu_rows",
                "fm_parse_libsvm_rows", "fm_gather_rows", "fm_compact_aux"):
        assert sym in mod.EXPECTED_SYMBOLS
