"""Differential tests for the fused Pallas embedding path (ISSUE 8).

Contract under test (ops/pallas_fused.py + the ``fused_embed`` lever in
sparse.py): the fused kernels are the REFERENCE's numerics, not merely
close — fp32 step outputs are BIT-EXACT against the XLA path they
subsume (the gfull_fused + segtotal_pallas composition for the FM
compact backward; the sel_blocked body for the FFM kernels), bf16 is
tolerance-bounded, 'auto' falls back to XLA with a queryable reason,
and 'require' raises the structured ops.PallasUnavailable everywhere a
kernel cannot serve. Interpret mode on CPU; the on-chip A/B is
bench.py's job.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import sparse
from fm_spark_tpu.models.field_ffm import FieldFFMSpec
from fm_spark_tpu.models.field_fm import FieldFMSpec
from fm_spark_tpu.ops import PallasUnavailable, pallas_fused, pallas_segsum
from fm_spark_tpu.ops.scatter import compact_aux
from fm_spark_tpu.train import TrainConfig

B, F, K, BUCKET, CAP = 256, 5, 8, 96, 96


def _fm_spec(**kw):
    kw.setdefault("num_features", F * BUCKET)
    return FieldFMSpec(num_fields=F, bucket=BUCKET, rank=K,
                       fused_linear=True, **kw)


def _batch(seed=1, bucket=BUCKET):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, bucket, (B, F)), jnp.int32)
    vals = jnp.asarray(rng.uniform(0.5, 1.5, (B, F)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, B), jnp.float32)
    weights = jnp.ones((B,), jnp.float32)
    return ids, vals, labels, weights


def _base_cfg(**kw):
    kw.setdefault("sparse_update", "dedup")
    kw.setdefault("host_dedup", True)
    kw.setdefault("compact_cap", CAP)
    return dict(learning_rate=0.05, lr_schedule="constant",
                optimizer="sgd", reg_factors=1e-4, reg_linear=1e-5,
                reg_bias=1e-6, **kw)


def _run(spec, cfg, body_fn, aux, batch, step_idx=3):
    params = spec.init(jax.random.key(0))
    step = body_fn(spec, cfg)
    return step(jax.tree_util.tree_map(jnp.copy, params), step_idx,
                *batch, aux)


def _assert_trees(p1, p2, exact=True, atol=0.0):
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        a, b = np.asarray(a), np.asarray(b)
        if exact:
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(
                a.astype(np.float64), b.astype(np.float64), atol=atol)


# --------------------------------------------------------------------------
# The fused FM compact backward: bit-exact vs the subsumed composition.
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_fm_step_fused_bwd_bit_exact_fp32(mode):
    spec = _fm_spec()
    batch = _batch()
    aux = jax.device_put(compact_aux(np.asarray(batch[0]), CAP))
    ref = TrainConfig(**_base_cfg(sparse_update=mode), fused_embed="off",
                      gfull_fused=True, segtotal_pallas=True)
    fused = TrainConfig(**_base_cfg(sparse_update=mode),
                        fused_embed="require")
    p1, l1 = _run(spec, ref, sparse.make_field_sparse_sgd_body, aux, batch)
    p2, l2 = _run(spec, fused, sparse.make_field_sparse_sgd_body, aux,
                  batch)
    assert float(l1) == float(l2)
    _assert_trees(p1, p2, exact=True)


def test_fm_step_fused_bwd_matches_plain_reference_tolerance():
    # Against the DEFAULT (blocked-prefix, concat-g_full) reference the
    # kernel is reassociation-equal, not bitwise: pin a tight bound.
    spec = _fm_spec()
    batch = _batch(seed=7)
    aux = jax.device_put(compact_aux(np.asarray(batch[0]), CAP))
    ref = TrainConfig(**_base_cfg(), fused_embed="off")
    fused = TrainConfig(**_base_cfg(), fused_embed="require")
    p1, l1 = _run(spec, ref, sparse.make_field_sparse_sgd_body, aux, batch)
    p2, l2 = _run(spec, fused, sparse.make_field_sparse_sgd_body, aux,
                  batch)
    assert abs(float(l1) - float(l2)) < 1e-6
    _assert_trees(p1, p2, exact=False, atol=1e-5)


def test_fm_step_fused_bwd_device_aux_overflow_drop_bit_exact():
    # compact_device with cap below the unique count: the kernel's
    # trash-row clamp must reproduce the masked-drop overflow semantics
    # exactly (overflow lanes expand to zero rows, updates dropped).
    spec = _fm_spec()
    rng = np.random.default_rng(11)
    ids = jnp.asarray(rng.integers(0, 2000, (B, F)), jnp.int32)
    batch = (ids, *_batch()[1:])
    kw = dict(host_dedup=False, compact_device=True,
              compact_overflow="drop", sparse_update="dedup_sr")
    spec2 = FieldFMSpec(num_features=F * 2000, num_fields=F, bucket=2000,
                        rank=K, fused_linear=True)
    ref = TrainConfig(**_base_cfg(**kw), fused_embed="off",
                      gfull_fused=True, segtotal_pallas=True)
    fused = TrainConfig(**_base_cfg(**kw), fused_embed="require")
    p1, l1 = _run(spec2, ref, sparse.make_field_sparse_sgd_body, None,
                  batch)
    p2, l2 = _run(spec2, fused, sparse.make_field_sparse_sgd_body, None,
                  batch)
    assert float(l1) == float(l2)
    _assert_trees(p1, p2, exact=True)


def test_fm_step_fused_bwd_bf16_tolerance_bounded():
    spec = _fm_spec(param_dtype="bfloat16", compute_dtype="bfloat16")
    batch = _batch(seed=3)
    aux = jax.device_put(compact_aux(np.asarray(batch[0]), CAP))
    ref = TrainConfig(**_base_cfg(sparse_update="dedup_sr"),
                      fused_embed="off", gfull_fused=True,
                      segtotal_pallas=True)
    fused = TrainConfig(**_base_cfg(sparse_update="dedup_sr"),
                        fused_embed="require")
    p1, l1 = _run(spec, ref, sparse.make_field_sparse_sgd_body, aux, batch)
    p2, l2 = _run(spec, fused, sparse.make_field_sparse_sgd_body, aux,
                  batch)
    # bf16 has ~3 decimal digits; one step's updates are O(lr·g) small.
    assert abs(float(l1) - float(l2)) < 1e-3
    _assert_trees(p1, p2, exact=False, atol=1e-2)


def test_fm_bwd_kernel_bit_exact_vs_gfull_plus_segtotal():
    # The kernel alone vs the two-stage reference it fuses, composed
    # exactly as the step composes them (sorted streams in, totals out).
    rng = np.random.default_rng(5)
    b, w, cap = 1024, K + 1, 64
    urows = jnp.asarray(rng.normal(size=(cap, w)), jnp.float32)
    seg = jnp.asarray(np.sort(rng.integers(0, cap, b)), jnp.int32)
    s1 = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)
    ds = jnp.asarray(rng.normal(size=b), jnp.float32)
    x = jnp.asarray(rng.uniform(0.5, 1.5, b), jnp.float32)
    tch = jnp.asarray(rng.integers(0, 2, b), jnp.float32)
    rv = jnp.asarray([1e-4] * K + [1e-5], jnp.float32)
    lr = jnp.float32(0.05)

    got = pallas_fused.fm_bwd_segment_totals(
        urows, s1, ds, x, tch, seg, -lr, rv, k=K, cap=cap,
        interpret=True)

    # Reference: the gfull_fused expression on expanded rows, then the
    # Pallas segment totals (same tile/window math).
    rows = urows[seg]
    colmask = jnp.arange(w) < K
    xv = rows * x[:, None]
    base = ds[:, None] * (s1 - jnp.where(colmask, xv, 0.0))
    g = base * x[:, None] + rv * rows * tch[:, None]
    want = pallas_segsum.segment_totals(
        (-lr * g).astype(jnp.float32), seg, cap, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fm_bwd_kernel_no_reg_matches_reference():
    # rv=None skips the reg term entirely (the reference's conditional
    # add) — a zero rv vector would still change the HLO.
    rng = np.random.default_rng(6)
    b, w, cap = 512, K + 1, 32
    urows = jnp.asarray(rng.normal(size=(cap, w)), jnp.float32)
    seg = jnp.asarray(np.sort(rng.integers(0, cap, b)), jnp.int32)
    s1 = jnp.asarray(rng.normal(size=(b, w)), jnp.float32)
    ds = jnp.asarray(rng.normal(size=b), jnp.float32)
    x = jnp.asarray(rng.uniform(0.5, 1.5, b), jnp.float32)
    got = pallas_fused.fm_bwd_segment_totals(
        urows, s1, ds, x, jnp.ones_like(x), seg, jnp.float32(-0.1),
        None, k=K, cap=cap, interpret=True)
    rows = urows[seg]
    colmask = jnp.arange(w) < K
    g = ds[:, None] * (s1 - jnp.where(colmask, rows * x[:, None], 0.0)
                       ) * x[:, None]
    want = pallas_segsum.segment_totals(
        (-0.1 * g).astype(jnp.float32), seg, cap, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --------------------------------------------------------------------------
# The fused gather→interaction forward.
# --------------------------------------------------------------------------


def test_fm_fused_forward_matches_xla_reference():
    rng = np.random.default_rng(8)
    tables = [jnp.asarray(rng.normal(size=(60, K + 1)), jnp.float32)
              for _ in range(F)]
    ids = jnp.asarray(rng.integers(0, 60, (B, F)), jnp.int32)
    vals = jnp.asarray(rng.uniform(0.5, 1.5, (B, F)), jnp.float32)
    scores, acc = pallas_fused.fm_fused_scores(
        tables, ids, vals, w0=jnp.float32(0.3), interpret=True)
    rows = [tables[f][ids[:, f]] for f in range(F)]
    xvs = [r[:, :K] * vals[:, f:f + 1] for f, r in enumerate(rows)]
    s = sum(xvs)
    ssq = sum(jnp.sum(x * x, axis=1) for x in xvs)
    ref = (0.5 * (jnp.sum(s * s, axis=1) - ssq)
           + sum(r[:, K] * vals[:, f] for f, r in enumerate(rows)) + 0.3)
    np.testing.assert_allclose(np.asarray(scores), np.asarray(ref),
                               atol=1e-5)
    # acc carries the forward residuals: cols [:k] = s, col k = linear.
    np.testing.assert_allclose(np.asarray(acc[:, :K]), np.asarray(s),
                               atol=1e-6)


def test_fm_fused_forward_rejects_overwide_on_tpu_contract():
    # Off-TPU the support probe is unrestricted; the width rule is the
    # row-DMA constraint and must stay queryable without raising.
    assert pallas_fused.fm_fwd_supported(1024, 65) is None


# --------------------------------------------------------------------------
# The sel-blocked FFM kernels.
# --------------------------------------------------------------------------


def _ffm_spec(**kw):
    kw.setdefault("num_features", F * BUCKET)
    return FieldFFMSpec(num_fields=F, bucket=BUCKET, rank=6, **kw)


def _ffm_cfg(**kw):
    kw.setdefault("fused_embed", "off")
    return TrainConfig(learning_rate=0.05, lr_schedule="constant",
                       optimizer="sgd", sparse_update="scatter_add",
                       sel_blocked=True, reg_factors=1e-4,
                       reg_linear=1e-5, **kw)


def test_ffm_step_pallas_bit_exact_fp32():
    spec = _ffm_spec()
    batch = _batch(seed=9)
    p1, l1 = _run(spec, _ffm_cfg(),
                  sparse.make_field_ffm_sparse_sgd_body, None, batch)
    p2, l2 = _run(spec, _ffm_cfg(fused_embed="require"),
                  sparse.make_field_ffm_sparse_sgd_body, None, batch)
    assert float(l1) == float(l2)
    _assert_trees(p1, p2, exact=True)


def test_ffm_step_pallas_bf16_compute_tolerance():
    spec = _ffm_spec(compute_dtype="bfloat16")
    batch = _batch(seed=10)
    p1, l1 = _run(spec, _ffm_cfg(),
                  sparse.make_field_ffm_sparse_sgd_body, None, batch)
    p2, l2 = _run(spec, _ffm_cfg(fused_embed="require"),
                  sparse.make_field_ffm_sparse_sgd_body, None, batch)
    assert abs(float(l1) - float(l2)) < 1e-3
    _assert_trees(p1, p2, exact=False, atol=1e-2)


def test_ffm_kernels_match_blocked_loop_directly():
    rng = np.random.default_rng(12)
    b, f, kk = 192, 4, 6
    rstk = jnp.asarray(rng.normal(size=(b, f, f * kk)), jnp.float32)
    vals = jnp.asarray(rng.uniform(0.5, 1.5, (b, f)), jnp.float32)
    ds = jnp.asarray(rng.normal(size=b), jnp.float32)
    acc = pallas_fused.ffm_sel_scores(rstk, vals, interpret=True)
    dvs = pallas_fused.ffm_sel_bwd(rstk, vals, ds, interpret=True)
    Rv = np.asarray(rstk).reshape(b, f, f, kk)
    x = np.asarray(vals)
    want_acc = np.zeros(b, np.float32)
    for i in range(f):
        sel_i = Rv[:, i] * x[:, i, None, None]
        selT_i = Rv[:, :, i, :] * x[:, :, None]
        prod = np.sum(sel_i * selT_i, axis=-1)
        want_acc = want_acc + np.sum(prod, axis=1) - prod[:, i]
        dsel_i = np.asarray(ds)[:, None, None] * selT_i
        dsel_i[:, i, :] = 0
        want_dv = (dsel_i * x[:, i, None, None]).reshape(b, f * kk)
        np.testing.assert_allclose(np.asarray(dvs[:, i, :]), want_dv,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(acc), want_acc, atol=1e-5)


# --------------------------------------------------------------------------
# The lever: plan resolution, auto fallback, require escalation.
# --------------------------------------------------------------------------


def test_plan_resolves_families_and_reasons():
    fm, ffm = _fm_spec(), _ffm_spec()
    base = _base_cfg()
    assert sparse.fused_embed_plan(
        fm, TrainConfig(**base, fused_embed="auto")) == \
        ("fm_compact_bwd", None)
    assert sparse.fused_embed_plan(
        ffm, _ffm_cfg(fused_embed="auto")) == ("ffm_sel", None)
    fam, reason = sparse.fused_embed_plan(
        fm, TrainConfig(**base, fused_embed="off"))
    assert fam is None and "off" in reason
    fam, reason = sparse.fused_embed_plan(
        fm, TrainConfig(**{**base, "compact_cap": 0,
                           "host_dedup": False}, fused_embed="auto"))
    assert fam is None and "compact" in reason
    ffm_cfg = _ffm_cfg(fused_embed="auto")
    import dataclasses

    no_selblk = dataclasses.replace(ffm_cfg, sel_blocked=False)
    fam, reason = sparse.fused_embed_plan(ffm, no_selblk)
    assert fam is None and "sel_blocked" in reason


def test_auto_falls_back_to_xla_bit_identically():
    # 'auto' with no serving family must compile EXACTLY the XLA path.
    spec = _fm_spec()
    batch = _batch(seed=13)
    off = TrainConfig(**_base_cfg(compact_cap=0, host_dedup=False,
                                  sparse_update="scatter_add"),
                      fused_embed="off")
    auto = TrainConfig(**_base_cfg(compact_cap=0, host_dedup=False,
                                   sparse_update="scatter_add"),
                       fused_embed="auto")
    p1, l1 = _run(spec, off, sparse.make_field_sparse_sgd_body, None,
                  batch)
    p2, l2 = _run(spec, auto, sparse.make_field_sparse_sgd_body, None,
                  batch)
    assert float(l1) == float(l2)
    _assert_trees(p1, p2, exact=True)


def test_require_raises_structured_error_when_unserved():
    spec = _fm_spec()
    cfg = TrainConfig(**_base_cfg(compact_cap=0, host_dedup=False,
                                  sparse_update="scatter_add"),
                      fused_embed="require")
    with pytest.raises(PallasUnavailable, match="compact"):
        sparse.make_field_sparse_sgd_body(spec, cfg)


def test_require_rejected_by_non_served_factories():
    from fm_spark_tpu.train import make_train_step

    cfg = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                      optimizer="adam", fused_embed="require")
    spec = _fm_spec()
    with pytest.raises(ValueError, match="fused_embed"):
        make_train_step(spec, cfg)


def test_vmem_budget_is_a_fallback_reason_not_a_crash():
    # A cap far past the residency budget: 'auto' reports the reason,
    # 'require' escalates to the structured error.
    big = _base_cfg(compact_cap=1 << 20)
    spec = FieldFMSpec(num_features=F * (1 << 21), num_fields=F,
                       bucket=1 << 21, rank=K, fused_linear=True)
    fam, reason = sparse.fused_embed_plan(
        spec, TrainConfig(**big, fused_embed="auto"))
    assert fam is None and "VMEM" in reason
    with pytest.raises(PallasUnavailable, match="VMEM"):
        sparse.make_field_sparse_sgd_body(
            spec, TrainConfig(**big, fused_embed="require"))


def test_unknown_fused_embed_value_rejected():
    with pytest.raises(ValueError, match="unknown fused_embed"):
        sparse.fused_embed_plan(
            _fm_spec(), TrainConfig(**_base_cfg(), fused_embed="maybe"))


def test_kernel_errors_are_catchable_as_valueerror():
    # Pre-existing callers pin ValueError; the structured subclass must
    # stay catchable that way (the PallasUnavailable contract).
    assert issubclass(PallasUnavailable, ValueError)


# --------------------------------------------------------------------------
# AOT: the PR-1 lower()/compile() machinery serves the fused families.
# --------------------------------------------------------------------------


def test_aot_lower_compile_fused_fm_step():
    spec = _fm_spec()
    cfg = TrainConfig(**_base_cfg(sparse_update="dedup_sr"),
                      fused_embed="require")
    lowered = sparse.lower_field_sparse_step(spec, cfg, B)
    compiled = lowered.compile()
    assert compiled is not None


def test_aot_lower_compile_fused_ffm_step():
    spec = _ffm_spec()
    lowered = sparse.lower_field_sparse_step(
        spec, _ffm_cfg(fused_embed="require"), B)
    assert lowered.compile() is not None


def test_multistep_roll_carries_fused_step():
    # The fori multistep roll must compose with the fused body (the
    # production loop's dispatch-amortized form).
    spec = _fm_spec()
    cfg = TrainConfig(**_base_cfg(), fused_embed="require")
    ids, vals, labels, weights = _batch(seed=14)
    aux = jax.device_put(compact_aux(np.asarray(ids), CAP))
    n = 2
    stack = lambda a: jnp.stack([a] * n)  # noqa: E731
    mstep = sparse.make_field_sparse_multistep(spec, cfg, n)
    params = spec.init(jax.random.key(0))
    aux_s = jax.tree_util.tree_map(stack, aux)
    p, loss = mstep(params, jnp.int32(0), jnp.int32(n), stack(ids),
                    stack(vals), stack(labels), stack(weights), aux_s)
    assert np.isfinite(float(loss))
