"""Checkpoint/resume + preemption: the rebuild's fault-tolerance story.

The reference leans on Spark lineage recompute (SURVEY.md §3.5); the
TPU-native strategy is checkpoint-restart (SURVEY.md §5). The key property
tested here is the one SURVEY.md §5 names: a killed-and-resumed run is
indistinguishable from an uninterrupted one (loss-curve continuity), which
requires the data-pipeline cursor to round-trip with the arrays.
"""

import os
import signal

import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.checkpoint import Checkpointer, PreemptionGuard
from fm_spark_tpu.data.pipeline import Batches
from fm_spark_tpu.data.synthetic import synthetic_ctr
from fm_spark_tpu.train import FMTrainer, TrainConfig


N_FEATURES = 64
NNZ = 5


def make_problem():
    ids, vals, labels = synthetic_ctr(
        num_examples=512, num_features=N_FEATURES, nnz=NNZ, seed=3
    )
    spec = models.FMSpec(num_features=N_FEATURES, rank=4, init_std=0.05)
    config = TrainConfig(
        num_steps=40, batch_size=64, learning_rate=0.1, optimizer="adam",
        lr_schedule="constant", reg_factors=1e-4, log_every=5,
    )
    return spec, config, (ids, vals, labels)


def run_uninterrupted(tmp_path):
    spec, config, (ids, vals, labels) = make_problem()
    trainer = FMTrainer(spec, config)
    batches = Batches(ids, vals, labels, config.batch_size, seed=7)
    trainer.fit(batches)
    return trainer


def test_roundtrip_preserves_structures(tmp_path):
    spec, config, (ids, vals, labels) = make_problem()
    trainer = FMTrainer(spec, config)
    batches = Batches(ids, vals, labels, config.batch_size, seed=7)
    ckpt = Checkpointer(str(tmp_path / "ck"), save_every=10, async_save=False)
    ckpt.save(3, trainer.params, trainer.opt_state, batches.state(),
              {"loss_history": [1.0, 0.5]})
    ckpt.wait()

    trainer2 = FMTrainer(spec, config)
    restored = ckpt.restore(trainer2.params, trainer2.opt_state)
    assert restored["step"] == 3
    assert restored["pipeline"] == batches.state()
    assert restored["extra"]["loss_history"] == [1.0, 0.5]
    # optax state comes back with its NamedTuple structure, not dicts.
    import jax

    assert jax.tree_util.tree_structure(
        restored["opt_state"]
    ) == jax.tree_util.tree_structure(trainer.opt_state)
    ckpt.close()


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """Interrupted-at-step-20 + resumed == never interrupted, bitwise."""
    golden = run_uninterrupted(tmp_path)

    spec, config, (ids, vals, labels) = make_problem()
    ckdir = str(tmp_path / "ck2")

    # Phase 1: train only 20 of 40 steps, checkpointing every 10.
    t1 = FMTrainer(spec, config)
    b1 = Batches(ids, vals, labels, config.batch_size, seed=7)
    ck1 = Checkpointer(ckdir, save_every=10, async_save=False)
    t1.fit(b1, num_steps=20, checkpointer=ck1)
    ck1.close()
    del t1  # "the process died"

    # Phase 2: brand-new process state; fit() auto-resumes from step 20.
    t2 = FMTrainer(spec, config)
    b2 = Batches(ids, vals, labels, config.batch_size, seed=7)
    ck2 = Checkpointer(ckdir, save_every=10, async_save=False)
    t2.fit(b2, checkpointer=ck2)
    ck2.close()

    assert t2.step_count == golden.step_count == 40
    for a, b in zip(
        np.asarray(golden.params["v"]).ravel(),
        np.asarray(t2.params["v"]).ravel(),
    ):
        assert a == b, "resumed run diverged from uninterrupted run"
    np.testing.assert_array_equal(
        np.asarray(golden.params["w"]), np.asarray(t2.params["w"])
    )
    # Same batch sequence ⇒ same logged losses after the join point.
    assert golden.loss_history[-1] == t2.loss_history[-1]


def test_preemption_guard_flushes_and_resumes(tmp_path):
    spec, config, (ids, vals, labels) = make_problem()
    ckdir = str(tmp_path / "ck3")

    class TripWire:
        """Batch iterator that SIGTERMs the process mid-training."""

        def __init__(self, inner, at):
            self.inner, self.at, self.n = inner, at, 0

        def state(self):
            return self.inner.state()

        def restore(self, s):
            self.inner.restore(s)

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == self.at:
                os.kill(os.getpid(), signal.SIGTERM)
            return next(self.inner)

    t1 = FMTrainer(spec, config)
    b1 = TripWire(Batches(ids, vals, labels, config.batch_size, seed=7), at=15)
    ck1 = Checkpointer(ckdir, save_every=1000, async_save=False)
    with PreemptionGuard() as guard:
        t1.fit(b1, checkpointer=ck1, preemption_guard=guard)
    ck1.close()
    stopped_at = t1.step_count
    assert 15 <= stopped_at < 40, "guard should have stopped the loop early"

    # Resume completes the run.
    t2 = FMTrainer(spec, config)
    b2 = Batches(ids, vals, labels, config.batch_size, seed=7)
    ck2 = Checkpointer(ckdir, save_every=1000, async_save=False)
    ck2_step = ck2.latest_step()
    assert ck2_step == stopped_at, "preemption flush missing"
    t2.fit(b2, checkpointer=ck2)
    ck2.close()
    assert t2.step_count == 40


def test_restore_none_on_fresh_dir(tmp_path):
    spec, config, _ = make_problem()
    trainer = FMTrainer(spec, config)
    ck = Checkpointer(str(tmp_path / "empty"), async_save=False)
    assert ck.restore(trainer.params, trainer.opt_state) is None
    ck.close()


def test_sharded_checkpoint_kill_and_resume(tmp_path, rng):
    """--ckpt-sharded: sharded-array checkpoints (no host gather) resume
    bit-identically on the same mesh, matching an uninterrupted run."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices (fake CPU mesh)")
    import dataclasses as _dc

    from fm_spark_tpu import cli, configs as configs_lib

    ids = rng.integers(0, 32, size=(512, 5)).astype(np.int32)

    small = _dc.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="shck", bucket=32, num_fields=5, rank=4,
        batch_size=64, num_steps=8,
    )
    configs_lib.CONFIGS["shck"] = small
    try:
        def run(ckdir, steps):
            rc = cli.main([
                "train", "--config", "shck", "--synthetic", "512",
                "--steps", str(steps), "--strategy", "field_sparse",
                "--ckpt-sharded", "--checkpoint-dir", str(ckdir),
                "--checkpoint-every", "4", "--test-fraction", "0",
                "--model-out", str(ckdir) + "_model", "--log-every", "4",
            ])
            assert rc == 0

        # Uninterrupted 8 steps.
        run(tmp_path / "full", 8)
        # Interrupted: 4 steps, then resume to 8 in a fresh process-like
        # second invocation against the same checkpoint dir.
        run(tmp_path / "part", 4)
        run(tmp_path / "part", 8)

        from fm_spark_tpu import models as models_lib

        _, p_full = models_lib.load_model(str(tmp_path / "full_model"))
        _, p_part = models_lib.load_model(str(tmp_path / "part_model"))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_full),
            jax.tree_util.tree_leaves(p_part),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        del configs_lib.CONFIGS["shck"]


def test_ckpt_sharded_rejects_canonical_checkpoint(tmp_path):
    import dataclasses as _dc

    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices (fake CPU mesh)")
    from fm_spark_tpu import cli, configs as configs_lib

    small = _dc.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="shck2", bucket=32, num_fields=5, rank=4,
        batch_size=64, num_steps=4,
    )
    configs_lib.CONFIGS["shck2"] = small
    try:
        ck = str(tmp_path / "ck")
        assert cli.main([
            "train", "--config", "shck2", "--synthetic", "512",
            "--steps", "4", "--strategy", "field_sparse",
            "--checkpoint-dir", ck, "--checkpoint-every", "2",
            "--test-fraction", "0",
        ]) == 0
        with pytest.raises(SystemExit, match="canonical|mesh"):
            cli.main([
                "train", "--config", "shck2", "--synthetic", "512",
                "--steps", "8", "--strategy", "field_sparse",
                "--ckpt-sharded", "--checkpoint-dir", ck,
                "--test-fraction", "0",
            ])
    finally:
        del configs_lib.CONFIGS["shck2"]


def test_canonical_resume_rejects_sharded_checkpoint(tmp_path, rng):
    """Reverse direction of the layout check: a --ckpt-sharded checkpoint
    resumed WITHOUT the flag gets the actionable hint, not an orbax
    tree-structure traceback."""
    import dataclasses as _dc

    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices (fake CPU mesh)")
    from fm_spark_tpu import cli, configs as configs_lib

    small = _dc.replace(
        configs_lib.CONFIGS["criteo1tb_fm_r64"],
        name="shck3", bucket=32, num_fields=5, rank=4,
        batch_size=64, num_steps=4,
    )
    configs_lib.CONFIGS["shck3"] = small
    try:
        ck = str(tmp_path / "ck")
        assert cli.main([
            "train", "--config", "shck3", "--synthetic", "512",
            "--steps", "4", "--strategy", "field_sparse",
            "--ckpt-sharded", "--checkpoint-dir", ck,
            "--checkpoint-every", "2", "--test-fraction", "0",
        ]) == 0
        with pytest.raises(SystemExit, match="ckpt-sharded"):
            cli.main([
                "train", "--config", "shck3", "--synthetic", "512",
                "--steps", "8", "--strategy", "field_sparse",
                "--checkpoint-dir", ck, "--test-fraction", "0",
            ])
    finally:
        del configs_lib.CONFIGS["shck3"]
