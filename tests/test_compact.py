"""COMPACT host-dedup (`TrainConfig.compact_cap`): the cap-lane path —
unique-row gather, inv expansion, cumsum segment sums, one unique+sorted
write per id — must match the scatter_add step up to fp32 reassociation
(the cumsum reorders the additions, so equality is allclose, not
bitwise; everything else in the step is identical math).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import compact_aux
from fm_spark_tpu.sparse import (
    make_field_sparse_multistep,
    make_field_sparse_sgd_body,
    make_field_sparse_sgd_step,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B, CAP = 5, 64, 4, 48, 48


def _batch(rng, b=B, f=F, bucket=BUCKET):
    ids = rng.integers(0, bucket, size=(b, f)).astype(np.int32)
    ids[:, 0] = rng.integers(0, 3, b)          # heavy duplication
    vals = rng.normal(size=(b, f)).astype(np.float32)
    labels = rng.integers(0, 2, b).astype(np.float32)
    weights = np.ones(b, np.float32)
    weights[::7] = 0.0                          # inert rows
    return ids, vals, labels, weights


def _spec(**kw):
    kw.setdefault("param_dtype", "float32")
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, **kw
    )


def test_compact_aux_semantics(rng):
    ids = rng.integers(0, 17, size=(40, 3)).astype(np.int32)
    cap = 24
    useg, segstart, segend, order, inv = compact_aux(ids, cap)
    assert useg.shape == segstart.shape == segend.shape == (3, cap)
    assert order.shape == inv.shape == (3, 40)
    for f in range(3):
        uniq = np.unique(ids[:, f])
        s = uniq.size
        np.testing.assert_array_equal(useg[f, :s], uniq)
        # Padding: distinct ascending out-of-range sentinels — the whole
        # vector stays sorted and unique (the XLA scatter promises).
        assert (np.diff(useg[f].astype(np.int64)) > 0).all()
        assert (useg[f, s:] >= np.iinfo(np.int32).max - cap).all()
        sid = ids[order[f], f]
        np.testing.assert_array_equal(sid, np.sort(ids[:, f]))
        for seg in range(s):
            lo, hi = segstart[f, seg], segend[f, seg]
            assert (sid[lo : hi + 1] == useg[f, seg]).all()
            if hi + 1 < 40:
                assert sid[hi + 1] != useg[f, seg]
        # inv maps each original lane to its id's segment.
        np.testing.assert_array_equal(useg[f, inv[f]], ids[:, f])


def test_compact_aux_overflow_raises(rng):
    ids = rng.integers(0, 40, size=(64, 2)).astype(np.int32)
    with pytest.raises(ValueError, match="compact cap"):
        compact_aux(ids, 4)


def test_compact_aux_native_matches_numpy(rng):
    from fm_spark_tpu import native

    if not native.available():
        pytest.skip(f"native library unavailable: {native.build_error()}")
    ids = (rng.zipf(1.3, size=(257, 7)) % 50).astype(np.int32)
    ids[:, 3] = 5  # constant field
    got = native.compact_aux_native(ids, 128)
    assert got is not None
    import unittest.mock as mock

    with mock.patch.object(native, "compact_aux_native", lambda *a: None):
        want = compact_aux(ids, 128)
    names = ("useg", "segstart", "segend", "order", "inv")
    for g, w, name in zip(got, want, names):
        np.testing.assert_array_equal(g, w, err_msg=name)
    with pytest.raises(ValueError, match="compact cap"):
        native.compact_aux_native(ids, 4)


def _run_pair(rng, cfg_kw=None, spec_kw=None, step_idx=3):
    ids, vals, labels, weights = _batch(rng)
    spec = _spec(**(spec_kw or {}))
    params = spec.init(jax.random.key(1))
    base = dict(learning_rate=0.05, optimizer="sgd",
                reg_factors=1e-4, reg_linear=1e-4)
    base.update(cfg_kw or {})
    ref_step = make_field_sparse_sgd_step(spec, TrainConfig(**base))
    cmp_step = make_field_sparse_sgd_step(
        spec,
        TrainConfig(**base, sparse_update="dedup", host_dedup=True,
                    compact_cap=CAP),
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids, CAP))
    args = (jnp.int32(step_idx), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights))
    p_ref, l_ref = ref_step(jax.tree.map(jnp.copy, params), *args)
    p_cmp, l_cmp = cmp_step(params, *args, aux)
    return p_ref, l_ref, p_cmp, l_cmp


def test_compact_step_matches_scatter_add(rng):
    p_ref, l_ref, p_cmp, l_cmp = _run_pair(rng)
    assert float(l_ref) == float(l_cmp)  # same forward math
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-6, atol=2e-7),
        p_ref, p_cmp,
    )


def test_compact_dedup_sr_fp32_matches_dedup(rng):
    """For fp32 tables SR is the identity, and set(urows + sum) must hit
    the same values as add(sum) bitwise — pins the urows plumbing."""
    ids, vals, labels, weights = _batch(rng)
    spec = _spec()
    params = spec.init(jax.random.key(2))
    mk = lambda su: make_field_sparse_sgd_step(
        spec,
        TrainConfig(learning_rate=0.05, optimizer="sgd", sparse_update=su,
                    host_dedup=True, compact_cap=CAP),
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids, CAP))
    args = (jnp.int32(0), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights))
    p_a, _ = mk("dedup")(jax.tree.map(jnp.copy, params), *args, aux)
    p_b, _ = mk("dedup_sr")(params, *args, aux)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), p_a, p_b
    )


def test_compact_bf16_sr_learns(rng):
    """bf16 + compact dedup_sr: loss decreases over a few steps (the
    quality envelope itself is pinned by bench_quality/QUALITY.md)."""
    ids, vals, labels, weights = _batch(rng, b=256)
    spec = _spec(param_dtype="bfloat16")
    params = spec.init(jax.random.key(3))
    step = make_field_sparse_sgd_step(
        spec,
        TrainConfig(learning_rate=0.3, lr_schedule="constant",
                    optimizer="sgd", sparse_update="dedup_sr",
                    host_dedup=True, compact_cap=B_CAP256),
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids, B_CAP256))
    losses = []
    for i in range(25):
        params, loss = step(params, jnp.int32(i), jnp.asarray(ids),
                            jnp.asarray(vals), jnp.asarray(labels),
                            jnp.asarray(weights), aux)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.01


B_CAP256 = 128


def test_compact_multistep_matches_single(rng):
    """compact aux stacks on the leading axis like every other batch
    leaf; N fori_loop steps == N separate calls."""
    spec = _spec()
    cfg = TrainConfig(learning_rate=0.05, optimizer="sgd",
                      sparse_update="dedup", host_dedup=True,
                      compact_cap=CAP)
    params = spec.init(jax.random.key(4))
    batches = []
    for _ in range(3):
        ids, vals, labels, weights = _batch(rng)
        aux = compact_aux(ids, CAP)
        batches.append((ids, vals, labels, weights, aux))

    single = make_field_sparse_sgd_step(spec, cfg)
    p1 = jax.tree.map(jnp.copy, params)
    for j, (ids, vals, labels, weights, aux) in enumerate(batches):
        p1, _ = single(p1, jnp.int32(j), jnp.asarray(ids),
                       jnp.asarray(vals), jnp.asarray(labels),
                       jnp.asarray(weights),
                       tuple(jnp.asarray(a) for a in aux))

    mstep = make_field_sparse_multistep(spec, cfg, 3)
    stack = lambda xs: jnp.asarray(np.stack(xs))
    ids_s = stack([b[0] for b in batches])
    vals_s = stack([b[1] for b in batches])
    labels_s = stack([b[2] for b in batches])
    weights_s = stack([b[3] for b in batches])
    aux_s = tuple(
        stack([b[4][i] for b in batches]) for i in range(5)
    )
    p2, _ = mstep(params, jnp.int32(0), jnp.int32(3), ids_s, vals_s,
                  labels_s, weights_s, aux_s)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), p1, p2
    )


def test_compact_validation():
    spec = _spec()
    with pytest.raises(ValueError, match="host_dedup"):
        make_field_sparse_sgd_body(
            spec, TrainConfig(optimizer="sgd", sparse_update="dedup",
                              compact_cap=8)
        )
    # The field-sharded body supports COMPACT aux (1-D mesh) but must
    # still reject plain full-B host_dedup rather than silently ignore
    # it (it consumes only the compact aux format).
    from fm_spark_tpu.parallel.field_step import (
        make_field_mesh,
        make_field_sharded_sgd_body,
    )

    mesh = make_field_mesh(1)
    with pytest.raises(ValueError, match="not supported"):
        make_field_sharded_sgd_body(
            spec,
            TrainConfig(optimizer="sgd", sparse_update="dedup",
                        host_dedup=True),
            mesh,
        )


@pytest.mark.slow
def test_cli_measured_best_flags_smoke(tmp_path):
    """End-to-end: the full measured-best flag set (PERF.md headline —
    bf16 tables, bf16 compute, compact host-dedup, dedup_sr) trains,
    evals, and saves through the CLI. Subprocess with ONE cpu device so
    field_sparse routes to the single-chip fused step."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(__file__))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "fm_spark_tpu.cli",
         "train", "--config", "criteo1tb_fm_r64", "--synthetic", "4096",
         "--steps", "15", "--batch-size", "512",
         "--strategy", "field_sparse",
         "--param-dtype", "bfloat16", "--compute-dtype", "bfloat16",
         "--sparse-update", "dedup_sr", "--host-dedup",
         "--compact-cap", "512", "--prefetch", "2",
         "--test-fraction", "0.2", "--log-every", "5",
         "--model-out", str(tmp_path / "m")],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert '"eval"' in proc.stdout or "auc" in proc.stdout
    from fm_spark_tpu.models.io import load_model

    spec2, params2 = load_model(str(tmp_path / "m"))
    assert spec2.param_dtype == "bfloat16"


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
@pytest.mark.parametrize("param_dtype", ["float32", "bfloat16"])
def test_col_layout_matches_row_bitwise(rng, mode, param_dtype):
    """table_layout='col' (transposed [w, bucket] storage) must be
    BITWISE equal to the row layout under transpose: same init values,
    same SR key stream, identical step math — only the physical
    orientation differs (PERF.md 'transpose' probe rationale)."""
    ids, vals, labels, weights = _batch(rng)
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids, CAP))
    base = dict(num_features=F * BUCKET, rank=K, num_fields=F,
                bucket=BUCKET, init_std=0.1, param_dtype=param_dtype)
    cfg = TrainConfig(learning_rate=0.05, optimizer="sgd",
                      reg_factors=1e-4, reg_linear=1e-4,
                      sparse_update=mode, host_dedup=True,
                      compact_cap=CAP)
    sr_ = models.FieldFMSpec(**base)
    sc = models.FieldFMSpec(**base, table_layout="col")
    pr = sr_.init(jax.random.key(1))
    pc = sc.init(jax.random.key(1))
    args = (jnp.int32(2), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights), aux)
    pr, lr_ = make_field_sparse_sgd_step(sr_, cfg)(pr, *args)
    pc, lc_ = make_field_sparse_sgd_step(sc, cfg)(pc, *args)
    assert float(lr_) == float(lc_)
    for f in range(F):
        np.testing.assert_array_equal(
            np.asarray(pc["vw"][f]).T, np.asarray(pr["vw"][f])
        )
    s_r = sr_.scores(pr, jnp.asarray(ids), jnp.asarray(vals))
    s_c = sc.scores(pc, jnp.asarray(ids), jnp.asarray(vals))
    np.testing.assert_array_equal(np.asarray(s_r), np.asarray(s_c))


def test_col_layout_validation():
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, table_layout="col",
    )
    # col without the compact path: the plain gather assumes row-major.
    with pytest.raises(ValueError, match="compact"):
        make_field_sparse_sgd_body(
            spec, TrainConfig(optimizer="sgd")
        )
    # col + field-sharded stacking: rejected.
    from fm_spark_tpu.parallel.field_step import stack_field_params

    with pytest.raises(ValueError, match="row"):
        stack_field_params(spec, spec.init(jax.random.key(0)), 2)
    with pytest.raises(ValueError, match="table_layout"):
        models.FieldFMSpec(
            num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
            init_std=0.1, table_layout="diagonal",
        )


def test_col_layout_model_io_roundtrip(rng, tmp_path):
    """spec.json carries table_layout; save/load and libFM export see
    identical values either way."""
    from fm_spark_tpu import models as m
    from fm_spark_tpu.models.io import load_model, save_model

    base = dict(num_features=F * BUCKET, rank=K, num_fields=F,
                bucket=BUCKET, init_std=0.1)
    sc = m.FieldFMSpec(**base, table_layout="col")
    pc = sc.init(jax.random.key(5))
    save_model(str(tmp_path / "m"), sc, pc)
    spec2, params2 = load_model(str(tmp_path / "m"))
    assert spec2.table_layout == "col"
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), params2, pc
    )
    flat_c = sc.to_flat_params(pc)
    flat_r = m.FieldFMSpec(**base).to_flat_params(
        m.FieldFMSpec(**base).init(jax.random.key(5))
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, b), flat_c, flat_r
    )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
@pytest.mark.parametrize("n_feat,num_fields", [(4, 5), (2, 5), (4, 4)])
def test_sharded_compact_matches_single(rng, mode, n_feat, num_fields):
    """Field-sharded compact (1-D feat mesh, incl. padded fields) must
    match the single-chip compact step exactly: same aux, same SR key
    stream (global field offsets), single-owner cap-lane writes."""
    import jax.numpy as jnp

    from fm_spark_tpu.parallel.field_step import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        pad_field_batch,
        shard_compact_aux,
        shard_field_batch,
        shard_field_params,
        stack_field_params,
        unstack_field_params,
    )

    bucket, rank, b, cap = 32, 4, 64, 64
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.3, lr_schedule="inv_sqrt",
                         optimizer="sgd", reg_factors=1e-3,
                         reg_linear=1e-4, reg_bias=1e-4,
                         sparse_update=mode, host_dedup=True,
                         compact_cap=cap)
    mesh = make_field_mesh(n_feat)
    params = spec.init(jax.random.key(0))
    ref_params = jax.tree.map(jnp.copy, params)
    sharded = shard_field_params(
        stack_field_params(spec, params, n_feat), mesh
    )
    step_sharded = make_field_sharded_sgd_step(spec, config, mesh)
    step_single = make_field_sparse_sgd_step(spec, config)

    for i in range(3):
        ids = rng.integers(0, bucket, size=(b, num_fields)).astype(np.int32)
        ids[:, 0] = rng.integers(0, 3, b)
        vals = rng.normal(size=(b, num_fields)).astype(np.float32)
        labels = rng.integers(0, 2, b).astype(np.float32)
        weights = np.ones(b, np.float32)
        weights[::5] = 0.0
        batch = (ids, vals, labels, weights)
        aux = compact_aux(ids, cap)
        paux = shard_compact_aux(aux, mesh, n_feat)
        sb = shard_field_batch(
            pad_field_batch(batch, num_fields, n_feat), mesh
        )
        sharded, loss_sh = step_sharded(sharded, jnp.int32(i), *sb, paux)
        ref_params, loss_ref = step_single(
            ref_params, jnp.int32(i), *map(jnp.asarray, batch),
            tuple(jnp.asarray(a) for a in aux),
        )
        np.testing.assert_allclose(
            float(loss_sh), float(loss_ref), rtol=1e-6
        )
    got = unstack_field_params(spec, jax.device_get(sharded))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        got, jax.device_get(ref_params),
    )


def test_sharded_compact_rejects_2d_mesh():
    from fm_spark_tpu.parallel.field_step import (
        make_field_mesh,
        make_field_sharded_sgd_body,
    )

    spec = _spec()
    mesh = make_field_mesh(4, n_row=2)
    with pytest.raises(ValueError, match="1-D"):
        make_field_sharded_sgd_body(
            spec,
            TrainConfig(optimizer="sgd", sparse_update="dedup",
                        host_dedup=True, compact_cap=8),
            mesh,
        )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_ffm_compact_matches_plain(rng, mode):
    """FieldFFM fused step: compact aux path == plain path (fp32; SR is
    the identity there so dedup_sr pins the urows plumbing too)."""
    from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step

    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.2, optimizer="sgd", sparse_update=mode)
    params = spec.init(jax.random.key(1))
    params_c = jax.tree.map(jnp.copy, params)
    step_p = make_field_ffm_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_c = make_field_ffm_sparse_sgd_step(
        spec, TrainConfig(host_dedup=True, compact_cap=CAP, **cfg)
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids_np, CAP))
    for i in range(2):
        params, _ = step_p(params, jnp.int32(i), *batch)
        params_c, _ = step_c(params_c, jnp.int32(i), *batch, aux)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_c["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
@pytest.mark.slow
def test_deepfm_compact_matches_plain(rng, mode):
    """FieldDeepFM hybrid step: compact embedding updates == plain; the
    dense MLP/w0 side (optax) must be bitwise-unaffected."""
    from fm_spark_tpu.sparse import make_field_deepfm_sparse_step

    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.05, optimizer="adam", sparse_update=mode)
    params = spec.init(jax.random.key(2))
    params_c = jax.tree.map(jnp.copy, params)
    step_p = make_field_deepfm_sparse_step(spec, TrainConfig(**cfg))
    step_c = make_field_deepfm_sparse_step(
        spec, TrainConfig(host_dedup=True, compact_cap=CAP, **cfg)
    )
    opt_p = step_p.init_opt_state(params)
    opt_c = step_c.init_opt_state(params_c)
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids_np, CAP))
    for i in range(2):
        params, opt_p, _ = step_p(params, opt_p, jnp.int32(i), *batch)
        params_c, opt_c, _ = step_c(params_c, opt_c, jnp.int32(i), *batch,
                                    aux)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_c["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8),
        {"w0": params_c["w0"], "mlp": params_c["mlp"]},
        {"w0": params["w0"], "mlp": params["mlp"]},
    )
