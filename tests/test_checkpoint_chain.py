"""Crash-consistent checkpoint chain (ISSUE 4 tentpole piece 2).

"The last good checkpoint" must be a guarantee, not a hope: every
committed save gets a per-save manifest with array checksums, the
persisted ``last_good`` pointer advances only after verification, and
``restore()`` walks back past torn (manifest-missing) and corrupt
(checksum-mismatching) saves to the newest verified step instead of
raising — or raises :class:`CheckpointChainBroken` when NOTHING
verifies, because silently restarting from scratch would discard the
run's progress. The SIGKILL subprocess test at the bottom drives the
real torn window: data committed, manifest never written.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.checkpoint import (
    CheckpointChainBroken,
    Checkpointer,
)
from fm_spark_tpu.resilience import faults
from fm_spark_tpu.resilience.faults import FaultInjected

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_PLAN, raising=False)
    monkeypatch.delenv(faults.ENV_STATE, raising=False)
    faults.clear()
    yield
    faults.clear()


def _params():
    spec = models.FMSpec(num_features=16, rank=2)
    return spec.init(jax.random.key(0))


def _save_two(ckdir, params):
    ck = Checkpointer(str(ckdir), save_every=1, async_save=False)
    ck.save(1, params, {}, {"epoch": 0}, {"loss_history": [0.9]})
    ck.save(2, params, {}, {"epoch": 1}, {"loss_history": [0.9, 0.8]})
    ck.close()


def _state_files(ckdir, step):
    files = [p for p in glob.glob(
        os.path.join(str(ckdir), str(step), "state", "**", "d", "*"),
        recursive=True) if os.path.isfile(p)]
    assert files, f"no array data files under step {step}"
    return files


def test_save_writes_manifest_and_advances_last_good(tmp_path):
    ck = Checkpointer(str(tmp_path), save_every=1, async_save=False)
    params = _params()
    assert ck.last_good_step() is None
    ck.save(3, params, {}, {"epoch": 0}, None)
    assert ck.last_good_step() == 3
    manifest = json.loads(
        (tmp_path / "manifests" / "3.json").read_text())
    assert manifest["step"] == 3
    # One checksum per array leaf, dtype/shape-stamped.
    assert all(":" in v for v in manifest["checksums"].values())
    assert manifest["meta_crc"]
    ck.close()


def test_async_save_verifies_at_wait(tmp_path):
    ck = Checkpointer(str(tmp_path), save_every=1, async_save=True)
    params = _params()
    ck.save(5, params, {}, {"epoch": 0}, None)
    ck.wait()  # commit + flush: manifest and pointer land here
    assert ck.last_good_step() == 5
    assert (tmp_path / "manifests" / "5.json").exists()
    ck.close()


def test_restore_walks_back_past_flipped_bytes(tmp_path):
    params = _params()
    _save_two(tmp_path, params)
    for p in _state_files(tmp_path, 2):
        with open(p, "r+b") as f:
            data = bytearray(f.read())
            for i in range(min(64, len(data))):
                data[i] ^= 0xFF
            f.seek(0)
            f.write(data)
    ck = Checkpointer(str(tmp_path), async_save=False)
    restored = ck.restore(params, {})
    assert restored["step"] == 1
    assert restored["extra"]["loss_history"] == [0.9]
    ck.close()


def test_restore_walks_back_past_truncated_save(tmp_path):
    params = _params()
    _save_two(tmp_path, params)
    for p in _state_files(tmp_path, 2):
        with open(p, "r+b") as f:
            f.truncate(max(os.path.getsize(p) // 2, 1))
    ck = Checkpointer(str(tmp_path), async_save=False)
    assert ck.restore(params, {})["step"] == 1
    ck.close()


def test_restore_skips_committed_but_unverified_newest_step(tmp_path):
    """The torn window driven in-process: the ``ckpt_commit`` fault
    fires AFTER step 2's data commit and BEFORE its manifest write —
    exactly where a crash strands a save — and restore must come back
    with step 1."""
    params = _params()
    ck = Checkpointer(str(tmp_path), save_every=1, async_save=False)
    ck.save(1, params, {}, {"epoch": 0}, {"loss_history": [0.9]})
    faults.activate("ckpt_commit@1=error")
    with pytest.raises(FaultInjected):
        ck.save(2, params, {}, {"epoch": 1}, {"loss_history": [0.9, 0.8]})
    faults.clear()
    # Step 2's DATA is committed (orbax finished) — only verification
    # is missing; the chain must not trust it.
    assert os.path.isdir(tmp_path / "2")
    assert not (tmp_path / "manifests" / "2.json").exists()
    assert ck.last_good_step() == 1

    ck2 = Checkpointer(str(tmp_path), async_save=False)
    restored = ck2.restore(params, {})
    assert restored["step"] == 1
    assert restored["extra"]["loss_history"] == [0.9]
    ck2.close()


def test_restore_raises_chain_broken_when_nothing_verifies(tmp_path):
    params = _params()
    ck = Checkpointer(str(tmp_path), save_every=1, async_save=False)
    ck.save(1, params, {}, {"epoch": 0}, None)
    ck.close()
    for p in _state_files(tmp_path, 1):
        with open(p, "r+b") as f:
            f.write(b"\xde\xad\xbe\xef")
    ck2 = Checkpointer(str(tmp_path), async_save=False)
    with pytest.raises(CheckpointChainBroken):
        ck2.restore(params, {})
    ck2.close()


def test_explicit_step_restore_fails_loudly_on_corruption(tmp_path):
    params = _params()
    _save_two(tmp_path, params)
    for p in _state_files(tmp_path, 2):
        with open(p, "r+b") as f:
            data = bytearray(f.read())
            data[:16] = b"\x00" * 16
            f.seek(0)
            f.write(data)
    ck = Checkpointer(str(tmp_path), async_save=False)
    # The caller asked for EXACTLY step 2: no silent walk-back.
    with pytest.raises(Exception):
        ck.restore(params, {}, step=2)
    # Step 1 by explicit request still restores.
    assert ck.restore(params, {}, step=1)["step"] == 1
    ck.close()


def test_legacy_directory_without_manifests_still_restores(tmp_path):
    """Pre-chain checkpoint dirs (no manifests/ at all) keep working:
    restore without verification, never a spurious torn-save skip."""
    params = _params()
    _save_two(tmp_path, params)
    import shutil

    shutil.rmtree(tmp_path / "manifests")
    os.unlink(tmp_path / "last_good.json")
    ck = Checkpointer(str(tmp_path), async_save=False)
    assert ck.restore(params, {})["step"] == 2
    ck.close()


_SIGKILL_CHILD = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
from fm_spark_tpu import models
from fm_spark_tpu.checkpoint import Checkpointer
from fm_spark_tpu.resilience import faults

ckdir = sys.argv[1]
spec = models.FMSpec(num_features=16, rank=2)
params = spec.init(jax.random.key(0))
ck = Checkpointer(ckdir, save_every=1, async_save=False)
ck.save(1, params, {}, {"epoch": 0}, {"loss_history": [0.9]})
# Arm AFTER step 1 verified: the next flush hangs in the torn window
# (data committed, manifest not yet written) until SIGKILL lands.
faults.activate("ckpt_commit@1=hang:300")
print("STEP1-VERIFIED", flush=True)
ck.save(2, params, {}, {"epoch": 1}, {"loss_history": [0.9, 0.8]})
print("NEVER-REACHED", flush=True)
"""


def test_sigkill_mid_save_never_leaves_torn_latest(tmp_path):
    """ISSUE 4 acceptance: SIGKILL during a save never leaves
    ``restore()`` pointing at a torn checkpoint — the chain resumes at
    the newest VERIFIED step."""
    ckdir = tmp_path / "ck"
    script = tmp_path / "child.py"
    script.write_text(_SIGKILL_CHILD)
    proc = subprocess.Popen(
        [sys.executable, str(script), str(ckdir)],
        stdout=subprocess.PIPE, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )
    try:
        line = proc.stdout.readline().strip()
        assert line == "STEP1-VERIFIED", line
        # Wait for step 2's DATA commit to land on disk (the hang fires
        # after orbax's atomic rename), then kill -9 mid-"write".
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.exists(ckdir / "2" / "_CHECKPOINT_METADATA"):
                break
            time.sleep(0.1)
        else:
            pytest.fail("step 2 data commit never appeared")
        time.sleep(0.3)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode == -signal.SIGKILL

    # The torn step's data exists but the chain never references it.
    assert os.path.isdir(ckdir / "2")
    assert not (ckdir / "manifests" / "2.json").exists()
    params = _params()
    ck = Checkpointer(str(ckdir), async_save=False)
    assert ck.last_good_step() == 1
    restored = ck.restore(params, {})
    assert restored["step"] == 1
    assert restored["extra"]["loss_history"] == [0.9]
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), np.asarray(params["w"]))
    ck.close()


# ------------------------------------------ demotion tombstones (ISSUE 13)


def _chain(ckdir, steps=(1, 2, 3)):
    params = _params()
    ck = Checkpointer(str(ckdir), save_every=1, async_save=False)
    for s in steps:
        ck.save(s, jax.tree_util.tree_map(lambda a: a * s, params),
                {}, {"epoch": s}, force=True)
    ck.wait()
    return ck, params


def test_demote_tombstones_and_republishes_last_good(tmp_path):
    """The coordinated-rollback primitive: a committed, VERIFIED save
    judged bad after publish gets a durable tombstone and the pointer
    republishes at the newest good step — bytes intact, model vetoed."""
    ck, params = _chain(tmp_path / "ck")
    assert ck.last_good_step() == 3
    assert ck.demote(3, reason="drift verdict") is True
    assert ck.last_good_step() == 2
    assert ck.is_tombstoned(3) and not ck.is_tombstoned(2)
    restored = ck.restore(params, {})
    assert restored["step"] == 2
    # Idempotent: a second demotion of the same step is a no-op.
    assert ck.demote(3) is False
    ck.close()


def test_demote_newer_than_is_one_atomic_range(tmp_path):
    """``demote_newer_than`` writes ONE range tombstone — a kill can
    never leave a partially-demoted suffix where some bad generation
    is still trusted."""
    ck, params = _chain(tmp_path / "ck", steps=(1, 2, 3, 4))
    demoted = ck.demote_newer_than(2, reason="drift day")
    assert demoted == [3, 4]
    assert ck.tombstoned_steps() == {3, 4}
    assert ck.tombstone_frontier() == 4
    assert ck.last_good_step() == 2
    stones = os.listdir(str(tmp_path / "ck" / "tombstones"))
    assert stones == ["range_2_4.json"]  # one atomic veto
    # Post-rollback saves land PAST the frontier and are trusted.
    ck.save(5, _params(), {}, None, force=True)
    ck.wait()
    assert ck.last_good_step() == 5
    assert ck.restore(params, {})["step"] == 5
    ck.close()


def test_explicit_restore_of_tombstoned_step_refuses(tmp_path):
    ck, params = _chain(tmp_path / "ck")
    ck.demote(3, reason="drift")
    with pytest.raises(CheckpointChainBroken, match="tombstone"):
        ck.restore(params, {}, step=3)
    ck.close()


def test_drift_alarm_racing_ckpt_commit_never_publishes(tmp_path):
    """The alarm-during-commit race: a save whose verify window is
    still open when its step gets demoted must NOT advance last_good
    — the tombstone wins even against an in-flight commit."""
    params = _params()
    ck = Checkpointer(str(tmp_path / "ck"), save_every=1,
                      async_save=True)
    ck.save(1, params, {}, None, force=True)
    ck.wait()
    assert ck.last_good_step() == 1
    # Async save 2: data commits, manifest still pending...
    ck.save(2, params, {}, None, force=True)
    ck._mgr.wait_until_finished()
    # ...and the drift verdict lands BEFORE the verify flush.
    os.makedirs(str(tmp_path / "ck" / "tombstones"), exist_ok=True)
    with open(str(tmp_path / "ck" / "tombstones" / "2.json"),
              "w") as f:
        json.dump({"step": 2, "reason": "drift"}, f)
    ck.wait()  # flushes the pending manifest
    assert ck.last_good_step() == 1  # pointer never vouched for 2
    assert ck.restore(params, {})["step"] == 1
    ck.close()


def test_ckpt_demote_fault_point_fires_in_the_demotion_window(tmp_path):
    """Registry coverage for ``ckpt_demote``: the fault point sits
    AFTER the tombstone write, BEFORE the pointer republish — an
    injected error leaves exactly the mid-demotion state every reader
    must already survive, and the re-run repairs the pointer."""
    ck, params = _chain(tmp_path / "ck")
    faults.activate("ckpt_demote@1=error")
    with pytest.raises(FaultInjected):
        ck.demote_newer_than(1, reason="drift")
    # Tombstone durable, pointer stale — readers veto anyway.
    assert ck.tombstoned_steps() == {2, 3}
    assert ck.last_good_step() == 3  # stale
    assert ck.restore(params, {})["step"] == 1
    faults.clear()
    # Recovery re-run: idempotent, repairs the pointer.
    assert ck.demote_newer_than(1, reason="drift") == []
    assert ck.last_good_step() == 1
    ck.close()


def test_follower_skips_tombstoned_steps(tmp_path):
    from fm_spark_tpu.checkpoint import ChainFollower

    ck, params = _chain(tmp_path / "ck")
    ck.demote_newer_than(1, reason="drift")
    ck.close()
    fol = ChainFollower(str(tmp_path / "ck"))
    assert fol.tombstoned_steps() == {2, 3}
    restored = fol.restore(params, {})
    assert restored is not None and restored["step"] == 1
    fol.close()


def test_sigkill_mid_demotion_recovers_to_pre_drift_save(tmp_path):
    """ISSUE 13 acceptance: SIGKILL at any point during the demotion
    window recovers to a consistent chain with ``last_good`` at the
    pre-drift save — the chaos drill asserts it from artifacts alone."""
    from fm_spark_tpu.resilience import chaos

    r = chaos.run_demote_kill_drill(str(tmp_path / "drill"))
    assert r["violations"] == [], r["violations"]
    assert r["rcs"] == [23, 0]
