"""Noise-aware regression sentinel (ISSUE 9): deterministic synthetic
series pinning each verdict, the real r01–r05 replay through the
backfill tool, and the keep-best gate — a ``regressed`` /
``attachment_transient`` verdict must NEVER overwrite MEASURED.json."""

import importlib.util
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fm_spark_tpu.obs.ledger import (  # noqa: E402
    PerfLedger,
    measurement_fingerprint,
)
from fm_spark_tpu.obs.sentinel import (  # noqa: E402
    ALL_VERDICTS,
    Sentinel,
    SentinelPolicy,
    classify,
    keepbest_allowed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: A stable cohort: per-chip rates with ~1% wiggle (the measured
#: healthy-attachment leg-to-leg spread).
STABLE = [1_000_000.0, 1_010_000.0, 995_000.0, 1_005_000.0, 998_000.0]


# ----------------------------------------------------------- classify


def test_step_improvement_classifies_improved():
    block = classify(STABLE, 1_400_000.0)
    assert block["verdict"] == "improved"
    assert block["z"] > 3.0
    assert block["n_history"] == len(STABLE)


def test_in_band_noise_classifies_flat():
    for v in (995_000.0, 1_000_000.0, 1_012_000.0):
        assert classify(STABLE, v)["verdict"] == "flat"


def test_healthy_drop_classifies_regressed():
    block = classify(STABLE, 880_000.0)
    assert block["verdict"] == "regressed"
    assert block["z"] < -3.0
    assert "healthy" in block["reason"]


def test_slow_drift_eventually_classifies_regressed():
    """A -1.5%/round drift: each step sits inside the band, but the
    trailing window follows it down slowly enough that the cumulative
    drop eventually breaks out — the failure mode a fixed threshold
    on the LAST value would never catch."""
    history = list(STABLE)
    value = 1_000_000.0
    verdicts = []
    for _ in range(30):
        value *= 0.985
        block = classify(history, value)
        verdicts.append(block["verdict"])
        history.append(value)
    assert verdicts[0] == "flat"  # one drift step is inside the band
    assert "regressed" in verdicts
    assert "improved" not in verdicts


def test_single_outlier_under_weather_is_attachment_transient():
    """The r03–r05 shape: a throttled window measures way low, but the
    supervisor journal says the attachment was flaky — weather, not a
    regression. The SAME value on a healthy attachment IS regressed."""
    low = 550_000.0
    assert classify(STABLE, low,
                    attachment_health="flaky")["verdict"] \
        == "attachment_transient"
    assert classify(STABLE, low,
                    attachment_health="down")["verdict"] \
        == "attachment_transient"
    assert classify(STABLE, low,
                    attachment_health="healthy")["verdict"] == "regressed"


def test_null_measurement_is_transient_under_weather():
    block = classify(STABLE, None, attachment_health="down")
    assert block["verdict"] == "attachment_transient"
    # A null with NO adverse evidence cannot be blamed on weather.
    assert classify(STABLE, None)["verdict"] == "insufficient_history"


def test_thin_history_is_insufficient():
    assert classify([], 1.0)["verdict"] == "insufficient_history"
    assert classify([1.0, 2.0], 1.0)["verdict"] == "insufficient_history"
    # Nulls in the history carry no statistical weight.
    assert classify([None, None, 1.0], 1.0)["verdict"] \
        == "insufficient_history"


def test_improvement_does_not_fire_on_inflated_noise():
    """One throttled value in the window must not widen the band enough
    to hide a real move — MAD (not stddev) is the noise scale."""
    history = STABLE + [600_000.0]  # one throttled outlier banked
    assert classify(history, 1_400_000.0)["verdict"] == "improved"
    assert classify(history, 850_000.0)["verdict"] == "regressed"


def test_rel_floor_absorbs_identical_history():
    """A cohort that repeats to the digit has MAD 0 — the relative
    floor keeps sub-threshold wiggle flat instead of flagging it."""
    flat_hist = [1_000_000.0] * 5
    assert classify(flat_hist, 1_030_000.0)["verdict"] == "flat"
    assert classify(flat_hist, 1_100_000.0)["verdict"] == "improved"


def test_policy_window_bounds_the_trailing_band():
    """Old history beyond the window must not drag the band: after 8+
    values at the new level, the old level is out of the statistic."""
    history = [500_000.0] * 10 + [1_000_000.0] * 8
    assert classify(history, 1_002_000.0,
                    policy=SentinelPolicy(window=8))["verdict"] == "flat"


def test_verdict_vocabulary_is_closed():
    assert set(ALL_VERDICTS) == {
        "improved", "flat", "regressed", "attachment_transient",
        "insufficient_history"}


# ------------------------------------------------- ledger-bound judge


def _seed(led, values, leg="legA", variant="v", health="healthy"):
    for i, v in enumerate(values):
        led.append({
            "kind": "bench_leg", "leg": leg, "run_id": f"r{i}",
            "value": v,
            "fingerprint": measurement_fingerprint(
                variant=variant, model="fm",
                attachment_health=health),
        })


def test_sentinel_prefers_exact_cohort(tmp_path):
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    _seed(led, STABLE, variant="a")
    _seed(led, [200.0, 210.0, 190.0], variant="b")
    fp_b = measurement_fingerprint(variant="b", model="fm")
    block = Sentinel(led).judge("legA", 205.0, fp_b)
    # Variant b judges against ITS cohort (~200), not the 1M leg-wide
    # mix it would drown in.
    assert block["cohort"] == "exact"
    assert block["verdict"] == "flat"


def test_sentinel_widens_to_leg_when_cohort_thin(tmp_path):
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    _seed(led, STABLE, variant="a")
    fp_new = measurement_fingerprint(variant="brand-new-lever",
                                     model="fm")
    block = Sentinel(led).judge("legA", 1_400_000.0, fp_new)
    # A fresh lever variant has no exact history — judged against the
    # metric's measured band instead of getting a free pass.
    assert block["cohort"] == "leg"
    assert block["verdict"] == "improved"


def test_widening_never_crosses_device_kinds(tmp_path):
    """A first TPU number must not score against CPU history: the
    leg-wide fallback cohort is pinned to the same device_kind +
    n_chips, so a cross-device judgment honestly reports
    insufficient_history instead of a fake 'improved'."""
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    for i, v in enumerate([100.0, 105.0, 95.0, 102.0]):
        led.append({
            "kind": "kernel_pricing", "leg": "gather", "run_id": f"r{i}",
            "value": v,
            "fingerprint": measurement_fingerprint(
                variant="gather", model="kernels", device_kind="cpu",
                n_chips=1)})
    fp_tpu = measurement_fingerprint(variant="gather", model="kernels",
                                     device_kind="TPU v5 lite", n_chips=1)
    # 50 GB/s would be z >> 3 against the CPU band — but it is not
    # comparable evidence, and a regressed TPU rate must not slip
    # through the keep-best gate dressed as 'improved'.
    block = Sentinel(led).judge("gather", 50_000.0, fp_tpu)
    assert block["verdict"] == "insufficient_history"
    # Same-device history still widens across lever configs.
    fp_cpu = measurement_fingerprint(variant="gather-v2",
                                     model="kernels", device_kind="cpu",
                                     n_chips=1)
    block = Sentinel(led).judge("gather", 101.0, fp_cpu)
    assert block["cohort"] == "leg"
    assert block["verdict"] == "flat"


def test_observe_judges_before_appending(tmp_path):
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    _seed(led, STABLE, variant="a")
    fp = measurement_fingerprint(variant="a", model="fm")
    block = Sentinel(led).observe({
        "kind": "bench_leg", "leg": "legA", "run_id": "rx",
        "value": 1_001_000.0, "fingerprint": fp})
    assert block["verdict"] == "flat"
    recs = led.records()
    assert len(recs) == len(STABLE) + 1
    assert recs[-1]["sentinel"]["verdict"] == "flat"
    # The judged value was NOT part of its own history.
    assert block["n_history"] == len(STABLE)


# ------------------------------------------------------ r01–r05 replay


def _load_backfill():
    spec = importlib.util.spec_from_file_location(
        "ledger_backfill_tool",
        os.path.join(REPO, "tools", "ledger_backfill.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def backfilled(tmp_path_factory):
    """The real repo artifacts replayed into a fresh ledger once."""
    mod = _load_backfill()
    path = str(tmp_path_factory.mktemp("ledger") / "ledger.jsonl")
    appended = mod.backfill(path, REPO)
    return mod, path, appended


def test_backfill_replays_r01_r05_pattern(backfilled):
    """THE acceptance pin: the nulled r03–r05 rounds land as
    ``attachment_transient`` — classified weather, not gaps — and the
    r02 sweep's five variant rates are the band they precede."""
    mod, path, appended = backfilled
    by_run = {}
    for rec in appended:
        by_run.setdefault(rec["run_id"], []).append(rec)
    for n in (3, 4, 5):
        (rec,) = by_run[f"backfill-bench-r{n:02d}"]
        assert rec["value"] is None
        assert rec["fingerprint"]["attachment_health"] == "down"
        assert rec["sentinel"]["verdict"] == "attachment_transient", (
            f"r{n:02d} must classify attachment_transient, got "
            f"{rec['sentinel']}")
    # r01 (backend init Unavailable) is the same weather shape.
    (r01,) = by_run["backfill-bench-r01"]
    assert r01["sentinel"]["verdict"] == "attachment_transient"
    # r02 parsed: five variant records, real values, healthy weather.
    r02 = by_run["backfill-bench-r02"]
    assert len(r02) == 5
    assert all(r["value"] > 0 for r in r02)


def test_backfill_measured_headline_replays_as_improved(backfilled):
    """The genuine round-5 lever improvement (1.059M → 1.422M) must
    read as signal against the r02 band — the sentinel agrees with
    the recorded history, not just with hand-picked examples."""
    mod, path, appended = backfilled
    (headline,) = [r for r in appended
                   if r["run_id"] == "backfill-measured-headline"]
    assert headline["value"] == pytest.approx(1422410.5)
    assert headline["sentinel"]["verdict"] == "improved"


def test_backfill_is_idempotent(backfilled):
    mod, path, appended = backfilled
    assert appended, "first backfill must append"
    assert mod.backfill(path, REPO) == []
    # Still exactly one copy of every record on disk.
    recs = PerfLedger(path).records()
    assert len(recs) == len(appended)


def test_backfill_refuses_a_live_ledger(tmp_path):
    """Backfill is day-one seeding ONLY: cohort history is append
    order, so 2026-07 values appended behind live measurements would
    become the band's most-recent entries and drag it backwards."""
    mod = _load_backfill()
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    led.append({"kind": "bench_leg", "leg": "legA", "run_id": "live-1",
                "value": 123.0,
                "fingerprint": measurement_fingerprint(
                    variant="v", model="fm")})
    assert mod.backfill(led.path, REPO) == []
    assert len(PerfLedger(led.path).records()) == 1


def test_backfill_ignores_non_cohort_kinds(tmp_path):
    """attachment_probe / kernel_pricing records never enter a bench
    cohort — a tpu_watch poll that beat the operator to the ledger
    must not forfeit the day-one seed."""
    mod = _load_backfill()
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    led.append({"kind": "attachment_probe", "leg": "attachment",
                "run_id": "watch-1", "value": 1.0,
                "fingerprint": measurement_fingerprint(
                    variant="probe", model="tpu_watch")})
    appended = mod.backfill(led.path, REPO)
    assert appended, "probe records must not block the seed"
    assert len(PerfLedger(led.path).records()) == 1 + len(appended)


def test_backfill_covers_multichip_artifacts(backfilled):
    mod, path, appended = backfilled
    multi = [r for r in appended if r["kind"] == "multichip_dryrun"]
    assert len(multi) == 5
    # The later dryruns carry the parsed projected aggregate.
    assert any(isinstance(r["value"], float) and r["value"] > 1e6
               for r in multi)


def test_backfill_cli_reports_verdict_counts(tmp_path, capsys):
    mod = _load_backfill()
    rc = mod.main(["--ledger", str(tmp_path / "l.jsonl")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["appended"] > 0
    assert doc["verdicts"]["attachment_transient"] >= 4
    rc = mod.main(["--ledger", str(tmp_path / "l.jsonl")])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["appended"] == 0


# ------------------------------------------------------ keep-best gate


@pytest.mark.parametrize("verdict,allowed", [
    ("improved", True),
    ("flat", True),
    ("insufficient_history", True),  # defers to the legacy > rule
    ("regressed", False),
    ("attachment_transient", False),
    ("garbage", False),
])
def test_keepbest_allowed_matrix(verdict, allowed):
    assert keepbest_allowed({"verdict": verdict}) is allowed


def test_keepbest_allows_pre_sentinel_artifacts():
    assert keepbest_allowed(None) is True
    assert keepbest_allowed({}) is True


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_gate_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("verdict", ["regressed", "attachment_transient"])
def test_emit_final_gate_never_overwrites_measured(tmp_path, monkeypatch,
                                                   verdict, capsys):
    """The acceptance pin: a TPU-stamped, numerically-better salvage
    line whose sentinel verdict is regressed/attachment_transient must
    leave MEASURED.json byte-identical."""
    import fm_spark_tpu.measured as measured

    src = os.path.join(REPO, "MEASURED.json")
    dst = tmp_path / "MEASURED.json"
    dst.write_bytes(open(src, "rb").read())
    monkeypatch.setattr(measured, "MEASURED_PATH", str(dst))

    bench = _load_bench()
    line = json.dumps({
        "metric": bench.METRIC, "value": 9_999_999.0,
        "unit": bench.UNIT, "vs_baseline": 8.0,
        "variant": "bfloat16/dedup_sr/compact12288/cd-bf16/gfull"
                   "/segtotal",
        "device": "TPU v5 lite",
        "sentinel": {"verdict": verdict, "reason": "test", "z": -9.0,
                     "n_history": 6},
    })
    before = dst.read_bytes()
    with bench._SALVAGE_LOCK:
        bench._SALVAGE.update(line=line, emitted=False)
    bench._emit_final()
    assert dst.read_bytes() == before, (
        f"{verdict} verdict overwrote MEASURED.json")
    # The refused line was still printed (the final-line contract).
    assert json.loads(capsys.readouterr().out)["value"] == 9_999_999.0


def test_emit_final_promotes_improved_verdict(tmp_path, monkeypatch,
                                              capsys):
    """The same line with an ``improved`` verdict DOES promote — the
    gate blocks verdicts, not the keep-best path itself."""
    import fm_spark_tpu.measured as measured

    src = os.path.join(REPO, "MEASURED.json")
    dst = tmp_path / "MEASURED.json"
    dst.write_bytes(open(src, "rb").read())
    monkeypatch.setattr(measured, "MEASURED_PATH", str(dst))

    bench = _load_bench()
    line = json.dumps({
        "metric": bench.METRIC, "value": 9_999_999.0,
        "unit": bench.UNIT, "vs_baseline": 8.0,
        "variant": "bfloat16/dedup_sr/compact12288/cd-bf16/gfull"
                   "/segtotal",
        "device": "TPU v5 lite",
        "sentinel": {"verdict": "improved", "reason": "test", "z": 9.0,
                     "n_history": 6},
    })
    with bench._SALVAGE_LOCK:
        bench._SALVAGE.update(line=line, emitted=False)
    bench._emit_final()
    capsys.readouterr()
    doc = json.loads(dst.read_text())
    assert doc["headline"]["rate_samples_per_sec_per_chip"] \
        == 9_999_999.0


def test_sentinel_widened_cohort_excludes_chaos_rows(tmp_path):
    """ISSUE 10 satellite: chaos-drill rows must never lend their band
    to a real cohort just because the exact history is thin — nor can
    a real band judge a chaos leg."""
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    # The only leg-wide history is chaos-drill rows at a crippled rate.
    for i, v in enumerate([100.0, 105.0, 95.0, 102.0, 99.0]):
        led.append({
            "kind": "bench_leg", "leg": "legA", "run_id": f"c{i}",
            "value": v,
            "fingerprint": measurement_fingerprint(
                variant="a", model="fm", chaos=True),
        })
    fp_real = measurement_fingerprint(variant="brand-new", model="fm")
    block = Sentinel(led).judge("legA", 1_000_000.0, fp_real)
    # Widening found nothing comparable: insufficient history, NOT an
    # "improved" verdict against the chaos band.
    assert block["verdict"] == "insufficient_history"
    # And a chaos measurement judges against the chaos band only.
    fp_chaos = measurement_fingerprint(variant="a", model="fm",
                                       chaos=True)
    chaos_block = Sentinel(led).judge("legA", 101.0, fp_chaos)
    assert chaos_block["cohort"] == "exact"
    assert chaos_block["verdict"] == "flat"


def test_emit_final_gate_refuses_chaos_stamped_payload(tmp_path,
                                                       monkeypatch,
                                                       capsys):
    """ISSUE 10 satellite: a chaos-drill leg — even TPU-stamped,
    numerically better, sentinel-improved — must never pass the
    keep-best gate into MEASURED.json."""
    import fm_spark_tpu.measured as measured

    src = os.path.join(REPO, "MEASURED.json")
    dst = tmp_path / "MEASURED.json"
    dst.write_bytes(open(src, "rb").read())
    monkeypatch.setattr(measured, "MEASURED_PATH", str(dst))

    bench = _load_bench()
    line = json.dumps({
        "metric": bench.METRIC, "value": 9_999_999.0,
        "unit": bench.UNIT, "vs_baseline": 8.0,
        "variant": "bfloat16/dedup_sr/compact12288/cd-bf16/gfull"
                   "/segtotal",
        "device": "TPU v5 lite",
        "chaos": True,
        "sentinel": {"verdict": "improved", "reason": "test", "z": 9.0,
                     "n_history": 6},
    })
    before = dst.read_bytes()
    with bench._SALVAGE_LOCK:
        bench._SALVAGE.update(line=line, emitted=False)
    bench._emit_final()
    assert dst.read_bytes() == before, (
        "a chaos-stamped payload overwrote MEASURED.json")
    assert json.loads(capsys.readouterr().out)["value"] == 9_999_999.0


def test_quality_eval_drifting_auc_series_verdicts(tmp_path):
    """ISSUE 13 satellite: the sentinel over a quality_eval cohort —
    a healthy AUC plateau reads flat, the label-flip collapse reads
    regressed (it is a QUALITY regression, not weather), and the
    cohort never mixes with bench legs."""
    led = PerfLedger(str(tmp_path / "l.jsonl"))
    fp = measurement_fingerprint(variant="quality/demo/ftrl",
                                 model="fm")
    plateau = [0.712, 0.708, 0.715, 0.711, 0.709, 0.713]
    for i, auc in enumerate(plateau):
        led.append({"kind": "quality_eval", "leg": "quality/demo",
                    "run_id": f"d{i}", "value": auc,
                    "fingerprint": fp})
    s = Sentinel(led)
    assert s.judge("quality/demo", 0.710, fp)["verdict"] == "flat"
    drift = s.judge("quality/demo", 0.33, fp)
    assert drift["verdict"] == "regressed"
    assert drift["z"] < -3
    # Same drop under adverse attachment weather would be transient —
    # but quality evals run on-host; healthy weather keeps it real.
    fp_flaky = measurement_fingerprint(variant="quality/demo/ftrl",
                                       model="fm",
                                       attachment_health="flaky")
    assert s.judge("quality/demo", 0.33, fp_flaky)["verdict"] \
        == "attachment_transient"
    # Cohort isolation: a bench leg's history is invisible here.
    assert s.judge("bench_legZ", 0.7, fp)["verdict"] \
        == "insufficient_history"
