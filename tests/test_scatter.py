"""ops/scatter.py: dedup ≡ scatter_add, SR unbiasedness, bf16+SR quality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import apply_row_updates, stochastic_round
from fm_spark_tpu.sparse import make_field_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig


def test_dedup_matches_scatter_add_fp32():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(50, 8)), jnp.float32)
    # Heavy duplication, including ids unseen in the batch.
    ids = jnp.asarray(rng.integers(0, 20, size=200), jnp.int32)
    delta = jnp.asarray(rng.normal(size=(200, 8)) * 0.1, jnp.float32)
    a = apply_row_updates(table, ids, delta, mode="scatter_add")
    b = apply_row_updates(table, ids, delta, mode="dedup")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


def test_dedup_sr_exact_in_fp32():
    # With an fp32 table SR is the identity, so dedup_sr must equal
    # scatter_add exactly up to reassociation.
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(30, 4)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 30, size=100), jnp.int32)
    delta = jnp.asarray(rng.normal(size=(100, 4)) * 0.05, jnp.float32)
    old_rows = table[ids]
    a = apply_row_updates(table, ids, delta, mode="scatter_add")
    c = apply_row_updates(table, ids, delta, mode="dedup_sr",
                          key=jax.random.key(0), old_rows=old_rows)
    np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                               rtol=1e-5, atol=1e-6)


def test_stochastic_round_unbiased_and_lands_small_updates():
    # A delta far below bf16 ulp of 1.0 must land in expectation.
    x = jnp.full((20000,), 1.0 + 1e-4, jnp.float32)  # ulp(1.0)=2^-8
    out = stochastic_round(x, jnp.bfloat16, jax.random.key(0))
    mean = float(jnp.mean(out.astype(jnp.float32)))
    # P(round up) = 1e-4 / 2^-8 ≈ 0.0256 → mean ≈ 1.0 + 1e-4.
    assert abs(mean - (1.0 + 1e-4)) < 3e-5, mean
    # Deterministic rounding would give exactly 1.0.
    assert mean > 1.0


def test_stochastic_round_fp32_identity():
    x = jnp.asarray(np.random.default_rng(0).normal(size=64), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(stochastic_round(x, jnp.float32, jax.random.key(0))),
        np.asarray(x),
    )


def test_stochastic_round_nonfinite_and_near_max():
    # Non-finite inputs must propagate unchanged (the raw bit-add would
    # corrupt NaN payloads / inf encodings), and finite values near bf16
    # max must saturate instead of carrying over into inf.
    key = jax.random.key(0)
    bf_max = float(jnp.finfo(jnp.bfloat16).max)
    x = jnp.asarray([np.inf, -np.inf, np.nan, bf_max, -bf_max, 1.0],
                    jnp.float32)
    out = stochastic_round(x, jnp.bfloat16, key)
    o = np.asarray(out, np.float32)
    assert o[0] == np.inf and o[1] == -np.inf and np.isnan(o[2])
    assert np.isfinite(o[3]) and np.isfinite(o[4]), o
    assert o[3] == bf_max and o[4] == -bf_max
    # Bulk check: f32 values strictly between bf16-max and the next
    # exponent (the mantissa carry range) never round to inf under any
    # noise draw — they saturate.
    big = jnp.full((4096,), np.float32(bf_max) * np.float32(1.001),
                   jnp.float32)
    assert float(big[0]) > bf_max and np.isfinite(float(big[0]))
    outs = stochastic_round(big, jnp.bfloat16, jax.random.key(7))
    assert np.isfinite(np.asarray(outs, np.float32)).all()


def test_unknown_mode_raises():
    t = jnp.zeros((4, 2))
    with pytest.raises(ValueError, match="unknown sparse_update"):
        apply_row_updates(t, jnp.zeros(3, jnp.int32), jnp.zeros((3, 2)),
                          mode="nope")
    with pytest.raises(ValueError, match="needs key"):
        apply_row_updates(t, jnp.zeros(3, jnp.int32), jnp.zeros((3, 2)),
                          mode="dedup_sr")


def test_fused_step_dedup_matches_scatter_add():
    num_fields, bucket, rank = 4, 32, 4
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank, num_fields=num_fields,
        bucket=bucket, init_std=0.1,
    )
    base = TrainConfig(learning_rate=0.3, optimizer="sgd",
                       reg_factors=1e-3, reg_linear=1e-4)
    import dataclasses

    step_a = make_field_sparse_sgd_step(spec, base)
    step_b = make_field_sparse_sgd_step(
        spec, dataclasses.replace(base, sparse_update="dedup")
    )
    pa = spec.init(jax.random.key(0))
    pb = jax.tree_util.tree_map(jnp.copy, pa)
    rng = np.random.default_rng(2)
    for i in range(3):
        ids = jnp.asarray(rng.integers(0, bucket, size=(64, num_fields)),
                          jnp.int32)
        vals = jnp.asarray(rng.uniform(0.5, 1.5, (64, num_fields)),
                           jnp.float32)
        labels = jnp.asarray(rng.integers(0, 2, 64), jnp.float32)
        w = jnp.ones((64,), jnp.float32)
        pa, la = step_a(pa, jnp.int32(i), ids, vals, labels, w)
        pb, lb = step_b(pb, jnp.int32(i), ids, vals, labels, w)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    for f in range(num_fields):
        np.testing.assert_allclose(
            np.asarray(pa["vw"][f]), np.asarray(pb["vw"][f]),
            rtol=1e-4, atol=1e-6,
        )


def test_update_rows_add_matches_scatter_add_on_duplicate_ids():
    """ISSUE 8 property test: the Pallas unique-row RMW
    (ops/pallas_fm.update_rows_add), fed the deduped per-segment sums a
    fused step would feed it, writes EXACTLY the table the plain
    scatter-add reference produces — on duplicate-heavy batches, the
    dedup/dedup_sr variants' exact aliasing case. Integer-valued deltas
    make both paths' sums exact, so equality is bitwise, not tolerance
    (any aliasing bug — a duplicate id written twice, a dropped
    segment — shifts a row by >= 1.0)."""
    from fm_spark_tpu.ops import pallas_fm
    from fm_spark_tpu.ops.scatter import _dedup

    for seed in range(5):
        rng = np.random.default_rng(seed)
        b = 256
        n_rows = int(rng.integers(8, 64))
        w = int(rng.integers(2, 10))
        table = jnp.asarray(
            rng.integers(-50, 50, size=(n_rows, w)).astype(np.float32))
        # Zipf-heavy duplication: many batch lanes alias few rows.
        ids = jnp.asarray(rng.zipf(1.2, size=b) % n_rows, jnp.int32)
        delta = jnp.asarray(
            rng.integers(-8, 8, size=(b, w)).astype(np.float32))

        want = apply_row_updates(table, ids, delta, mode="scatter_add")

        # The fused-step feed: segment-sum duplicates, then one
        # unique-lane Pallas RMW (bench_kernels' update family).
        sid, summed, run_start, _order = jax.jit(_dedup)(ids, delta)
        uids = jnp.where(run_start, sid, 0)
        valid = run_start.astype(jnp.int32)
        got = pallas_fm.update_rows_add(
            jnp.copy(table), uids, valid, summed, interpret=True)
        np.testing.assert_array_equal(
            np.asarray(got), np.asarray(want),
            err_msg=f"seed={seed} rows={n_rows} w={w}")


def test_compact_apply_totals_matches_compact_apply_write():
    """The fused backward's write half (compact_apply_totals) against
    compact_apply fed the same totals through its own segment-sum: the
    two entrances to _compact_write must land identical tables (dedup)
    and identical SR draws (dedup_sr), or the fused path would fork the
    update semantics."""
    from fm_spark_tpu.ops.scatter import (
        compact_apply,
        compact_apply_totals,
        compact_aux,
        compact_gather,
        sr_key,
    )

    rng = np.random.default_rng(7)
    b, n_rows, w, cap = 512, 40, 6, 48
    ids = rng.integers(0, n_rows, size=(b, 1)).astype(np.int32)
    aux = compact_aux(ids, cap)
    caux = tuple(jnp.asarray(a[0]) for a in aux)
    useg, _, _, order, inv = caux
    table = jnp.asarray(
        rng.integers(-20, 20, size=(n_rows, w)).astype(np.float32))
    delta = jnp.asarray(
        rng.integers(-4, 4, size=(b, w)).astype(np.float32))
    urows = compact_gather(table, useg)

    # Totals exactly as the fused backward emits them: per-segment sums
    # of the sorted deltas (integer-valued, so the sum path is exact).
    sdelta = np.asarray(delta)[np.asarray(order)]
    seg = np.asarray(inv)[np.asarray(order)]
    totals = np.zeros((cap, w), np.float32)
    np.add.at(totals, seg, sdelta)
    totals = jnp.asarray(totals)

    a = compact_apply(table, delta, caux, "dedup", None, urows)
    t = compact_apply_totals(table, totals, caux, "dedup", None, urows)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(t))

    key = sr_key(jax.random.key(3), 0, 0)
    a = compact_apply(table, delta, caux, "dedup_sr", key, urows)
    t = compact_apply_totals(table, totals, caux, "dedup_sr", key, urows)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(t))
