"""fmlint framework + rule suite (ISSUE 15).

Covers: the registry/driver, inline suppressions (reason REQUIRED),
the baseline add/burn-down round trip, a synthetic positive AND
negative fixture for EVERY registered rule (the meta-test applies the
PR-10 fault-coverage pattern to the linter itself: a rule with no
firing fixture is a rule that can rot silently), the shipped-repo
zero-unbaselined gate (the tier-1 wiring), and subprocess drills that
prove the thread-safety and JAX-hazard passes catch seeded synthetic
violations through the real CLI.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from fm_spark_tpu import analysis
from fm_spark_tpu.analysis import core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FMLINT = os.path.join(REPO, "tools", "fmlint.py")


def write_tree(root, files: dict):
    """Materialize ``{relpath: source}`` under ``root``."""
    for rel, src in files.items():
        path = root / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return str(root)


def run_rule(root, rule_id):
    ctx = core.Context(str(root))
    found, suppressed = core.run_rules(ctx, rules=[rule_id])
    return found, suppressed


# ----------------------------------------------------------------- fixtures
#
# One (positive, negative) fixture pair per registered rule. The
# meta-test below asserts this table covers the registry EXACTLY, so a
# new rule cannot ship without a firing fixture.

FIXTURES = {
    "parse-error": {
        "positive": {"fm_spark_tpu/broken.py": "def f(:\n"},
        "negative": {"fm_spark_tpu/fine.py": "def f():\n    return 1\n"},
        "expect": 1,
    },
    "eventlog-only": {
        "positive": {"fm_spark_tpu/resilience/bad.py": """\
            import json, sys
            def transition(state):
                print('circuit open')
                sys.stderr.write('backing off\\n')
                with open('events.json', 'w') as f:
                    json.dump({'event': 'backoff'}, f)
                return json.dumps(state)
        """},
        "negative": {"fm_spark_tpu/resilience/good.py": """\
            def transition(journal, state):
                journal.emit('backoff', state=state)
        """},
        "expect": 4,
    },
    "bare-print": {
        "positive": {"fm_spark_tpu/mod.py": """\
            def f():
                print('narration')
        """},
        "negative": {
            "fm_spark_tpu/mod.py": """\
                import sys
                def f(stream):
                    print('directed', file=stream)
            """,
            # CLI stdout IS the interface — exempt.
            "fm_spark_tpu/cli.py": "print('usage: ...')\n",
        },
        "expect": 1,
    },
    "pallas-fallback": {
        "positive": {"fm_spark_tpu/ops/pallas_bad.py": """\
            def kernel(x):
                assert x.ndim == 2
                raise ValueError('bad shape')
        """},
        "negative": {
            "fm_spark_tpu/ops/pallas_good.py": """\
                from fm_spark_tpu.ops import PallasUnavailable
                def kernel(x):
                    raise PallasUnavailable('no TPU lowering')
            """,
            # Non-kernel module in ops/: asserts stay legal.
            "fm_spark_tpu/ops/util.py": "def f(x):\n    assert x\n",
        },
        "expect": 2,
    },
    "wallclock-duration": {
        "positive": {"fm_spark_tpu/dur.py": """\
            import time
            import time as t
            from time import time as now
            def measure(t0, t1):
                a = time.time() - t0
                b = t1 - t.time()
                c = now() - t0
                t1 -= time.time()
                return a, b, c
        """},
        "negative": {"fm_spark_tpu/dur.py": """\
            import time
            def measure(t0):
                ok = {'ts': time.time()}       # timestamp: legal
                ok2 = time.perf_counter() - t0  # monotonic: legal
                return ok, ok2
        """},
        "expect": 4,
    },
    "leg-provenance": {
        "positive": {"bench.py":
                     "leg_record = {'variant': 'x', 'value': 1.0}\n"},
        "negative": {"bench.py": """\
            leg_record = {'variant': 'x', 'value': 1.0,
                          'run_id': rid, 'fingerprint': fp}
        """},
        "expect": 1,
    },
    "registry-coverage": {
        "positive": {
            "fm_spark_tpu/resilience/faults.py":
                'KNOWN_POINTS = ("train_step", "brand_new_point")\n',
            "fm_spark_tpu/resilience/watchdog.py":
                'KNOWN_PHASES = ("step_window",)\n',
            "fm_spark_tpu/obs/introspect.py":
                'TRIGGERS = ("step_time_spike",)\n',
            "tests/test_x.py": """\
                def test_a():
                    assert "train_step" and "step_window"
                    assert "step_time_spike"
            """,
        },
        "negative": {
            "fm_spark_tpu/resilience/faults.py":
                'KNOWN_POINTS = ("train_step",)\n',
            "fm_spark_tpu/resilience/watchdog.py":
                'KNOWN_PHASES = ("step_window",)\n',
            "fm_spark_tpu/obs/introspect.py":
                'TRIGGERS = ("step_time_spike",)\n',
            "tests/test_x.py": """\
                def test_a():
                    assert "train_step" and "step_window"
                    assert "step_time_spike"
            """,
        },
        "expect": 1,
    },
    "trace-propagation": {
        "positive": {"fm_spark_tpu/serve/bad.py": """\
            import http.client
            def dispatch(port, body):
                conn = http.client.HTTPConnection('127.0.0.1', port)
                conn.request('POST', '/predict', body=body)
                return conn.getresponse()
        """},
        "negative": {
            "fm_spark_tpu/serve/good.py": """\
                import http.client
                def dispatch(port, body, trace):
                    conn = http.client.HTTPConnection('127.0.0.1', port)
                    headers = {'X-FM-Trace': trace.to_header()}
                    conn.request('POST', '/predict', body=body,
                                 headers=headers)
                    return conn.getresponse()
                def dispatch_by_name(port, body, trace, obs):
                    conn = http.client.HTTPConnection('127.0.0.1', port)
                    conn.request('POST', '/x', body=body,
                                 headers={obs.TRACE_HEADER: trace})
                    return conn.getresponse()
            """,
            # Off the serve/ request path: out of scope.
            "fm_spark_tpu/other.py": """\
                def fetch(conn):
                    conn.request('GET', '/healthz')
            """,
        },
        "expect": 1,
    },
    "fleet-transport-discipline": {
        "positive": {"fm_spark_tpu/serve/bad.py": """\
            import http.client, socket
            def dial(host, port):
                c = http.client.HTTPConnection(host, port)
                s = socket.create_connection((host, port))
                return c, s
        """},
        "negative": {
            "fm_spark_tpu/serve/good.py": """\
                from fm_spark_tpu.resilience import netfaults
                def dial(host, port, peer):
                    return netfaults.FaultyHTTPConnection(
                        host, port, peer=peer)
            """,
            # User-side of the trust boundary: reasoned suppression.
            "fm_spark_tpu/serve/client.py": """\
                import http.client
                def attempt(host, port):
                    return http.client.HTTPConnection(host, port)  # fmlint: disable=fleet-transport-discipline -- models a CLIENT outside the fleet transport boundary
            """,
            # Outside serve/: out of scope.
            "fm_spark_tpu/resilience/nf.py": """\
                import http.client
                def dial(host, port):
                    return http.client.HTTPConnection(host, port)
            """,
        },
        "expect": 2,
    },
    "suppression-hygiene": {
        "positive": {"fm_spark_tpu/mod.py": """\
            def f():
                x = 1  # fmlint: disable=bare-print
                y = 2  # fmlint: disable=no-such-rule -- because
                return x + y
        """},
        "negative": {"fm_spark_tpu/mod.py": """\
            def f():
                print('x')  # fmlint: disable=bare-print -- demo reason
        """},
        "expect": 2,
    },
    "thread-lock-discipline": {
        "positive": {"fm_spark_tpu/worker.py": """\
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._t = None
                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()
                def _run(self):
                    while True:
                        self._count += 1     # unlocked thread write
                def read(self):
                    return self._count       # unlocked cross-domain read
        """},
        "negative": {"fm_spark_tpu/worker.py": """\
            import threading
            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0
                    self._q = __import__('queue').Queue()
                    self._t = None
                def start(self):
                    self._t = threading.Thread(
                        target=self._run, daemon=True)
                    self._t.start()
                def _run(self):
                    while True:
                        with self._lock:
                            self._count += 1
                        self._q.put(1)       # Queue: inherently safe
                def read(self):
                    with self._lock:
                        return self._count
        """},
        "expect": 2,
    },
    "thread-lifecycle": {
        # os.path.join / "".join in scope must NOT count as a thread
        # join (the rule would be near-vacuous on real code otherwise).
        "positive": {"fm_spark_tpu/spawn.py": """\
            import os
            import threading
            class Spawner:
                def start(self):
                    self._log = os.path.join('/tmp', 'x.log')
                    self._csv = ",".join(["a", "b"])
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
                def _run(self):
                    pass
        """},
        "negative": {"fm_spark_tpu/spawn.py": """\
            import threading
            class Daemonized:
                def start(self):
                    self._t = threading.Thread(target=self._run,
                                               daemon=True)
                    self._t.start()
                def _run(self):
                    pass
            class Joined:
                def start(self):
                    self._t = threading.Thread(target=self._run)
                    self._t.start()
                def close(self):
                    self._t.join(timeout=5)
                def _run(self):
                    pass
            def probe():
                t = threading.Timer(1.0, lambda: None)
                t.daemon = True
                t.start()
        """},
        "expect": 1,
    },
    "jax-host-sync": {
        # Only HOT_FILES are scanned — the fixture plants a fake
        # train.py; the same code in another module is the negative.
        "positive": {"fm_spark_tpu/train.py": """\
            import numpy as np
            def fit(step, batches):
                for b in batches:
                    out = step(b)
                    loss = float(out['loss'])
                    arr = np.asarray(out['grad'])
                    s = out['metric'].item()
                    out['x'].block_until_ready()
                return loss, arr, s
        """},
        "negative": {
            "fm_spark_tpu/train.py": """\
                import jax.numpy as jnp
                def fit(step, batches):
                    for b in batches:
                        out = step(jnp.asarray(b))   # device-side: legal
                    return {k: float(v) for k, v in out.items()}
            """,
            # Same syncs OFF the hot-file list: legal.
            "fm_spark_tpu/report.py": """\
                import numpy as np
                def summarize(rows):
                    for r in rows:
                        x = float(r['v'])
                    return x
            """,
        },
        "expect": 4,
    },
    "jax-jit-side-effect": {
        "positive": {"fm_spark_tpu/steps.py": """\
            import jax
            @jax.jit
            def step(x):
                print('tracing')
                return x * 2
            def inner(x):
                journal.emit('oops', x=1)
                return x
            compiled = jax.jit(inner)
        """},
        "negative": {"fm_spark_tpu/steps.py": """\
            import jax
            @jax.jit
            def step(x):
                return x * 2
            def outer(x):
                print('host side, not jitted')
                return step(x)
        """},
        "expect": 2,
    },
    "jax-unfenced-timing": {
        "positive": {"fm_spark_tpu/train.py": """\
            import time
            def fit(step_fn, batches):
                for b in batches:
                    t0 = time.perf_counter()
                    out = step_fn(b)
                    dt = time.perf_counter() - t0
                return out, dt
        """},
        "negative": {"fm_spark_tpu/train.py": """\
            import time, jax
            def fit(step_fn, batches):
                for b in batches:
                    t0 = time.perf_counter()
                    out = step_fn(b)
                    jax.block_until_ready(out)
                    dt = time.perf_counter() - t0
                return out, dt
        """},
        "expect": 1,
    },
    "durable-write-discipline": {
        # Raw overwrite-opens and raw renames on the durable surface
        # (obs/, embed/, checkpoint.py) bypass the io-fault seam.
        "positive": {
            "fm_spark_tpu/obs/sink.py": """\
                import json, os
                def publish(path, doc):
                    with open(path + '.tmp', 'w') as f:
                        json.dump(doc, f)
                    os.replace(path + '.tmp', path)
            """,
            "fm_spark_tpu/checkpoint.py": """\
                def stamp(path):
                    with open(path, mode='wb') as f:
                        f.write(b'x')
            """,
        },
        "negative": {
            # The seam itself, appends, reads, and non-literal modes
            # are all legal — and the same raw write OUTSIDE the
            # durable surface is out of scope.
            "fm_spark_tpu/obs/sink.py": """\
                from fm_spark_tpu.utils import durable
                def publish(path, doc, line, mode):
                    durable.atomic_write_json(path, doc,
                                              path_class='obs')
                    durable.append_line_path(path, line,
                                             path_class='obs')
                    with open(path) as f:
                        body = f.read()
                    with open(path, 'a') as f:
                        f.write(line)
                    with open(path, mode) as f:
                        f.write(line)
                    return body
            """,
            "fm_spark_tpu/tools_helper.py": """\
                def scratch(path):
                    with open(path, 'w') as f:
                        f.write('not a durability promise')
            """,
        },
        "expect": 3,
    },
}


# ---------------------------------------------------------------- framework

def test_registry_has_rules_and_glossary():
    rules = analysis.all_rules()
    assert len(rules) >= 12
    for r in rules:
        assert r.doc, f"rule {r.id} has no glossary doc"
    # The six monolith rules all migrated.
    migrated = {"eventlog-only", "bare-print", "pallas-fallback",
                "wallclock-duration", "leg-provenance",
                "registry-coverage"}
    assert migrated <= {r.id for r in rules}


def test_rule_decorator_rejects_duplicates_and_bad_ids():
    with pytest.raises(ValueError, match="kebab-case"):
        core.rule("Bad_Id", "x")(lambda ctx: [])
    with pytest.raises(ValueError, match="duplicate"):
        core.rule("bare-print", "x")(lambda ctx: [])


def test_finding_render_and_location():
    f = core.Finding("bare-print", "fm_spark_tpu/m.py", 3, "msg", "f")
    assert f.location == "fm_spark_tpu/m.py:3"
    assert f.render() == "fm_spark_tpu/m.py:3 [f] bare-print: msg"
    assert f.to_dict()["rule"] == "bare-print"


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_fires_on_positive_fixture(rule_id, tmp_path):
    fx = FIXTURES[rule_id]
    write_tree(tmp_path, fx["positive"])
    found, _ = run_rule(tmp_path, rule_id)
    assert len(found) == fx["expect"], \
        f"{rule_id}: {[f.render() for f in found]}"
    assert all(f.rule == rule_id for f in found)
    assert all(f.line >= 1 and f.path for f in found)


@pytest.mark.parametrize("rule_id", sorted(FIXTURES))
def test_rule_quiet_on_negative_fixture(rule_id, tmp_path):
    fx = FIXTURES[rule_id]
    write_tree(tmp_path, fx["negative"])
    found, _ = run_rule(tmp_path, rule_id)
    assert found == [], f"{rule_id}: {[f.render() for f in found]}"


def test_every_registered_rule_has_a_firing_fixture():
    """The PR-10 fault-coverage pattern applied to the linter itself:
    the fixture table must cover the registry EXACTLY — a rule with no
    positive fixture is a rule whose detection can rot silently."""
    assert set(FIXTURES) == {r.id for r in analysis.all_rules()}


# ------------------------------------------------------------- suppressions

def test_reasoned_suppression_suppresses_and_is_recorded(tmp_path):
    write_tree(tmp_path, {"fm_spark_tpu/m.py": """\
        def f():
            print('x')  # fmlint: disable=bare-print -- CLI-adjacent demo path, narration is the contract here
    """})
    ctx = core.Context(str(tmp_path))
    found, suppressed = core.run_rules(
        ctx, rules=["bare-print", "suppression-hygiene"])
    assert found == []
    assert len(suppressed) == 1
    f, reason = suppressed[0]
    assert f.rule == "bare-print" and "narration" in reason


def test_bare_suppression_does_not_suppress_and_is_a_finding(tmp_path):
    write_tree(tmp_path, {"fm_spark_tpu/m.py": """\
        def f():
            print('x')  # fmlint: disable=bare-print
    """})
    found, suppressed = core.run_rules(
        core.Context(str(tmp_path)),
        rules=["bare-print", "suppression-hygiene"])
    assert suppressed == []
    rules = sorted(f.rule for f in found)
    assert rules == ["bare-print", "suppression-hygiene"]


def test_suppression_only_silences_the_named_rule(tmp_path):
    # A wallclock violation suppressed under the WRONG rule id stays.
    write_tree(tmp_path, {"fm_spark_tpu/m.py": """\
        import time
        def f(t0):
            return time.time() - t0  # fmlint: disable=bare-print -- wrong rule named
    """})
    found, suppressed = core.run_rules(
        core.Context(str(tmp_path)), rules=["wallclock-duration"])
    assert len(found) == 1 and suppressed == []


def test_suppression_hygiene_is_never_suppressible(tmp_path):
    write_tree(tmp_path, {"fm_spark_tpu/m.py": (
        "x = 1  # fmlint: disable=suppression-hygiene,no-such -- sneaky\n"
    )})
    found, suppressed = core.run_rules(
        core.Context(str(tmp_path)), rules=["suppression-hygiene"])
    assert len(found) == 1 and suppressed == []
    assert "no-such" in found[0].message


# ----------------------------------------------------------------- baseline

def _one_violation_repo(tmp_path):
    return write_tree(tmp_path, {"fm_spark_tpu/m.py": (
        "def f():\n    print('x')\n")})


def test_baseline_round_trip_and_burn_down(tmp_path):
    repo = _one_violation_repo(tmp_path)
    bl = str(tmp_path / "baseline.json")
    rules = ["bare-print"]
    # 1. Fresh repo, empty baseline: the finding is NEW -> not ok.
    rep = core.analyze(repo, baseline_path=bl, rules=rules)
    assert not rep["ok"] and len(rep["new"]) == 1
    # 2. Absorb it.
    ctx = core.Context(repo)
    findings, _ = core.run_rules(ctx, rules=rules)
    core.write_baseline(bl, findings)
    doc = json.load(open(bl))
    assert doc["counts"]["bare-print"]["fm_spark_tpu/m.py"] == 1
    # 3. Same repo now passes, finding tracked as baselined.
    rep = core.analyze(repo, baseline_path=bl, rules=rules)
    assert rep["ok"] and rep["baselined_total"] == 1
    assert rep["new"] == [] and rep["burned_down"] == []
    # 4. A SECOND finding in the same file exceeds the cell -> fails.
    (tmp_path / "fm_spark_tpu" / "m.py").write_text(
        "def f():\n    print('x')\n    print('y')\n")
    rep = core.analyze(repo, baseline_path=bl, rules=rules)
    assert not rep["ok"] and len(rep["new"]) == 2  # whole cell listed
    # 5. Fixing ALL of them reports burn-down, still ok.
    (tmp_path / "fm_spark_tpu" / "m.py").write_text(
        "def f():\n    return 1\n")
    rep = core.analyze(repo, baseline_path=bl, rules=rules)
    assert rep["ok"] and rep["new"] == []
    assert rep["burned_down"] == [{
        "rule": "bare-print", "path": "fm_spark_tpu/m.py",
        "baseline": 1, "current": 0}]


def test_missing_baseline_means_empty(tmp_path):
    assert core.load_baseline(str(tmp_path / "nope.json")) == {}
    (tmp_path / "junk.json").write_text("{not json")
    assert core.load_baseline(str(tmp_path / "junk.json")) == {}


def test_baseline_never_hides_a_new_rule_file_cell(tmp_path):
    repo = _one_violation_repo(tmp_path)
    bl = str(tmp_path / "baseline.json")
    # Baseline holds a DIFFERENT file's debt: this file still fails.
    json.dump({"version": 1, "counts": {
        "bare-print": {"fm_spark_tpu/other.py": 3}}}, open(bl, "w"))
    rep = core.analyze(repo, baseline_path=bl, rules=["bare-print"])
    assert not rep["ok"] and len(rep["new"]) == 1


# ------------------------------------------------------------------- report

def test_report_shape_and_write(tmp_path):
    repo = _one_violation_repo(tmp_path)
    rep = core.analyze(repo, rules=["bare-print"], run_id="r-test")
    assert rep["tool"] == "fmlint" and rep["run_id"] == "r-test"
    assert rep["counts"] == {"bare-print": {"fm_spark_tpu/m.py": 1}}
    assert "bare-print" in rep["rules"]  # glossary rides the report
    out = core.write_report(rep, str(tmp_path / "obs" / "r-test"))
    assert out and os.path.basename(out) == "fmlint.json"
    loaded = json.load(open(out))
    assert loaded["total_findings"] == 1 and not loaded["ok"]


# --------------------------------------------------- the tier-1 repo gate

def test_shipped_repo_has_zero_unbaselined_findings():
    """THE gate (acceptance criterion): the full rule set over the real
    repo, against the committed baseline — an unbaselined finding
    anywhere turns tier-1 red."""
    rep = core.analyze(REPO)
    lines = "\n".join(
        f"{f['path']}:{f['line']} {f['rule']}: {f['message']}"
        for f in rep["new"])
    assert rep["ok"], f"unbaselined fmlint findings:\n{lines}"


def test_shipped_suppressions_all_carry_reasons():
    rep = core.analyze(REPO)
    assert rep["suppressed"], "expected the documented lock-free/" \
        "fence suppressions to be visible in the report"
    for s in rep["suppressed"]:
        assert s["reason"].strip()


# ------------------------------------------------------------ CLI (tier-1)

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, FMLINT, *args],
        capture_output=True, text=True, cwd=REPO)


def test_cli_clean_on_shipped_repo(tmp_path):
    p = _run_cli("--out", str(tmp_path))
    assert p.returncode == 0, p.stderr
    rep = json.load(open(tmp_path / "fmlint.json"))
    assert rep["ok"] and rep["new"] == []
    assert rep["run_id"].startswith("fmlint-")


def test_cli_list_rules():
    p = _run_cli("--list-rules")
    assert p.returncode == 0
    for r in analysis.all_rules():
        assert r.id in p.stdout


def test_cli_unknown_rule_is_usage_error():
    p = _run_cli("--rules", "no-such-rule")
    assert p.returncode == 2


def test_cli_catches_seeded_thread_safety_violation(tmp_path):
    """Acceptance criterion: the thread-safety pass demonstrably
    catches a seeded synthetic violation through the real CLI in a
    subprocess."""
    repo = write_tree(tmp_path, FIXTURES["thread-lock-discipline"]
                      ["positive"])
    p = _run_cli("--repo", repo, "--rules", "thread-lock-discipline",
                 "--no-report")
    assert p.returncode == 1
    assert "thread-lock-discipline" in p.stderr
    assert "_count" in p.stderr


def test_cli_catches_seeded_jax_hazard_violation(tmp_path):
    """Acceptance criterion, JAX half: a seeded host sync in a step
    loop fails the CLI run."""
    repo = write_tree(
        tmp_path, FIXTURES["jax-host-sync"]["positive"])
    p = _run_cli("--repo", repo, "--rules",
                 "jax-host-sync,jax-unfenced-timing", "--no-report")
    assert p.returncode == 1
    assert "jax-host-sync" in p.stderr


def test_cli_write_baseline_round_trip(tmp_path):
    repo = write_tree(tmp_path, {"fm_spark_tpu/m.py":
                                 "def f():\n    print('x')\n"})
    bl = str(tmp_path / "fmlint_baseline.json")
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--baseline", bl, "--no-report")
    assert p.returncode == 1
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--baseline", bl, "--write-baseline")
    assert p.returncode == 0, p.stderr
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--baseline", bl, "--no-report")
    assert p.returncode == 0
    assert "1 baselined" in p.stderr


def test_cli_write_baseline_with_rules_subset_merges(tmp_path):
    """--write-baseline under a --rules subset rewrites ONLY the
    selected rules' cells — a targeted run must never erase another
    rule's baselined debt (post-review regression)."""
    repo = write_tree(tmp_path, {"fm_spark_tpu/m.py": (
        "def f():\n    print('x')\n")})
    bl = str(tmp_path / "fmlint_baseline.json")
    json.dump({"version": 1, "counts": {
        "jax-host-sync": {"fm_spark_tpu/train.py": 4}}}, open(bl, "w"))
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--baseline", bl, "--write-baseline")
    assert p.returncode == 0, p.stderr
    counts = json.load(open(bl))["counts"]
    assert counts["jax-host-sync"] == {"fm_spark_tpu/train.py": 4}
    assert counts["bare-print"] == {"fm_spark_tpu/m.py": 1}
    # Paying the selected rule's debt down and re-absorbing drops its
    # cells but still leaves the unselected rule's ledger intact.
    (tmp_path / "fm_spark_tpu" / "m.py").write_text("x = 1\n")
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--baseline", bl, "--write-baseline")
    assert p.returncode == 0, p.stderr
    counts = json.load(open(bl))["counts"]
    assert counts == {"jax-host-sync": {"fm_spark_tpu/train.py": 4}}


def test_cli_report_lands_in_obs_layout(tmp_path):
    """Default report path is artifacts/obs/<run_id>/fmlint.json —
    exercised against a synthetic repo so the real artifacts/ tree
    stays untouched by tests."""
    repo = write_tree(tmp_path, {"fm_spark_tpu/ok.py": "x = 1\n"})
    p = _run_cli("--repo", repo, "--rules", "bare-print",
                 "--run-id", "r-fmlint-test")
    assert p.returncode == 0
    path = (tmp_path / "artifacts" / "obs" / "r-fmlint-test"
            / "fmlint.json")
    assert path.is_file()
    assert json.load(open(path))["run_id"] == "r-fmlint-test"
