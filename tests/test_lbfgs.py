"""FMWithLBFGS / fit_lbfgs: convergence, regularization, compat surface."""

import jax
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.compat import FMWithLBFGS, FMWithSGD, evaluate
from fm_spark_tpu.data import synthetic_ctr
from fm_spark_tpu.lbfgs import fit_lbfgs
from fm_spark_tpu.train import TrainConfig


def test_lbfgs_drives_loss_down_on_planted_fm():
    ids, vals, labels = synthetic_ctr(2000, 200, 4, rank=3, seed=1)
    spec = models.FMSpec(num_features=200, rank=4, init_std=0.05)
    params0 = spec.init(jax.random.key(0))
    from fm_spark_tpu.lbfgs import make_objective

    obj = make_objective(
        spec, TrainConfig(),
        np.asarray(ids), np.asarray(vals), np.asarray(labels),
        np.ones(labels.shape, np.float32),
    )
    before = float(obj(params0))
    params, info = fit_lbfgs(
        spec, params0, ids, vals, labels, num_iterations=60,
    )
    assert info["loss"] < before - 0.05
    assert np.isfinite(info["grad_norm"])
    assert 1 <= info["iterations"] <= 60


def test_lbfgs_convergence_tol_stops_early():
    ids, vals, labels = synthetic_ctr(500, 100, 3, seed=2)
    spec = models.FMSpec(num_features=100, rank=2, init_std=0.05)
    params, info = fit_lbfgs(
        spec, spec.init(jax.random.key(0)), ids, vals, labels,
        num_iterations=500, convergence_tol=1e-2,
    )
    assert info["iterations"] < 500


@pytest.mark.slow
def test_lbfgs_regularization_shrinks_weights():
    ids, vals, labels = synthetic_ctr(1000, 100, 3, seed=3)
    spec = models.FMSpec(num_features=100, rank=3, init_std=0.05)
    p0 = spec.init(jax.random.key(0))
    free, _ = fit_lbfgs(spec, p0, ids, vals, labels, num_iterations=40)
    reg, _ = fit_lbfgs(
        spec, p0, ids, vals, labels, num_iterations=40,
        config=TrainConfig(reg_linear=1.0, reg_factors=1.0),
    )
    assert float(np.square(reg["v"]).sum()) < float(np.square(free["v"]).sum())
    assert float(np.square(reg["w"]).sum()) < float(np.square(free["w"]).sum())


@pytest.mark.slow
def test_compat_fmwithlbfgs_beats_chance_and_roughly_matches_sgd():
    data = synthetic_ctr(3000, 150, 4, rank=3, seed=4)
    m_lbfgs = FMWithLBFGS.train(
        data, numIterations=50, dim=(True, True, 4), regParam=(0, 1e-4, 1e-4)
    )
    auc_lbfgs = evaluate(m_lbfgs, data)["auc"]
    m_sgd = FMWithSGD.train(
        data, numIterations=300, stepSize=0.5, miniBatchFraction=0.2,
        dim=(True, True, 4),
    )
    auc_sgd = evaluate(m_sgd, data)["auc"]
    assert auc_lbfgs > 0.65
    assert auc_lbfgs > auc_sgd - 0.05  # same model class, same ballpark


@pytest.mark.slow
def test_compat_fmwithlbfgs_regression_clips():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50, size=(400, 3)).astype(np.int32)
    vals = np.ones(ids.shape, np.float32)
    labels = rng.uniform(1.0, 5.0, 400).astype(np.float32)
    model = FMWithLBFGS.train(
        (ids, vals, labels), task="regression", numIterations=30
    )
    preds = model.predict(ids, vals)
    assert preds.min() >= 1.0 - 1e-5
    assert preds.max() <= 5.0 + 1e-5


def test_dim_flags_respected():
    data = synthetic_ctr(500, 80, 3, seed=5)
    model = FMWithLBFGS.train(data, numIterations=10, dim=(False, False, 2))
    assert float(np.asarray(model.params["w0"])) == 0.0
    assert float(np.abs(np.asarray(model.params["w"])).max()) == 0.0
