"""Pin the bucket hash's collision curve to the uniform expectation.

ISSUE 16's hash-audit satellite: the bench_embed ladder's quality claim
rests on ``murmur3_u64(token) % m`` behaving like a uniform hash at
every decade of the feature axis. ``tools/hash_audit.py`` measures the
per-decade collision rate; this test pins the measurement to the
analytic expected curve so the claim is continuously CHECKED — a hash
regression (or a broken murmur re-implementation) fails tier-1, it
does not quietly degrade the 1B rung.
"""

import importlib.util
import json
import math
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "hash_audit_tool", os.path.join(REPO, "tools", "hash_audit.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tool():
    return _load_tool()


def test_expected_curve_matches_closed_form(tool):
    # n=2 tokens, m=4 buckets: the second token collides w.p. 1/4, so
    # the expected colliding fraction is exactly 0.25/2 = 0.125.
    assert tool.expected_collision_fraction(2, 4) == pytest.approx(0.125)


def test_expected_curve_matches_poisson_model_at_ladder_decades(tool):
    """Independent derivation: with Poisson(n/m) bucket occupancy the
    expected colliding fraction is ``1 − (m/n)(1 − e^{−n/m})``. The
    tool's exact binomial curve must agree at every ladder decade."""
    n = 1_000_000
    for m in tool.DECADES:
        poisson = 1.0 - (m / n) * (1.0 - math.exp(-n / m))
        assert tool.expected_collision_fraction(n, m) == pytest.approx(
            poisson, rel=1e-3)
    # And the small-load approximation n/(2m) anchors the magnitudes
    # the PERF.md round-20 note quotes: ~5% at 10M, ~0.05% at 1B.
    assert tool.expected_collision_fraction(n, 10 ** 7) == pytest.approx(
        0.05, rel=0.05)
    assert tool.expected_collision_fraction(n, 10 ** 9) == pytest.approx(
        5e-4, rel=0.05)


def test_measured_collisions_track_expectation_per_decade(tool):
    """The pinned curve: the PRODUCTION hash's measured collision rate
    sits on the uniform expectation (ratio ≈ 1) at scaled-down decades
    spanning two orders of magnitude. Tokens-per-decade is sized so the
    expected collision count is in the hundreds — tight enough that a
    biased hash shows up, large enough that Poisson noise does not."""
    for m in (100_000, 1_000_000):
        row = tool.audit_decade(n_tokens=50_000, m=m, seed=0)
        assert row["colliding_tokens"] > 0
        assert 0.7 < row["ratio_vs_uniform"] < 1.3, row


def test_cli_gate_passes_on_production_hash(tool, capsys):
    rc = tool.main(["--tokens", "30000", "--decades", "60000,600000"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["ok"]
    assert [r["buckets"] for r in out["rows"]] == [60000, 600000]
    assert out["worst_ratio_vs_uniform"] <= 1.25


def test_cli_gate_fails_on_a_broken_hash(tool, capsys, monkeypatch):
    """A clustering hash (mod a small prime) must blow the gate — the
    auditor detects a broken hash, it does not just restate one."""
    import fm_spark_tpu.data.hashing as hashing

    monkeypatch.setattr(hashing, "murmur3_u64",
                        lambda tokens: np.asarray(tokens) % np.uint64(97))
    rc = tool.main(["--tokens", "20000", "--decades", "100000"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and not out["ok"]
    assert out["worst_ratio_vs_uniform"] > 1.25
