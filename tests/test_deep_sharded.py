"""TrainConfig.deep_sharded: the example-sharded deep head (VERDICT r4 #4).

The lever re-routes ONLY the deep head's collectives (h all_gather →
example a2a; pullback dynamic_slice → reverse a2a; replicated MLP grad →
psum over feat), so a deep_sharded step must match the replicated sharded
step to tight tolerance: per-example deep scores are the same values up
to matmul row-blocking, and the MLP grad reassociates across the psum.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 4, 32, 4, 64


def _spec(**kw):
    return models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(16, 16), init_std=0.1, **kw,
    )


def _cfg(**kw):
    return TrainConfig(learning_rate=0.05, optimizer="adam",
                       reg_factors=1e-3, reg_linear=1e-4, reg_bias=1e-4,
                       **kw)


def _run_steps(spec, config, mesh, n_feat, steps=3, seed=0):
    from fm_spark_tpu.parallel import (
        make_field_deepfm_sharded_step,
        pad_field_batch,
        shard_field_batch,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )

    params = spec.init(jax.random.key(1))
    step = make_field_deepfm_sharded_step(spec, config, mesh)
    sharded = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, params, n_feat), mesh
    )
    opt = step.init_opt_state(sharded)
    rng = np.random.default_rng(seed)
    losses = []
    for i in range(steps):
        batch = (
            np.asarray(rng.integers(0, BUCKET, (B, F)), np.int32),
            np.asarray(rng.uniform(0.5, 1.5, (B, F)), np.float32),
            np.asarray(rng.integers(0, 2, B), np.float32),
            np.ones((B,), np.float32),
        )
        sb = shard_field_batch(
            pad_field_batch(batch, F, n_feat), mesh
        )
        sharded, opt, loss = step(sharded, opt, jnp.int32(i), *sb)
        losses.append(float(loss))
    return jax.device_get(sharded), losses


@pytest.mark.parametrize("n_feat,n_row", [(2, 1), (4, 1), (2, 2)])
def test_deep_sharded_matches_replicated(eight_devices, n_feat, n_row):
    from fm_spark_tpu.parallel import make_field_mesh

    spec = _spec()
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    p_rep, l_rep = _run_steps(spec, _cfg(), mesh, n_feat)
    p_sh, l_sh = _run_steps(spec, _cfg(deep_sharded=True), mesh, n_feat)
    np.testing.assert_allclose(l_sh, l_rep, rtol=1e-6)
    for key in ("w0", "vw"):
        np.testing.assert_allclose(p_sh[key], p_rep[key], rtol=2e-5,
                                   atol=2e-6, err_msg=key)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5,
                                                atol=2e-6),
        p_sh["mlp"], p_rep["mlp"],
    )


def test_deep_sharded_bf16_wire_matches_replicated(eight_devices):
    """Under a bf16 wire BOTH paths round h identically (cast before
    the collective), and the deep-score gather stays fp32 by design —
    so the FIRST step's loss matches exactly (pins the
    no-logit-quantization rule). Later steps drift at the 1e-6 level:
    the deep PULLBACK's reverse a2a legitimately rides the bf16 wire
    (those bytes are the lever), which the replicated head's local
    dynamic_slice never rounds."""
    from fm_spark_tpu.parallel import make_field_mesh

    spec = _spec()
    mesh = make_field_mesh(4, devices=eight_devices[:4])
    cfg = dict(collective_dtype="bfloat16")
    p_rep, l_rep = _run_steps(spec, _cfg(**cfg), mesh, 4, steps=2)
    p_sh, l_sh = _run_steps(spec, _cfg(deep_sharded=True, **cfg),
                            mesh, 4, steps=2)
    np.testing.assert_allclose(l_sh[0], l_rep[0], rtol=1e-7)
    np.testing.assert_allclose(l_sh, l_rep, rtol=1e-4)
    np.testing.assert_allclose(p_sh["vw"], p_rep["vw"], rtol=1e-3,
                               atol=1e-5)


def test_deep_sharded_with_bf16_wire_and_multistep(eight_devices):
    """Composition smoke: deep_sharded + bf16 wire in the sharded
    multistep roll runs and stays finite (quality envelope for bf16 wire
    is measured by bench_quality.py, not here)."""
    from fm_spark_tpu.parallel import make_field_mesh
    from fm_spark_tpu.parallel.deepfm_step import (
        make_field_deepfm_sharded_multistep,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )
    from fm_spark_tpu.parallel import (
        pad_field_batch,
        shard_field_batch_stacked,
    )

    spec = _spec()
    n_feat = 4
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    config = _cfg(deep_sharded=True, collective_dtype="bfloat16")
    mstep = make_field_deepfm_sharded_multistep(spec, config, mesh, 2)
    params = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, spec.init(jax.random.key(2)),
                                  n_feat),
        mesh,
    )
    opt = mstep.init_opt_state(params)
    rng = np.random.default_rng(3)
    batch = pad_field_batch(
        (
            np.asarray(rng.integers(0, BUCKET, (B, F)), np.int32),
            np.asarray(rng.uniform(0.5, 1.5, (B, F)), np.float32),
            np.asarray(rng.integers(0, 2, B), np.float32),
            np.ones((B,), np.float32),
        ),
        F, n_feat,
    )
    stacked = tuple(np.stack([a, a], axis=0) for a in batch)
    params, opt, loss = mstep(
        params, opt, jnp.int32(0), jnp.int32(2),
        *shard_field_batch_stacked(stacked, mesh)
    )
    assert np.isfinite(float(loss))


def test_deep_sharded_eval_matches_replicated(eight_devices):
    """The deep_sharded EVAL forward produces the replicated head's
    metrics (pure wire re-route; no backward in eval)."""
    from fm_spark_tpu.parallel import make_field_mesh
    from fm_spark_tpu.parallel.deepfm_step import (
        make_field_deepfm_sharded_eval_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )
    from fm_spark_tpu.parallel import pad_field_batch, shard_field_batch
    from fm_spark_tpu.utils import metrics as metrics_lib

    spec = _spec()
    n_feat = 4
    mesh = make_field_mesh(n_feat, devices=eight_devices[:n_feat])
    params = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, spec.init(jax.random.key(5)),
                                  n_feat),
        mesh,
    )
    rng = np.random.default_rng(9)
    sb = shard_field_batch(
        pad_field_batch(
            (
                np.asarray(rng.integers(0, BUCKET, (B, F)), np.int32),
                np.asarray(rng.uniform(0.5, 1.5, (B, F)), np.float32),
                np.asarray(rng.integers(0, 2, B), np.float32),
                np.ones((B,), np.float32),
            ),
            F, n_feat,
        ),
        mesh,
    )
    outs = {}
    for flag in (False, True):
        estep = make_field_deepfm_sharded_eval_step(spec, mesh,
                                                    deep_sharded=flag)
        m = estep(params, metrics_lib.init_metrics(), *sb)
        outs[flag] = metrics_lib.finalize_metrics(m)
    for key in ("auc", "logloss", "count"):
        np.testing.assert_allclose(float(outs[True][key]),
                                   float(outs[False][key]), rtol=1e-6,
                                   err_msg=key)


def test_deep_sharded_rejected_elsewhere(eight_devices):
    """No-silent-fallback: every factory that does not implement the
    example-sharded head must fail loudly."""
    from fm_spark_tpu.parallel import (
        make_field_ffm_sharded_step,
        make_field_mesh,
        make_field_sharded_sgd_step,
    )
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step
    from fm_spark_tpu.train import make_train_step

    mesh = make_field_mesh(4, devices=eight_devices[:4])
    cfg = TrainConfig(deep_sharded=True)
    fm = models.FieldFMSpec(num_features=F * BUCKET, rank=K,
                            num_fields=F, bucket=BUCKET)
    with pytest.raises(ValueError, match="deep_sharded"):
        make_field_sharded_sgd_step(fm, cfg, mesh)
    ffm = models.FieldFFMSpec(num_features=F * BUCKET, rank=K,
                              num_fields=F, bucket=BUCKET)
    with pytest.raises(ValueError, match="deep_sharded"):
        make_field_ffm_sharded_step(ffm, cfg, mesh)
    with pytest.raises(ValueError, match="deep_sharded"):
        make_field_sparse_sgd_step(fm, cfg)
    dense = models.FMSpec(num_features=64, rank=4)
    with pytest.raises(ValueError, match="deep_sharded"):
        make_train_step(dense, cfg)
