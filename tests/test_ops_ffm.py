"""FFM kernel property tests: batched contraction vs explicit pair loop."""

import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu.ops import ffm as ffm_ops


def _problem(rng, b=8, n=30, nf=5, k=4, nnz=5):
    w0 = jnp.float32(rng.normal())
    w = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(n, nf, k)) * 0.3, jnp.float32)
    ids = np.stack([rng.choice(n, size=nnz, replace=False) for _ in range(b)])
    vals = rng.normal(size=(b, nnz)).astype(np.float32)
    return w0, w, v, jnp.asarray(ids, jnp.int32), jnp.asarray(vals)


def test_ffm_vs_pair_loop(rng):
    w0, w, v, ids, vals = _problem(rng)
    fast = ffm_ops.ffm_scores(w0, w, v, ids, vals)
    slow = ffm_ops.ffm_scores_dense(w0, w, v, ids, vals)
    np.testing.assert_allclose(fast, slow, rtol=2e-4, atol=2e-4)


def test_ffm_custom_fields(rng):
    # Two slots sharing a field: field layout [0, 0, 1, 2, 3].
    w0, w, v, ids, vals = _problem(rng, nf=4)
    fields = jnp.asarray([0, 0, 1, 2, 3], jnp.int32)
    fast = ffm_ops.ffm_scores(w0, w, v, ids, vals, fields=fields)
    slow = ffm_ops.ffm_scores_dense(w0, w, v, ids, vals, fields=np.asarray(fields))
    np.testing.assert_allclose(fast, slow, rtol=2e-4, atol=2e-4)


def test_ffm_padded_slot_contributes_nothing(rng):
    w0, w, v, ids, vals = _problem(rng)
    vals = vals.at[:, -1].set(0.0)
    full = ffm_ops.ffm_scores(w0, w, v, ids, vals)
    # Swapping the padded slot's id must not change anything.
    ids2 = ids.at[:, -1].set((ids[:, -1] + 1) % w.shape[0])
    again = ffm_ops.ffm_scores(w0, w, v, ids2, vals)
    np.testing.assert_allclose(full, again, rtol=1e-6, atol=1e-6)


def test_ffm_nnz_field_mismatch_raises(rng):
    # Regression: used to silently produce NaN via out-of-range jnp.take fill.
    w0, w, v, ids, vals = _problem(rng, nf=3, nnz=5)
    with pytest.raises(ValueError, match="nnz"):
        ffm_ops.ffm_scores(w0, w, v, ids, vals)
    with pytest.raises(ValueError, match="shape"):
        ffm_ops.ffm_scores(w0, w, v, ids, vals, fields=jnp.zeros((2,), jnp.int32))


def test_ffm_out_of_range_field_raises(rng):
    w0, w, v, ids, vals = _problem(rng, nf=4)
    with pytest.raises(ValueError, match="must be in"):
        ffm_ops.ffm_scores(w0, w, v, ids, vals,
                           fields=jnp.asarray([0, 1, 99, 2, 3], jnp.int32))
    with pytest.raises(ValueError, match="must be in"):
        ffm_ops.ffm_scores(w0, w, v, ids, vals,
                           fields=jnp.asarray([0, -1, 2, 3, 1], jnp.int32))
