"""Automated run doctor (ISSUE 9): the E2E acceptance — a REAL bench
run's obs directory diagnosed by ``tools/run_doctor.py --latest`` via
subprocess — plus unit coverage of the attribution/finding logic."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_doctor():
    spec = importlib.util.spec_from_file_location(
        "run_doctor_tool", os.path.join(REPO, "tools", "run_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------- E2E


@pytest.fixture(scope="module")
def bench_run(tmp_path_factory):
    """One real (CPU) fast-first sweep: artifacts dir + final payload."""
    art = tmp_path_factory.mktemp("art")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--fast-first", "--model", "fm_kaggle",
         "--batch", "128", "--steps", "2",
         "--attempts", "1", "--attempt-timeout", "300",
         "--total-deadline", "420", "--artifacts-dir", str(art)],
        capture_output=True, text=True, cwd=REPO, timeout=460,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    return art, json.loads(lines[-1])


def test_doctor_latest_diagnoses_real_bench_run(bench_run):
    """Acceptance: ``run_doctor.py --latest`` over a real bench run dir
    produces a phase-attributed diagnosis — compile share, per-leg
    sentinel verdicts, and a findings section."""
    art, final = bench_run
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         "--latest", str(art / "obs")],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    assert final["run_id"] in out
    # Phase attribution with a real wall-clock and the compile row.
    assert "## Where the time went" in out
    assert "compile+warmup" in out and "execute" in out
    # Per-leg verdict table: every completed leg's ledger record, with
    # variant, value, verdict, and weather columns.
    assert "## Per-leg verdicts" in out
    assert f"{final['legs_completed']} ledger record(s)" in out
    for label in final["all_variants"]:
        assert label[:52] in out
    for verdict in final["all_verdicts"].values():
        assert verdict in out
    assert "healthy" in out
    assert "## Diagnosis" in out


def test_doctor_explicit_dir_and_ledger_flag(bench_run):
    art, final = bench_run
    run_dir = art / "obs" / final["run_id"]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         str(run_dir), "--ledger", str(art / "obs" / "ledger.jsonl")],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert final["run_id"] in proc.stdout
    assert "## Per-leg verdicts" in proc.stdout


def test_bench_leg_records_carry_provenance(bench_run):
    """Every sweep record and every ledger record from the run carries
    run_id + fingerprint (the ISSUE 9 leg-record contract, runtime
    side), and the fingerprint names the cohort fields."""
    art, final = bench_run
    sweep = [json.loads(ln) for ln in
             (art / "sweep_fm_kaggle.jsonl").read_text().splitlines()]
    assert sweep
    for rec in sweep:
        assert rec["run_id"] == final["run_id"]
        fp = rec["fingerprint"]
        assert fp["key"] and fp["config_hash"]
        assert fp["device_kind"] == "cpu"
        assert fp["attachment_health"] == "healthy"
        assert rec["verdict"] in ("improved", "flat", "regressed",
                                  "attachment_transient",
                                  "insufficient_history")
    ledger = [json.loads(ln) for ln in
              (art / "obs" / "ledger.jsonl").read_text().splitlines()]
    legs = [r for r in ledger if r["kind"] == "bench_leg"]
    assert len(legs) == len(sweep)
    assert {r["variant"] for r in legs} == {r["variant"] for r in sweep}
    # jax version is known in-child — it must ride the fingerprint.
    assert all(r["fingerprint"]["jax_version"] for r in legs)


def test_result_json_carries_sentinel_block(bench_run):
    """ISSUE 9 acceptance: the bench result JSON carries the promoted
    leg's sentinel verdict block plus the per-leg verdict map."""
    art, final = bench_run
    sb = final["sentinel"]
    assert sb["verdict"] in ("improved", "flat", "regressed",
                             "attachment_transient",
                             "insufficient_history")
    assert set(final["all_verdicts"]) == set(final["all_variants"])


def test_cost_attribution_lands_per_leg_and_renders(bench_run):
    """ISSUE 14 acceptance: every completed bench leg lands ONE
    cost_attribution ledger record (measured step time x bytes-moved
    model), and the doctor renders the cost table."""
    art, final = bench_run
    ledger = [json.loads(ln) for ln in
              (art / "obs" / "ledger.jsonl").read_text().splitlines()]
    legs = [r for r in ledger if r["kind"] == "bench_leg"]
    cost = [r for r in ledger if r["kind"] == "cost_attribution"]
    assert len(cost) == len(legs) >= 1
    assert ({r["variant"] for r in cost}
            == {r["variant"] for r in legs})
    for rec in cost:
        assert rec["run_id"] == final["run_id"]
        assert rec["value"] > 0 and rec["unit"] == "GB/s(model)"
        assert rec["step_ms"] > 0
        assert rec["bytes_per_step"] == sum(rec["families"].values())
        assert set(rec["families"]) == {"gather", "interact",
                                        "update", "segsum"}
        assert rec["fingerprint"]["key"]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         "--latest", str(art / "obs")],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "## Cost attribution" in proc.stdout
    assert "GB/s(model)" in proc.stdout


def test_doctor_run_id_selector(bench_run):
    """ISSUE 14 satellite: ``--run-id`` selects a run by NAME (the
    mtime-based --latest pick is wrong while a daemon keeps its run
    dir hot), and a bogus id is a loud error, never a fallback."""
    art, final = bench_run
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         "--run-id", final["run_id"], str(art / "obs")],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert final["run_id"] in proc.stdout
    assert "## Per-leg verdicts" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         "--run-id", "no-such-run", str(art / "obs")],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 1
    assert "no-such-run" in proc.stderr


# ---------------------------------------------------------------- unit


def _synthetic_run(legs):
    spans = [
        {"name": "bench/leg", "label": "a", "t_start": 100.0,
         "dur_ms": 10_000.0},
        {"name": "bench/leg", "label": "b", "t_start": 110.0,
         "dur_ms": 10_000.0},
        {"name": "resilience/backoff", "t_start": 105.0,
         "dur_ms": 2_000.0},
    ]
    return {"run_id": "synth", "dir": "/x", "spans": spans,
            "snapshot": {"counters": {}, "gauges": {}},
            "timeline": [{"kind": "failure", "ts": 105.0}],
            "dead": [], "dump": None}


def test_diagnose_attributes_compile_vs_execute():
    doctor = _load_doctor()
    legs = [
        {"variant": "a", "value": 100.0, "dt_s": 2.0,
         "sentinel": {"verdict": "flat"}, "fingerprint": {}},
        {"variant": "b", "value": 90.0, "dt_s": 3.0,
         "sentinel": {"verdict": "regressed", "reason": "z=-4"},
         "fingerprint": {}},
    ]
    diag = doctor.diagnose(_synthetic_run(legs), legs, [])
    # Two 10s legs, 5s of timed windows -> 15s compile+warmup, 5s exec.
    assert diag["phases"]["compile+warmup"] == pytest.approx(15.0)
    assert diag["phases"]["execute"] == pytest.approx(5.0)
    assert diag["phases"]["faults/backoff"] == pytest.approx(2.0)
    assert diag["fault_kinds"] == {"failure": 1}
    found = doctor.findings(diag, legs)
    assert any("compile-dominated" in f for f in found)
    assert any("REGRESSED: b" in f for f in found)


def test_findings_flag_weather_and_stamps():
    doctor = _load_doctor()
    diag = {"wall_s": 100.0, "fresh_compiles": 0,
            "phases": {"compile+warmup": 1.0, "execute": 80.0,
                       "faults/backoff": 15.0, "eval": 0.0,
                       "other": 4.0},
            "ingest_busy_s": 0.0, "backoff_s": 15.0,
            "fault_kinds": {"failure": 3, "circuit_open": 1}}
    legs = [{"variant": "v", "value": None,
             "sentinel": {"verdict": "attachment_transient",
                          "reason": "weather"},
             "fingerprint": {"degraded": True, "fused_fallback": True}}]
    found = doctor.findings(diag, legs)
    assert any("attachment weather" in f and "circuit opened" in f
               for f in found)
    assert any("transient (weather, not code)" in f for f in found)
    assert any("degraded leg" in f for f in found)
    assert any("fused-embed fallback" in f for f in found)


def test_findings_clean_run():
    doctor = _load_doctor()
    diag = {"wall_s": 100.0, "fresh_compiles": 0,
            "phases": {"compile+warmup": 10.0, "execute": 85.0,
                       "faults/backoff": 0.0, "eval": 2.0,
                       "other": 3.0},
            "ingest_busy_s": 0.0, "backoff_s": 0.0, "fault_kinds": {}}
    found = doctor.findings(diag, [])
    assert found == [
        "clean run: no faults, no regressions, 85% of wall-clock "
        "executing"]


def test_findings_flag_ingest_bound():
    doctor = _load_doctor()
    diag = {"wall_s": 100.0, "fresh_compiles": 0,
            "phases": {"compile+warmup": 1.0, "execute": 10.0,
                       "faults/backoff": 0.0, "eval": 0.0,
                       "other": 89.0},
            "ingest_busy_s": 40.0, "backoff_s": 0.0, "fault_kinds": {}}
    assert any("ingest-bound" in f
               for f in doctor.findings(diag, []))


def test_doctor_cli_errors(tmp_path):
    doctor = _load_doctor()
    assert doctor.main(["--latest", str(tmp_path / "none")]) == 1
    assert doctor.main([str(tmp_path / "nope")]) == 1
    assert doctor.main([]) == 2


def test_doctor_renders_chaos_verdict(tmp_path, capsys):
    """ISSUE 10: a run dir holding a chaos_verdict.json gets a chaos
    section + diagnosis lines, including the minimized repro plan."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r1"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    verdict = {
        "run_id": "r1", "mode": "bounded", "n_schedules": 2,
        "n_green": 1, "n_failed": 1, "n_skipped": 0, "total_s": 3.2,
        "all_green": False,
        "schedules": [
            {"seed": 0, "scenario": "commit_loss",
             "plan": "ckpt_commit@1=device_loss", "verdict": "green",
             "outcome": "completed", "violations": []},
            {"seed": 3, "scenario": "recovery_storm",
             "plan": "train_step@4=device_loss;probe@1=device_loss",
             "verdict": "failed", "outcome": "completed",
             "violations": [{"invariant": "exactly_once_stream",
                             "detail": "records replayed"}],
             "minimized_plan": "train_step@4=device_loss"},
        ],
        "failures": [
            {"seed": 3, "scenario": "recovery_storm",
             "violations": [{"invariant": "exactly_once_stream",
                             "detail": "records replayed"}],
             "minimized_plan": "train_step@4=device_loss"},
        ],
    }
    (run_dir / "chaos_verdict.json").write_text(json.dumps(verdict))
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "## Chaos verdict" in out
    assert "exactly_once_stream" in out
    assert "FM_SPARK_FAULTS='train_step@4=device_loss'" in out
    assert "CHAOS: seed 3" in out


def test_doctor_renders_deep_captures(tmp_path, capsys):
    """ISSUE 14: a run dir holding capture bundles gets a Deep
    captures section plus a DEEP CAPTURE diagnosis pointer per bundle;
    a torn bundle (no manifest) is skipped, never fatal."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r14"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    bundle = run_dir / "captures" / "serve_slo_overrun_001"
    bundle.mkdir(parents=True)
    (bundle / "capture.json").write_text(json.dumps({
        "trigger": "serve_slo_overrun", "seq": 1, "run_id": "r14",
        "ts": 5.0, "context": {"deadline_s": 0.01, "elapsed_s": 0.09},
        "profiler": {"status": "armed", "trace_s": 0.5},
    }))
    torn = run_dir / "captures" / "step_time_spike_001"
    torn.mkdir()
    (torn / "metrics.json").write_text("{}")
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "## Deep captures (1 bundle(s))" in out
    assert "serve_slo_overrun" in out and "profiler=armed" in out
    assert "DEEP CAPTURE [serve_slo_overrun]" in out
    assert "step_time_spike" not in out


def test_doctor_chaos_findings_green_and_budget():
    doctor = _load_doctor()
    assert doctor.chaos_findings(None) == []
    green = doctor.chaos_findings(
        {"all_green": True, "n_green": 25, "total_s": 17.0})
    assert len(green) == 1 and "chaos campaign green" in green[0]
    over = doctor.chaos_findings(
        {"all_green": False, "failures": [], "budget_exhausted": True,
         "n_skipped": 7})
    assert any("out of budget" in f for f in over)


def test_doctor_renders_continuous_learning_section(tmp_path, capsys):
    """ISSUE 13: a run with an online-learning footprint (quality_eval
    ledger rows + drift events in the flight ring) gets a Continuous
    learning section — the AUC series with verdicts, the drift
    timeline, and a DRIFT ROLLBACK finding."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r9"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    flight = [
        {"kind": "quality_eval", "ts": 10.0, "seq": 1, "day": 3,
         "eval_day": 4, "step": 16, "auc": 0.67, "sentinel": "flat"},
        {"kind": "divergence_detected", "ts": 11.0, "seq": 2,
         "step": 20, "reason": "metric drop", "mode": "max"},
        {"kind": "generation_demoted", "ts": 11.1, "seq": 3,
         "steps": [20], "newer_than": 16},
        {"kind": "last_good_republished", "ts": 11.2, "seq": 4,
         "prev": 20, "step": 16},
        {"kind": "online_rollback", "ts": 11.3, "seq": 5, "day": 4,
         "demoted": [20], "restored_step": 16},
    ]
    (run_dir / "flight.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in flight))
    (run_dir / "metrics.jsonl").write_text(json.dumps({
        "gauges": {"online/auc": 0.32, "online/drift_score": 0.52,
                   "checkpoint/quarantined_generations": 1},
        "counters": {"online.days_total": 5,
                     "online.rollbacks_total": 1,
                     "checkpoint.demotions_total": 1},
    }) + "\n")
    ledger = tmp_path / "ledger.jsonl"
    rows = [
        {"kind": "quality_eval", "leg": "quality/demo/ftrl",
         "run_id": "r9", "value": 0.70, "day": 1, "step": 4,
         "fingerprint": {"key": "k1"},
         "sentinel": {"verdict": "flat"}},
        {"kind": "quality_eval", "leg": "quality/demo/ftrl",
         "run_id": "r9", "value": 0.32, "day": 4, "step": 20,
         "fingerprint": {"key": "k1"},
         "sentinel": {"verdict": "regressed",
                      "reason": "z=-9 below the band"}},
    ]
    ledger.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert doctor.main([str(run_dir), "--ledger", str(ledger)]) == 0
    out = capsys.readouterr().out
    assert "## Continuous learning" in out
    assert "drift timeline:" in out
    assert "generation_demoted" in out
    assert "0.3200" in out and "regressed" in out
    assert "DRIFT ROLLBACK" in out
    assert "QUALITY REGRESSED" in out


def test_doctor_renders_static_analysis_section(tmp_path, capsys):
    """ISSUE 15: a run dir holding an fmlint.json report gets a Static
    analysis section + diagnosis lines — unbaselined findings render
    as loudly as a regressed leg."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r1"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    rep = {
        "version": 1, "tool": "fmlint", "run_id": "r1", "ok": False,
        "rules": {"bare-print": "no bare print",
                  "jax-host-sync": "no host syncs in step loops"},
        "counts": {"jax-host-sync": {"fm_spark_tpu/train.py": 1}},
        "total_findings": 1,
        "new": [{"rule": "jax-host-sync",
                 "path": "fm_spark_tpu/train.py", "line": 7,
                 "message": "host sync float(...) inside a hot-path "
                            "loop body", "func": "fit"}],
        "baselined_total": 0,
        "burned_down": [{"rule": "bare-print",
                         "path": "fm_spark_tpu/x.py",
                         "baseline": 2, "current": 0}],
        "suppressed": [],
    }
    (run_dir / "fmlint.json").write_text(json.dumps(rep))
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "## Static analysis" in out and "FAILING" in out
    assert "jax-host-sync" in out
    assert "STATIC ANALYSIS: 1 unbaselined finding(s)" in out
    assert "burn-down" in out
    # A clean report renders quietly green.
    rep.update(ok=True, new=[], counts={}, total_findings=0,
               burned_down=[], baselined_total=3)
    (run_dir / "fmlint.json").write_text(json.dumps(rep))
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "OK" in out
    assert "3 baselined finding(s) still burning down" in out


def test_doctor_renders_serving_fleet_section(tmp_path, capsys):
    """ISSUE 17: a run dir holding a fleet health journal
    (``fleet_health.jsonl``) gets a Serving fleet section — the
    admission counters, the per-replica lifecycle table, and the
    replica-loss -> recovery timeline as a finding."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r17"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    events = [
        {"event": "replica_spawn", "replica": 0, "ts": 100.0},
        {"event": "replica_ready", "replica": 0, "ts": 101.2,
         "generation_step": 3},
        {"event": "replica_spawn", "replica": 1, "ts": 100.0},
        {"event": "replica_ready", "replica": 1, "ts": 101.4,
         "generation_step": 3},
        {"event": "replica_down", "replica": 1, "ts": 105.0,
         "rc": 9, "reason": "process died", "incarnation": 1},
        {"event": "replica_spawn", "replica": 1, "ts": 105.1},
        {"event": "replica_ready", "replica": 1, "ts": 106.5,
         "generation_step": 3},
        # ISSUE 19: replica 0 is partitioned (drained, no process
        # death) and heals; the autoscaler grows once meanwhile.
        {"event": "replica_drained", "replica": 0, "ts": 107.0,
         "via": "dispatch"},
        {"event": "autoscale_decision", "ts": 107.5, "action": "grow",
         "reason": "shed_frac=0.300>0.05 for 2 ticks", "tick": 12,
         "n_ready": 1, "to_n": 3, "shed_frac": 0.3, "fill": 0.0},
        {"event": "replica_ready", "replica": 0, "ts": 108.2,
         "generation_step": 3},
        {"event": "frontdoor_summary", "ts": 110.0, "accepted": 40,
         "answered": 39, "timeout": 1, "failed": 0, "shed": 3,
         "shed_queue": 1, "shed_deadline": 2, "rejected": 0,
         "retries": 2},
    ]
    (run_dir / "fleet_health.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in events))
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "## Serving fleet" in out
    assert "accepted 40  answered 39" in out
    assert "shed 3 (queue 1 / deadline 2)" in out
    assert "replica-loss timeline (crash vs partition)" in out
    assert ("replica 1 down (rc=9) -> ready after 1.500s "
            "[crash: respawned]") in out
    assert "replica 1 lost (rc=9) and re-admitted after 1.500s" in out
    # The partition is classified apart from the crash: no respawn.
    assert ("replica 0 drained -> readmitted after 1.200s "
            "[partition: process stayed alive, no respawn]") in out
    assert "replica 0 PARTITIONED" in out
    assert ("autoscale decision log (1 grow / 0 shrink, "
            "0 direction change(s)):") in out
    assert "-> 3 replica(s)  [shed_frac=0.300>0.05 for 2 ticks]" in out


def test_fleet_diagnose_unit_contracts():
    """The fleet view's edge cases: no footprint -> None; open books
    and generation skew -> loud findings; a crash-looping replica is
    called out by name."""
    doctor = _load_doctor()
    assert doctor.fleet_diagnose({"snapshot": {}}, []) is None
    # Snapshot-counter fallback when the door died before its summary.
    run = {"snapshot": {"counters": {"frontdoor.accepted_total": 5,
                                     "frontdoor.answered_total": 3}}}
    fleet = doctor.fleet_diagnose(run, [])
    assert fleet["counters"]["accepted"] == 5
    finds = doctor.fleet_findings(fleet)
    assert any("FLEET BOOKS OPEN" in f for f in finds)
    # Generation skew across ready replicas + a crash-looper.
    events = []
    for _ in range(3):
        events += [{"event": "replica_spawn", "replica": 0},
                   {"event": "replica_down", "replica": 0, "rc": 1}]
    events += [
        {"event": "replica_spawn", "replica": 0},
        {"event": "replica_ready", "replica": 0,
         "generation_step": 7},
        {"event": "replica_spawn", "replica": 1},
        {"event": "replica_ready", "replica": 1,
         "generation_step": 5},
        {"event": "frontdoor_summary", "accepted": 2, "answered": 2,
         "shed": 0, "shed_queue": 0, "shed_deadline": 0,
         "rejected": 0, "timeout": 0, "failed": 0, "retries": 0},
    ]
    fleet = doctor.fleet_diagnose({"snapshot": {}}, events)
    assert fleet["generation_skew"] == 2
    finds = doctor.fleet_findings(fleet)
    assert any("GENERATION SKEW" in f for f in finds)
    assert any("CRASH-LOOPING" in f for f in finds)


def test_doctor_renders_storage_health_section(tmp_path, capsys):
    """ISSUE 20: a run whose durable seam took disk faults gets a
    Storage health section — the failure counters by path class, the
    degraded-obs window, the retry/backoff table, the emergency-GC
    events, the io-fault timeline — and a DISK_DEGRADED finding."""
    doctor = _load_doctor()
    run_dir = tmp_path / "r20"
    run_dir.mkdir()
    (run_dir / "trace.jsonl").write_text("")
    flight = [
        {"kind": "io_write_failed", "ts": 10.0, "seq": 1,
         "path_class": "obs", "phase": "append", "best_effort": True},
        {"kind": "ckpt_io_retry", "ts": 10.5, "seq": 2,
         "path": "manifest_4.json", "attempt": 1, "errno": 5,
         "delay_s": 0.05},
        {"kind": "ckpt_emergency_gc", "ts": 11.0, "seq": 3,
         "trigger": "last_good.json", "steps": [2, 3]},
        {"kind": "ckpt_emergency_gc_done", "ts": 11.2, "seq": 4,
         "steps": [2, 3]},
        {"kind": "io_write_failed", "ts": 12.0, "seq": 5,
         "path_class": "obs", "phase": "atomic_write",
         "best_effort": True},
    ]
    (run_dir / "flight.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in flight))
    (run_dir / "metrics.jsonl").write_text(json.dumps({
        "gauges": {"obs/io_degraded": 1.0},
        "counters": {"io.write_failed_total": 3,
                     "io.write_failed.obs_total": 2,
                     "io.write_failed.ckpt_total": 1,
                     "checkpoint.io_retries_total": 1,
                     "checkpoint.emergency_gc_total": 1},
    }) + "\n")
    assert doctor.main([str(run_dir)]) == 0
    out = capsys.readouterr().out
    assert "## Storage health" in out
    assert "write failures 3 (ckpt 1 / obs 2)" in out
    assert "obs degraded true" in out
    assert "degraded-obs window: 2 swallowed" in out
    assert "retry of" in out and "manifest_4.json" in out
    assert "io-fault timeline:" in out
    assert "ckpt_emergency_gc" in out
    assert "DISK_DEGRADED" in out
    assert "ENOSPC emergency GC" in out
    assert "transient disk errors absorbed" in out


def test_storage_diagnose_unit_contracts():
    """No storage footprint -> None (a healthy disk renders no
    section); a loud CheckpointIOError event outranks the degraded
    finding; fail-loud-only failures do not claim DISK_DEGRADED."""
    doctor = _load_doctor()
    assert doctor.storage_diagnose({"snapshot": {}}, []) is None
    assert doctor.storage_diagnose(
        {"snapshot": {}}, [{"kind": "bench_leg_start"}]) is None
    run = {"snapshot": {"counters": {"io.write_failed_total": 2,
                                     "io.write_failed.ckpt_total": 2}}}
    ev = [{"kind": "checkpoint_io_error", "ts": 1.0,
           "path": "last_good.json", "errno": 28}]
    st = doctor.storage_diagnose(run, ev)
    assert st["by_class"] == {"ckpt": 2}
    assert st["degraded_window"] is None
    finds = doctor.storage_findings(st)
    assert any("CHECKPOINT IO ERROR" in f for f in finds)
    assert not any("DISK_DEGRADED" in f for f in finds)
