"""make_field_sharded_multistep: N field-sharded steps in ONE compiled
program (fori INSIDE the shard_map) ≡ N separate sharded step calls.

The multi-chip form of --steps-per-call (round 4): amortizes the
projection model's t_fixed dispatch term across the roll. FM and FFM;
host-built aux rejected (compact_device composes instead).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.parallel import (
    make_field_ffm_sharded_step,
    make_field_mesh,
    make_field_sharded_multistep,
    make_field_sharded_sgd_step,
    pad_field_batch,
    shard_field_batch,
    shard_field_batch_stacked,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B, N = 5, 32, 4, 64, 4


def _batches(rng, n_batches):
    out = []
    for _ in range(n_batches):
        out.append((
            rng.integers(0, BUCKET, size=(B, F)).astype(np.int32),
            rng.uniform(0.5, 1.5, size=(B, F)).astype(np.float32),
            rng.integers(0, 2, B).astype(np.float32),
            np.ones((B,), np.float32),
        ))
    return out


def _stack(padded):
    return tuple(
        np.stack([b[i] for b in padded], axis=0) for i in range(4)
    )


def _params(spec, n_feat, mesh, key=0):
    return shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(key)), n_feat),
        mesh,
    )


CONFIGS = {
    "plain": dict(),
    "devcompact_levers": dict(sparse_update="dedup_sr",
                              compact_device=True, compact_cap=B,
                              collective_dtype="bfloat16",
                              score_sharded=True, gfull_fused=True),
}


@pytest.mark.parametrize("n_row", [1, 2])
@pytest.mark.parametrize("which", list(CONFIGS))
def test_sharded_multistep_matches_per_step(eight_devices, n_row, which):
    n_feat = 4
    extra = dict(CONFIGS[which])
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.2, lr_schedule="inv_sqrt",
                         optimizer="sgd", reg_factors=1e-3,
                         reg_linear=1e-4, **extra)
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    batches = _batches(np.random.default_rng(0), 2 * N)
    padded = [pad_field_batch(b, F, n_feat) for b in batches]

    params_s = _params(spec, n_feat, mesh)
    step = make_field_sharded_sgd_step(spec, config, mesh)
    for i, b in enumerate(padded):
        params_s, loss_s = step(params_s, jnp.int32(i),
                                *shard_field_batch(b, mesh))

    params_m = _params(spec, n_feat, mesh)
    mstep = make_field_sharded_multistep(spec, config, mesh, N)
    for call in range(2):
        stacked = shard_field_batch_stacked(
            _stack(padded[call * N: (call + 1) * N]), mesh)
        params_m, loss_m = mstep(params_m, jnp.int32(call * N),
                                 jnp.int32(N), *stacked)
    np.testing.assert_allclose(float(loss_m), float(loss_s), rtol=1e-6)
    got_s = unstack_field_params(spec, jax.device_get(params_s))
    got_m = unstack_field_params(spec, jax.device_get(params_m))
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(got_m["vw"][f], np.float32),
            np.asarray(got_s["vw"][f], np.float32),
            rtol=1e-5, atol=1e-6, err_msg=f"field {f}",
        )


def test_sharded_multistep_partial_tail(eight_devices):
    n_feat = 4
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.2, optimizer="sgd")
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    batches = _batches(np.random.default_rng(1), N)
    padded = [pad_field_batch(b, F, n_feat) for b in batches]
    m = 2

    params_s = _params(spec, n_feat, mesh, key=1)
    step = make_field_sharded_sgd_step(spec, config, mesh)
    for i, b in enumerate(padded[:m]):
        params_s, _ = step(params_s, jnp.int32(i),
                           *shard_field_batch(b, mesh))

    params_m = _params(spec, n_feat, mesh, key=1)
    mstep = make_field_sharded_multistep(spec, config, mesh, N)
    params_m, _ = mstep(params_m, jnp.int32(0), jnp.int32(m),
                        *shard_field_batch_stacked(_stack(padded), mesh))
    got_s = unstack_field_params(spec, jax.device_get(params_s))
    got_m = unstack_field_params(spec, jax.device_get(params_m))
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(got_m["vw"][f]), np.asarray(got_s["vw"][f]),
            rtol=1e-5, atol=1e-6,
        )


def test_sharded_multistep_ffm(eight_devices):
    n_feat = 4
    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.1, optimizer="sgd",
                         sparse_update="dedup")
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    batches = _batches(np.random.default_rng(2), N)
    padded = [pad_field_batch(b, F, n_feat) for b in batches]

    params_s = _params(spec, n_feat, mesh, key=2)
    step = make_field_ffm_sharded_step(spec, config, mesh)
    for i, b in enumerate(padded):
        params_s, _ = step(params_s, jnp.int32(i),
                           *shard_field_batch(b, mesh))

    params_m = _params(spec, n_feat, mesh, key=2)
    mstep = make_field_sharded_multistep(spec, config, mesh, N)
    params_m, _ = mstep(params_m, jnp.int32(0), jnp.int32(N),
                        *shard_field_batch_stacked(_stack(padded), mesh))
    got_s = unstack_field_params(spec, jax.device_get(params_s))
    got_m = unstack_field_params(spec, jax.device_get(params_m))
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(got_m["vw"][f]), np.asarray(got_s["vw"][f]),
            rtol=2e-5, atol=1e-6,
        )


def test_sharded_multistep_rejects_host_aux(eight_devices):
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET)
    mesh = make_field_mesh(4, devices=eight_devices)
    with pytest.raises(ValueError, match="host-built"):
        make_field_sharded_multistep(
            spec, TrainConfig(optimizer="sgd", sparse_update="dedup",
                              host_dedup=True, compact_cap=B), mesh, 2)


def test_sharded_multistep_deepfm(eight_devices):
    """The DeepFM sharded roll: optax state through the outer-jit fori
    around the shard_map — params, mlp, AND moments match per-step."""
    from fm_spark_tpu.parallel import make_field_deepfm_sharded_multistep
    from fm_spark_tpu.parallel.field_step import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
        unstack_field_deepfm_params,
    )

    n_feat = 4
    deep = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(8,), init_std=0.1)
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    config = TrainConfig(learning_rate=0.05, optimizer="adam",
                         reg_factors=1e-3, reg_linear=1e-4,
                         reg_bias=1e-4)
    batches = _batches(np.random.default_rng(3), N)
    padded = [pad_field_batch(b, F, n_feat) for b in batches]

    def dparams():
        return shard_field_deepfm_params(
            stack_field_deepfm_params(
                deep, deep.init(jax.random.key(4)), n_feat), mesh)

    params_s = dparams()
    step = make_field_deepfm_sharded_step(deep, config, mesh)
    opt_s = step.init_opt_state(params_s)
    for i, b in enumerate(padded):
        params_s, opt_s, loss_s = step(params_s, opt_s, jnp.int32(i),
                                       *shard_field_batch(b, mesh))

    params_m = dparams()
    mstep = make_field_deepfm_sharded_multistep(deep, config, mesh, N)
    opt_m = mstep.init_opt_state(params_m)
    params_m, opt_m, loss_m = mstep(
        params_m, opt_m, jnp.int32(0), jnp.int32(N),
        *shard_field_batch_stacked(_stack(padded), mesh))
    np.testing.assert_allclose(float(loss_m), float(loss_s), rtol=1e-6)
    got_s = unstack_field_deepfm_params(deep, jax.device_get(params_s))
    got_m = unstack_field_deepfm_params(deep, jax.device_get(params_m))
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(got_m["vw"][f]), np.asarray(got_s["vw"][f]),
            rtol=1e-5, atol=1e-6, err_msg=f"vw[{f}]")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        got_m["mlp"], got_s["mlp"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        jax.device_get(opt_m), jax.device_get(opt_s))
