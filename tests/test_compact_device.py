"""DEVICE-built compact aux (`TrainConfig.compact_device`): the in-step
builder (ops/scatter.device_compact_aux) must reproduce the host builder
bit-for-bit (both sorts are stable), so every downstream compact result
is identical; and it must lift the host aux's structural limits — the
2-D (feat, row) mesh and overflow-without-crash — with the documented
semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import compact_aux, device_compact_aux
from fm_spark_tpu.parallel import (
    make_field_mesh,
    make_field_sharded_sgd_step,
    pad_field_batch,
    shard_field_batch,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.sparse import make_field_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B, CAP = 5, 64, 4, 48, 48


def _batch(rng, b=B, f=F, bucket=BUCKET):
    ids = rng.integers(0, bucket, size=(b, f)).astype(np.int32)
    ids[:, 0] = rng.integers(0, 3, b)          # heavy duplication
    vals = rng.normal(size=(b, f)).astype(np.float32)
    labels = rng.integers(0, 2, b).astype(np.float32)
    weights = np.ones(b, np.float32)
    weights[::7] = 0.0                          # inert rows
    return ids, vals, labels, weights


def _spec(**kw):
    kw.setdefault("param_dtype", "float32")
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, **kw
    )


def _base_cfg(**kw):
    base = dict(learning_rate=0.05, optimizer="sgd",
                reg_factors=1e-4, reg_linear=1e-4)
    base.update(kw)
    return TrainConfig(**base)


def test_device_aux_matches_host_aux_bitwise(rng):
    ids = rng.integers(0, 17, size=(40, 3)).astype(np.int32)
    cap = 24
    want = compact_aux(ids, cap)
    names = ("useg", "segstart", "segend", "order", "inv")
    for f in range(3):
        got, nseg = jax.jit(device_compact_aux, static_argnums=1)(
            jnp.asarray(ids[:, f]), cap
        )
        assert int(nseg) == np.unique(ids[:, f]).size
        for g, w, name in zip(got, want, names):
            np.testing.assert_array_equal(
                np.asarray(g), w[f], err_msg=f"field {f} {name}"
            )


def test_device_aux_overflow_counts_and_targets(rng):
    # 30 unique ids, cap 8: segments 8.. (the LARGEST ids) must lose
    # their useg slot; the first 8 stay exact.
    ids = np.arange(30, dtype=np.int32)
    rng.shuffle(ids)
    cap = 8
    (useg, segstart, segend, order, inv), nseg = jax.jit(
        device_compact_aux, static_argnums=1
    )(jnp.asarray(ids), cap)
    assert int(nseg) == 30
    np.testing.assert_array_equal(np.asarray(useg), np.arange(8))
    # inv still maps every lane to its true segment (>= cap for dropped).
    np.testing.assert_array_equal(np.sort(np.asarray(inv)), np.arange(30))


@pytest.mark.parametrize(
    "mode,pdtype", [("dedup", "float32"), ("dedup_sr", "bfloat16")]
)
def test_single_chip_device_matches_host_compact(rng, mode, pdtype):
    ids, vals, labels, weights = _batch(rng)
    spec = _spec(param_dtype=pdtype)
    params = spec.init(jax.random.key(1))
    host_step = make_field_sparse_sgd_step(
        spec, _base_cfg(sparse_update=mode, host_dedup=True,
                        compact_cap=CAP),
    )
    dev_step = make_field_sparse_sgd_step(
        spec, _base_cfg(sparse_update=mode, compact_device=True,
                        compact_cap=CAP),
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids, CAP))
    args = (jnp.int32(3), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights))
    p_host, l_host = host_step(jax.tree.map(jnp.copy, params), *args, aux)
    p_dev, l_dev = dev_step(params, *args)
    assert float(l_host) == float(l_dev)
    # Same stable sort → same cumsum association → bitwise-equal tables
    # (incl. the SR noise stream, which keys on (step, field) only).
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)
        ),
        p_host, p_dev,
    )


def test_sharded_1d_device_matches_single_chip(rng):
    ids, vals, labels, weights = _batch(rng, b=64)
    spec = _spec()
    config = _base_cfg(sparse_update="dedup", compact_device=True,
                       compact_cap=CAP)
    canonical = spec.init(jax.random.key(1))
    single = make_field_sparse_sgd_step(spec, config)
    mesh = make_field_mesh(8)
    sharded = make_field_sharded_sgd_step(spec, config, mesh)
    sp = shard_field_params(
        stack_field_params(spec, jax.tree.map(jnp.copy, canonical), 8),
        mesh,
    )
    batch = pad_field_batch((ids, vals, labels, weights), F, 8)
    for i in range(3):
        canonical, l1 = single(
            canonical, jnp.int32(i), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights),
        )
        sp, l2 = sharded(sp, jnp.int32(i), *shard_field_batch(batch, mesh))
        assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    got = unstack_field_params(spec, jax.device_get(sp))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-7,
        ),
        canonical, got,
    )


def test_sharded_2d_device_matches_single_chip(rng):
    ids, vals, labels, weights = _batch(rng, b=64)
    spec = _spec()
    config = _base_cfg(sparse_update="dedup", compact_device=True,
                       compact_cap=CAP)
    canonical = spec.init(jax.random.key(1))
    single = make_field_sparse_sgd_step(spec, config)
    mesh = make_field_mesh(8, n_row=2)     # 4 feat x 2 row
    sharded = make_field_sharded_sgd_step(spec, config, mesh)
    sp = shard_field_params(
        stack_field_params(spec, jax.tree.map(jnp.copy, canonical), 4),
        mesh,
    )
    batch = pad_field_batch((ids, vals, labels, weights), F, 4)
    for i in range(3):
        canonical, l1 = single(
            canonical, jnp.int32(i), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights),
        )
        sp, l2 = sharded(sp, jnp.int32(i), *shard_field_batch(batch, mesh))
        assert float(l1) == pytest.approx(float(l2), rel=1e-5)
    got = unstack_field_params(spec, jax.device_get(sp))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-5, atol=1e-6,
        ),
        canonical, got,
    )


@pytest.mark.slow
def test_overflow_drop_semantics(rng):
    # One near-unique field overflows cap; policy 'drop' must train
    # through and act exactly as if the overflow ids (the LARGEST ids
    # past the cap-th unique) were absent features (val=0) — provable
    # bitwise with reg=0.
    b, cap = 48, 8
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)  # near-unique field
    spec = _spec()
    cfg = dict(learning_rate=0.05, optimizer="sgd", reg_factors=0.0,
               reg_linear=0.0)
    drop_step = make_field_sparse_sgd_step(
        spec, TrainConfig(**cfg, sparse_update="dedup",
                          compact_device=True, compact_cap=cap,
                          compact_overflow="drop"),
    )
    ref_step = make_field_sparse_sgd_step(
        spec, TrainConfig(**cfg, sparse_update="dedup",
                          compact_device=True, compact_cap=b,
                          compact_overflow="error"),
    )
    # Reference batch: overflowing ids' vals zeroed by hand.
    vals_ref = vals.copy()
    for f in range(F):
        uniq = np.unique(ids[:, f])
        if uniq.size > cap:
            vals_ref[np.isin(ids[:, f], uniq[cap:]), f] = 0.0
    params = spec.init(jax.random.key(1))
    p_drop, l_drop = drop_step(
        jax.tree.map(jnp.copy, params), jnp.int32(0), jnp.asarray(ids),
        jnp.asarray(vals), jnp.asarray(labels), jnp.asarray(weights),
    )
    p_ref, l_ref = ref_step(
        params, jnp.int32(0), jnp.asarray(ids), jnp.asarray(vals_ref),
        jnp.asarray(labels), jnp.asarray(weights),
    )
    assert np.isfinite(float(l_drop))
    assert float(l_drop) == float(l_ref)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        p_drop, p_ref,
    )


def test_overflow_error_poisons_loss(rng):
    b, cap = 48, 8
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)
    spec = _spec()
    step = make_field_sparse_sgd_step(
        spec, _base_cfg(sparse_update="dedup", compact_device=True,
                        compact_cap=cap),  # compact_overflow defaults to error
    )
    _, loss = step(
        spec.init(jax.random.key(1)), jnp.int32(0), jnp.asarray(ids),
        jnp.asarray(vals), jnp.asarray(labels), jnp.asarray(weights),
    )
    assert np.isneginf(float(loss))


def test_sharded_2d_overflow_sentinel_not_counted(rng):
    # On the 2-D mesh the ownership-mask sentinel segment must NOT count
    # as overflow: a field whose uniques exactly fill cap on one row
    # shard still trains with finite loss under policy 'error'.
    ids, vals, labels, weights = _batch(rng, b=64)
    spec = _spec()
    config = _base_cfg(sparse_update="dedup", compact_device=True,
                       compact_cap=64)
    mesh = make_field_mesh(8, n_row=2)
    sharded = make_field_sharded_sgd_step(spec, config, mesh)
    sp = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(1)), 4), mesh
    )
    batch = pad_field_batch((ids, vals, labels, weights), F, 4)
    sp, loss = sharded(sp, jnp.int32(0), *shard_field_batch(batch, mesh))
    assert np.isfinite(float(loss))


def test_config_validation():
    spec = _spec()
    with pytest.raises(ValueError, match="compact_device requires"):
        make_field_sparse_sgd_step(
            spec, _base_cfg(sparse_update="dedup", compact_device=True)
        )
    with pytest.raises(ValueError, match="exclusive"):
        make_field_sparse_sgd_step(
            spec, _base_cfg(sparse_update="dedup", compact_device=True,
                            host_dedup=True, compact_cap=8)
        )
    with pytest.raises(ValueError, match="device-side policy"):
        make_field_sparse_sgd_step(
            spec, _base_cfg(sparse_update="dedup", host_dedup=True,
                            compact_cap=8, compact_overflow="drop")
        )
    with pytest.raises(ValueError, match="host-pipeline policy"):
        make_field_sparse_sgd_step(
            spec, _base_cfg(sparse_update="dedup", compact_device=True,
                            compact_cap=8, compact_overflow="split")
        )
    # A non-default overflow policy without a cap is a silent no-op —
    # rejected (ADVICE r3).
    for policy in ("drop", "split"):
        with pytest.raises(ValueError, match="no.*effect|no effect"):
            make_field_sparse_sgd_step(
                spec, _base_cfg(compact_overflow=policy)
            )
    # The 'error' policy's -inf sentinel requires a provably
    # non-negative loss; an unlisted loss must fail at construction,
    # not silently corrupt the sentinel (ADVICE r4).
    from fm_spark_tpu.sparse import _check_host_dedup

    with pytest.raises(ValueError, match="non-negative losses"):
        _check_host_dedup(
            _base_cfg(sparse_update="dedup_sr", compact_device=True,
                      compact_cap=8, compact_overflow="error"),
            "exotic_negative_loss",
        )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_ffm_device_matches_host_compact(rng, mode):
    """FieldFFM fused step via the shared _rows_for dispatch: device-
    built aux == host-built aux bitwise (stable sorts agree)."""
    from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step

    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.2, optimizer="sgd", sparse_update=mode)
    params = spec.init(jax.random.key(1))
    params_c = jax.tree.map(jnp.copy, params)
    step_h = make_field_ffm_sparse_sgd_step(
        spec, TrainConfig(host_dedup=True, compact_cap=CAP, **cfg)
    )
    step_d = make_field_ffm_sparse_sgd_step(
        spec, TrainConfig(compact_device=True, compact_cap=CAP, **cfg)
    )
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids_np, CAP))
    for i in range(2):
        params, _ = step_h(params, jnp.int32(i), *batch, aux)
        params_c, _ = step_d(params_c, jnp.int32(i), *batch)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32)
        ),
        params, params_c,
    )


@pytest.mark.slow
def test_deepfm_device_matches_host_compact(rng):
    """FieldDeepFM hybrid step: device-built aux == host-built aux."""
    from fm_spark_tpu.sparse import make_field_deepfm_sparse_step

    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.05, optimizer="adam", sparse_update="dedup")
    params = spec.init(jax.random.key(2))
    params_c = jax.tree.map(jnp.copy, params)
    step_h = make_field_deepfm_sparse_step(
        spec, TrainConfig(host_dedup=True, compact_cap=CAP, **cfg)
    )
    step_d = make_field_deepfm_sparse_step(
        spec, TrainConfig(compact_device=True, compact_cap=CAP, **cfg)
    )
    opt_h = step_h.init_opt_state(params)
    opt_d = step_d.init_opt_state(params_c)
    aux = tuple(jnp.asarray(a) for a in compact_aux(ids_np, CAP))
    for i in range(2):
        params, opt_h, _ = step_h(params, opt_h, jnp.int32(i), *batch, aux)
        params_c, opt_d, _ = step_d(params_c, opt_d, jnp.int32(i), *batch)
    # The two programs differ (aux built in-step), so XLA may fuse the
    # dense MLP reductions differently — tight allclose, not bitwise.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=1e-8,
        ),
        params, params_c,
    )


class _ListSource:
    def __init__(self, batches):
        self._batches = list(batches)
        self._i = 0

    def next_batch(self):
        b = self._batches[self._i % len(self._batches)]
        self._i += 1
        return b

    def state(self):
        return {"i": self._i}

    def restore(self, state):
        self._i = int(state["i"])


def test_host_overflow_split_trains_through(rng):
    """VERDICT r2 #4: an adversarial batch (one near-unique field whose
    uniques exceed cap) must TRAIN THROUGH under compact_overflow=
    'split' — halved, inert-padded to the static batch shape, exact
    semantics per half — instead of killing the run."""
    from fm_spark_tpu.data import DedupAuxBatches

    b, cap = 48, 16
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)  # 48 uniques > 16
    src = _ListSource([(ids, vals, labels, weights)])
    wrapped = DedupAuxBatches(src, cap=cap, overflow="split")
    spec = _spec()
    step = make_field_sparse_sgd_step(
        spec, _base_cfg(sparse_update="dedup", host_dedup=True,
                        compact_cap=cap, compact_overflow="split"),
    )
    params = spec.init(jax.random.key(1))
    losses = []
    for i in range(4):  # 48/16 → split to quarters: 4 sub-batches queued
        bi = wrapped.next_batch()
        assert bi[0].shape == (b, F)            # static step shape kept
        aux = tuple(jnp.asarray(a) for a in bi[4])
        params, loss = step(
            params, jnp.int32(i), jnp.asarray(bi[0]), jnp.asarray(bi[1]),
            jnp.asarray(bi[2]), jnp.asarray(bi[3]), aux,
        )
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    # All four sub-batches came from the ONE source batch.
    assert src._i == 1
    # Real rows partition the batch: total live weight across the splits
    # equals the original batch's.
    # (weights zeroed every 7th row in _batch)
    # Re-generate the four sub-batches to check the partition property.
    src2 = _ListSource([(ids, vals, labels, weights)])
    w2 = DedupAuxBatches(src2, cap=cap, overflow="split")
    tot = sum(float(w2.next_batch()[3].sum()) for _ in range(4))
    assert tot == float(weights.sum())


def test_host_overflow_error_still_raises(rng):
    from fm_spark_tpu.data import DedupAuxBatches
    from fm_spark_tpu.ops.scatter import CompactCapOverflow

    b, cap = 48, 16
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)
    wrapped = DedupAuxBatches(
        _ListSource([(ids, vals, labels, weights)]), cap=cap
    )
    with pytest.raises(CompactCapOverflow):
        wrapped.next_batch()


def test_split_state_replays_whole_batch(rng):
    """A checkpoint cursor taken while split halves are pending must
    point BEFORE the split source batch (resume replays it whole —
    duplicates allowed, silent skips never)."""
    from fm_spark_tpu.data import DedupAuxBatches

    b, cap = 48, 16
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)
    src = _ListSource([(ids, vals, labels, weights)])
    wrapped = DedupAuxBatches(src, cap=cap, overflow="split")
    wrapped.next_batch()                    # half 1 of the split
    assert wrapped.state() == {"i": 0}      # pre-split cursor
    for _ in range(3):
        wrapped.next_batch()                # drain remaining halves
    assert wrapped.state() == {"i": 1}      # batch consumed → advanced


def test_multistep_poison_is_sticky(rng):
    """The fori-rolled multistep must not swallow an inner step's −inf
    overflow poison when a later step is clean."""
    from fm_spark_tpu.sparse import make_field_sparse_multistep

    b, cap = 48, 8
    ids, vals, labels, weights = _batch(rng, b=b)
    ids2 = ids.copy()
    ids2[:, 2] = rng.permutation(b).astype(np.int32)  # overflows cap
    spec = _spec()
    cfg = TrainConfig(learning_rate=0.05, optimizer="sgd",
                      sparse_update="dedup", compact_device=True,
                      compact_cap=cap)  # compact_overflow='error'
    mstep = make_field_sparse_multistep(spec, cfg, 2)
    stack = lambda a, b_: jnp.stack([jnp.asarray(a), jnp.asarray(b_)])
    params, loss = mstep(
        spec.init(jax.random.key(1)), jnp.int32(0), jnp.int32(2),
        stack(ids2, ids), stack(vals, vals), stack(labels, labels),
        stack(weights, weights),
    )
    # Step 0 overflowed, step 1 was clean — the poison must survive.
    assert np.isneginf(float(loss))


def test_sharded_builders_validate_unconditionally():
    """compact_device without compact_cap must fail at BUILD time on the
    sharded factories exactly as on the single-chip ones (review r3
    finding: the sharded builders used to validate only when
    compact_cap > 0, silently training the plain path)."""
    from fm_spark_tpu.parallel import (
        make_field_ffm_sharded_body,
        make_field_sharded_sgd_body,
    )

    mesh = make_field_mesh(8)
    cfg = _base_cfg(sparse_update="dedup", compact_device=True)
    with pytest.raises(ValueError, match="compact_device requires"):
        make_field_sharded_sgd_body(_spec(), cfg, mesh)
    ffm_spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    with pytest.raises(ValueError, match="compact_device requires"):
        make_field_ffm_sharded_body(ffm_spec, cfg, mesh)


def test_sharded_deepfm_device_matches_single_chip(rng):
    """Sharded DeepFM with the device-built compact aux must match the
    single-chip device-compact DeepFM step (round-3 capability cell)."""
    from fm_spark_tpu.parallel import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
        unstack_field_deepfm_params,
    )
    from fm_spark_tpu.sparse import make_field_deepfm_sparse_step

    ids, vals, labels, weights = _batch(rng, b=64)
    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    config = _base_cfg(sparse_update="dedup", compact_device=True,
                       compact_cap=CAP, optimizer="adam")
    canonical = spec.init(jax.random.key(2))
    single = make_field_deepfm_sparse_step(spec, config)
    mesh = make_field_mesh(8)
    sharded = make_field_deepfm_sharded_step(spec, config, mesh)
    sp = shard_field_deepfm_params(
        stack_field_deepfm_params(
            spec, jax.tree.map(jnp.copy, canonical), 8
        ),
        mesh,
    )
    opt_s = single.init_opt_state(canonical)
    opt_sh = sharded.init_opt_state(sp)
    batch = pad_field_batch((ids, vals, labels, weights), F, 8)
    for i in range(3):
        canonical, opt_s, l1 = single(
            canonical, opt_s, jnp.int32(i), jnp.asarray(ids),
            jnp.asarray(vals), jnp.asarray(labels), jnp.asarray(weights),
        )
        sp, opt_sh, l2 = sharded(
            sp, opt_sh, jnp.int32(i), *shard_field_batch(batch, mesh)
        )
        assert float(l1) == pytest.approx(float(l2), rel=1e-5)
    got = unstack_field_deepfm_params(spec, jax.device_get(sp))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=1e-6,
        ),
        canonical, got,
    )


def test_sharded_deepfm_device_overflow_error(rng):
    """The overflow poison must propagate through the sharded DeepFM
    step's dense-optimizer wrapper too."""
    from fm_spark_tpu.parallel import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )

    b, cap = 64, 8
    ids, vals, labels, weights = _batch(rng, b=b)
    ids[:, 2] = rng.permutation(b).astype(np.int32)
    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    config = _base_cfg(sparse_update="dedup", compact_device=True,
                       compact_cap=cap, optimizer="adam")
    mesh = make_field_mesh(8)
    sharded = make_field_deepfm_sharded_step(spec, config, mesh)
    sp = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, spec.init(jax.random.key(2)), 8),
        mesh,
    )
    opt = sharded.init_opt_state(sp)
    batch = pad_field_batch((ids, vals, labels, weights), F, 8)
    sp, opt, loss = sharded(
        sp, opt, jnp.int32(0), *shard_field_batch(batch, mesh)
    )
    assert np.isneginf(float(loss))


@pytest.mark.parametrize("dev_compact", [False, True])
def test_sharded_deepfm_2d_matches_single_chip(rng, dev_compact):
    """DeepFM on the 2-D (feat, row) mesh — shared-forward refactor
    (round 3): ownership-masked gathers + row-psum'd deep-head input
    must match the single-chip step, with and without the device-built
    compact aux."""
    from fm_spark_tpu.parallel import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
        unstack_field_deepfm_params,
    )
    from fm_spark_tpu.sparse import make_field_deepfm_sparse_step

    ids, vals, labels, weights = _batch(rng, b=64)
    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    kw = dict(sparse_update="dedup", optimizer="adam")
    if dev_compact:
        kw.update(compact_device=True, compact_cap=CAP)
    config = _base_cfg(**kw)
    canonical = spec.init(jax.random.key(2))
    single = make_field_deepfm_sparse_step(spec, config)
    mesh = make_field_mesh(8, n_row=2)    # 4 feat x 2 row
    sharded = make_field_deepfm_sharded_step(spec, config, mesh)
    sp = shard_field_deepfm_params(
        stack_field_deepfm_params(
            spec, jax.tree.map(jnp.copy, canonical), 4
        ),
        mesh,
    )
    opt_s = single.init_opt_state(canonical)
    opt_sh = sharded.init_opt_state(sp)
    batch = pad_field_batch((ids, vals, labels, weights), F, 4)
    for i in range(3):
        canonical, opt_s, l1 = single(
            canonical, opt_s, jnp.int32(i), jnp.asarray(ids),
            jnp.asarray(vals), jnp.asarray(labels), jnp.asarray(weights),
        )
        sp, opt_sh, l2 = sharded(
            sp, opt_sh, jnp.int32(i), *shard_field_batch(batch, mesh)
        )
        assert float(l1) == pytest.approx(float(l2), rel=2e-5)
    got = unstack_field_deepfm_params(spec, jax.device_get(sp))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=2e-6,
        ),
        canonical, got,
    )


def test_overflow_guard_sticky():
    """ADVICE r3 + round-4 review: an overflow at step i followed by
    clean steps must still fail the NEXT boundary check — the guard is
    a running min, not a point read of the latest loss."""
    import jax.numpy as jnp

    from fm_spark_tpu.cli import _make_overflow_guard

    cfg = _base_cfg(sparse_update="dedup", compact_device=True,
                    compact_cap=8)  # compact_overflow defaults to error
    note, check, fetch = _make_overflow_guard(cfg)
    note(jnp.float32(0.69))
    check()  # clean so far
    note(jnp.float32(-jnp.inf))   # the poisoned step
    note(jnp.float32(0.55))       # clean again — must NOT clear it
    with pytest.raises(SystemExit, match="compact_cap overflow"):
        check()
    # fetch_loss shares the sticky detector.
    note2, _, fetch2 = _make_overflow_guard(cfg)
    note2(jnp.float32(-jnp.inf))
    note2(jnp.float32(0.5))
    with pytest.raises(SystemExit, match="compact_cap overflow"):
        fetch2(jnp.float32(0.5))
    # Inactive policy (drop): everything is a no-op / plain float.
    note3, check3, fetch3 = _make_overflow_guard(
        _base_cfg(sparse_update="dedup", compact_device=True,
                  compact_cap=8, compact_overflow="drop"))
    note3(jnp.float32(-jnp.inf))
    check3()
    assert fetch3(jnp.float32(0.5)) == np.float32(0.5)
