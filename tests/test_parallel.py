"""Sharded-vs-single-device equivalence on the 8-fake-CPU-device mesh.

These are the parity tests SURVEY.md §4 mandates: the identical shard_map/
psum code path that runs on a real v5e-8 executes here over 8 host devices
(the `local[*]` idiom). A DP step must match the single-device step; a
row-sharded step must match both; metrics must reduce identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.data import synthetic_ctr
from fm_spark_tpu.parallel import (
    make_mesh,
    make_parallel_eval_step,
    make_parallel_train_step,
    shard_batch,
    shard_params,
)
from fm_spark_tpu.train import TrainConfig, make_eval_step, make_train_step, make_optimizer
from fm_spark_tpu.utils import metrics as metrics_lib

N_FEATURES = 256
BATCH = 64


@pytest.fixture(scope="module")
def problem():
    ids, vals, labels = synthetic_ctr(BATCH * 4, N_FEATURES, 6, seed=5)
    return ids, vals, labels


def _single_device_reference(spec, config, batches):
    step = make_train_step(spec, config)
    params = spec.init(jax.random.key(config.seed))
    opt_state = make_optimizer(config).init(params)
    losses = []
    for ids, vals, labels, w in batches:
        params, opt_state, m = step(
            params, opt_state, jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(w),
        )
        losses.append(float(m["loss"]))
    return params, losses


def _batches(problem, steps):
    ids, vals, labels = problem
    out = []
    for i in range(steps):
        sl = slice(i * BATCH, (i + 1) * BATCH)
        out.append((ids[sl], vals[sl], labels[sl], np.ones(BATCH, np.float32)))
    return out


@pytest.mark.parametrize(
    "strategy,mesh_shape",
    [("dp", (8, 1)), ("row", (1, 8)), ("row", (4, 2)), ("row", (2, 4))],
)
def test_sharded_step_matches_single_device(problem, strategy, mesh_shape, eight_devices):
    spec = models.FMSpec(num_features=N_FEATURES, rank=8, init_std=0.1)
    config = TrainConfig(learning_rate=0.3, optimizer="sgd",
                         reg_linear=0.01, reg_factors=0.01, seed=2)
    batches = _batches(problem, 3)
    ref_params, ref_losses = _single_device_reference(spec, config, batches)

    mesh = make_mesh(*mesh_shape, devices=eight_devices)
    step = make_parallel_train_step(spec, config, mesh, strategy)
    params = shard_params(spec.init(jax.random.key(config.seed)), mesh, spec, strategy)
    opt_state = make_optimizer(config).init(params)
    losses = []
    for b in batches:
        sb = shard_batch(b, mesh)
        params, opt_state, m = step(params, opt_state, *sb)
        losses.append(float(m["loss"]))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)
    gathered = jax.tree_util.tree_map(np.asarray, jax.device_get(params))
    for key in ("w0", "w", "v"):
        np.testing.assert_allclose(
            gathered[key], np.asarray(ref_params[key]), rtol=1e-4, atol=1e-5,
            err_msg=f"param {key} diverged under {strategy} {mesh_shape}",
        )


@pytest.mark.slow
def test_dp_supports_ffm_and_deepfm(problem, eight_devices):
    ids, vals, labels = problem
    mesh = make_mesh(8, 1, devices=eight_devices)
    for spec in (
        models.FFMSpec(num_features=N_FEATURES, rank=4, num_fields=6),
        models.DeepFMSpec(num_features=N_FEATURES, rank=4, num_fields=6,
                          mlp_dims=(16, 16, 16)),
    ):
        config = TrainConfig(learning_rate=0.1, seed=0)
        batches = _batches(problem, 2)
        ref_params, ref_losses = _single_device_reference(spec, config, batches)
        step = make_parallel_train_step(spec, config, mesh, "dp")
        params = shard_params(spec.init(jax.random.key(0)), mesh, spec, "dp")
        opt_state = make_optimizer(config).init(params)
        losses = []
        for b in batches:
            params, opt_state, m = step(params, opt_state, *shard_batch(b, mesh))
            losses.append(float(m["loss"]))
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-5, atol=1e-6)


def test_row_rejects_non_fm(eight_devices):
    spec = models.FFMSpec(num_features=N_FEATURES, rank=4, num_fields=6)
    mesh = make_mesh(1, 8, devices=eight_devices)
    with pytest.raises(ValueError, match="FM family"):
        make_parallel_train_step(spec, TrainConfig(), mesh, "row")


def test_row_rejects_indivisible_table(eight_devices):
    spec = models.FMSpec(num_features=255, rank=4)
    mesh = make_mesh(1, 8, devices=eight_devices)
    with pytest.raises(ValueError, match="divisible"):
        make_parallel_train_step(spec, TrainConfig(), mesh, "row")


@pytest.mark.parametrize("strategy,mesh_shape", [("dp", (8, 1)), ("row", (2, 4))])
def test_sharded_eval_matches_single_device(problem, strategy, mesh_shape, eight_devices):
    spec = models.FMSpec(num_features=N_FEATURES, rank=8, init_std=0.1)
    params = spec.init(jax.random.key(9))
    ids, vals, labels = problem
    w = np.ones(ids.shape[0], np.float32)
    w[-10:] = 0.0

    ref_step = make_eval_step(spec)
    ref = metrics_lib.finalize_metrics(
        ref_step(params, metrics_lib.init_metrics(), jnp.asarray(ids),
                 jnp.asarray(vals), jnp.asarray(labels), jnp.asarray(w))
    )

    mesh = make_mesh(*mesh_shape, devices=eight_devices)
    estep = make_parallel_eval_step(spec, mesh, strategy)
    sp = shard_params(params, mesh, spec, strategy)
    sb = shard_batch((ids, vals, labels, w), mesh)
    out = metrics_lib.finalize_metrics(
        estep(sp, metrics_lib.init_metrics(), *sb)
    )
    for k in ("auc", "logloss", "count"):
        np.testing.assert_allclose(
            float(out[k]), float(ref[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )


@pytest.mark.parametrize("family", ["ffm", "deepfm"])
def test_dp_ffm_deepfm_trains_finite(eight_devices, family):
    # The reference's one true strategy (dp) must cover every model
    # family (SURVEY.md §2 parallelism table). NOTE the name: this fast
    # finiteness smoke used to be a second ``def
    # test_dp_supports_ffm_and_deepfm``, which silently SHADOWED the
    # stricter @slow loss-equivalence variant above (VERDICT r5 weak
    # #2) — Python keeps only the last binding, so the equivalence test
    # was never collected. Distinct names keep both live;
    # tests/test_no_shadowed_tests.py guards the whole suite against a
    # recurrence.
    import numpy as np

    from fm_spark_tpu import models
    from fm_spark_tpu.parallel import (
        make_mesh, make_parallel_train_step, shard_batch, shard_params,
    )
    from fm_spark_tpu.train import TrainConfig, make_optimizer

    num_features, nnz = 256, 4
    if family == "ffm":
        spec = models.FFMSpec(num_features=num_features, rank=4,
                              num_fields=nnz, init_std=0.05)
    else:
        spec = models.DeepFMSpec(num_features=num_features, rank=4,
                                 num_fields=nnz, mlp_dims=(16, 16, 16),
                                 init_std=0.05)
    config = TrainConfig(learning_rate=0.05, optimizer="adam",
                         reg_factors=1e-4)
    mesh = make_mesh(8, 1, devices=eight_devices)
    step = make_parallel_train_step(spec, config, mesh, "dp")
    params = shard_params(spec.init(jax.random.key(0)), mesh, spec, "dp")
    opt_state = make_optimizer(config).init(params)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(5):
        batch = shard_batch((
            rng.integers(0, num_features, size=(64, nnz)).astype(np.int32),
            np.ones((64, nnz), np.float32),
            rng.integers(0, 2, 64).astype(np.float32),
            np.ones((64,), np.float32),
        ), mesh)
        params, opt_state, m = step(params, opt_state, *batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
