"""score_sharded: example-sharded score/dscores math on the sharded FM
step must be EXACT vs the replicated computation.

Per-example score reduction and loss gradients are elementwise in the
example axis, so slicing them per chip and all_gathering dscores is the
same arithmetic on the same values — params must come out bit-identical;
only the scalar loss reassociates (psum of block partials).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.parallel import (
    make_field_mesh,
    make_field_sharded_sgd_step,
    pad_field_batch,
    shard_field_batch,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 32, 4, 64


def _spec():
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )


def _batches(rng, n=2):
    out = []
    for _ in range(n):
        ids = rng.integers(0, BUCKET, size=(B, F)).astype(np.int32)
        vals = rng.uniform(0.5, 1.5, size=(B, F)).astype(np.float32)
        labels = rng.integers(0, 2, B).astype(np.float32)
        weights = np.ones((B,), np.float32)
        weights[-5:] = 0.0
        out.append((ids, vals, labels, weights))
    return out


def _run(spec, config, mesh, n_feat, batches):
    params = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(9)), n_feat),
        mesh,
    )
    step = make_field_sharded_sgd_step(spec, config, mesh)
    for i, batch in enumerate(batches):
        sb = shard_field_batch(pad_field_batch(batch, F, n_feat), mesh)
        params, loss = step(params, jnp.int32(i), *sb)
    return unstack_field_params(spec, jax.device_get(params)), float(loss)


@pytest.mark.parametrize("n_row", [1, 2])
@pytest.mark.parametrize("extra", [
    {}, {"reg_factors": 1e-3, "reg_linear": 1e-4, "reg_bias": 1e-4},
    {"gfull_fused": True},
])
def test_score_sharded_bitwise_params(eight_devices, n_row, extra):
    n_feat = 4
    spec = _spec()
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    rng = np.random.default_rng(0)
    batches = _batches(rng)
    base = dict(learning_rate=0.3, optimizer="sgd", **extra)
    p_rep, l_rep = _run(spec, TrainConfig(**base), mesh, n_feat, batches)
    p_sh, l_sh = _run(spec, TrainConfig(**base, score_sharded=True),
                      mesh, n_feat, batches)
    np.testing.assert_allclose(l_rep, l_sh, rtol=1e-6)
    assert np.array_equal(p_rep["w0"], p_sh["w0"])
    for f in range(F):
        assert np.array_equal(p_rep["vw"][f], p_sh["vw"][f]), f


def test_score_sharded_composes_with_compact_device(eight_devices):
    # The full scale-out stack in one step: 2-D mesh + device-built
    # compact aux + bf16 wire + gfull + score sharding.
    n_feat, n_row = 4, 2
    spec = _spec()
    mesh = make_field_mesh(8, devices=eight_devices, n_row=n_row)
    rng = np.random.default_rng(1)
    config = TrainConfig(
        learning_rate=0.2, optimizer="sgd", sparse_update="dedup_sr",
        compact_device=True, compact_cap=B, score_sharded=True,
        collective_dtype="bfloat16", gfull_fused=True,
    )
    p, loss = _run(spec, config, mesh, n_feat, _batches(rng, n=1))
    assert np.isfinite(loss)


def test_score_sharded_rejected_where_unimplemented(eight_devices):
    from fm_spark_tpu.parallel import make_field_ffm_sharded_step
    from fm_spark_tpu.parallel.field_step import (
        make_field_deepfm_sharded_step,
    )
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step

    config = TrainConfig(optimizer="sgd", score_sharded=True)
    spec = _spec()
    with pytest.raises(ValueError, match="score_sharded"):
        make_field_sparse_sgd_step(spec, config)
    mesh = make_field_mesh(4, devices=eight_devices)
    ffm = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=2, num_fields=F, bucket=BUCKET)
    with pytest.raises(ValueError, match="score_sharded"):
        make_field_ffm_sharded_step(ffm, config, mesh)
    deep = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=2, num_fields=F, bucket=BUCKET,
        mlp_dims=(8,))
    with pytest.raises(ValueError, match="score_sharded"):
        make_field_deepfm_sharded_step(deep, config, mesh)
