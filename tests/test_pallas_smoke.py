"""Tier-1 interpret-mode smoke for EVERY Pallas kernel (ISSUE 8).

``ops.pallas_fused.interpret_smokes()`` is the registry: one tiny
interpret-mode invocation per shipped kernel. The smoke asserts each
runs finite, and pins the registry against the ``ops/pallas_*`` module
surface so a new kernel cannot ship unregistered (and therefore
unsmoked). Skips cleanly when Pallas interpret mode is unavailable on
the installed jax.
"""

import ast
import os

import numpy as np
import pytest

pallas = pytest.importorskip(
    "jax.experimental.pallas",
    reason="Pallas (and its interpret mode) unavailable on this jax")

from fm_spark_tpu.ops import pallas_fused  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OPS = os.path.join(REPO, "fm_spark_tpu", "ops")


def _smokes():
    try:
        return pallas_fused.interpret_smokes()
    except Exception as e:  # pragma: no cover - env-specific
        pytest.skip(f"Pallas interpret smokes unavailable: {e!r}")


def test_registry_names_every_kernel_module():
    """Every ops/pallas_*.py module must contribute at least one smoke
    (a module with zero registered kernels is dead or unsmoked)."""
    smokes = _smokes()
    modules = {name.split(".")[0] for name in smokes}
    on_disk = {f[:-3] for f in os.listdir(OPS)
               if f.startswith("pallas_") and f.endswith(".py")}
    assert on_disk == modules, (
        f"kernel modules {on_disk - modules} have no interpret smoke "
        f"registered in pallas_fused.interpret_smokes()")


def test_registry_covers_every_public_pallas_call():
    """Pin the registry against the modules' public API: every top-level
    public function that invokes pl.pallas_call (directly or via its
    module-private helper) must be registered. AST-derived so a new
    kernel entry point turns this red until it registers."""
    smokes = _smokes()
    registered = {name.split(".", 1)[1] for name in smokes}
    public_kernels = set()
    for fname in sorted(os.listdir(OPS)):
        if not (fname.startswith("pallas_") and fname.endswith(".py")):
            continue
        with open(os.path.join(OPS, fname)) as f:
            tree = ast.parse(f.read())
        # Functions that directly contain a pallas_call.
        callers = set()
        for node in tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "pallas_call"):
                    callers.add(node.name)
        # Public functions that are direct callers, or call a PRIVATE
        # direct caller (one hop — the _fwd_field pattern). The
        # availability probe is a probe, not a kernel.
        private_callers = {c for c in callers if c.startswith("_")}
        for node in tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name.startswith("_") or node.name == "pallas_probe":
                continue
            names = {sub.func.id for sub in ast.walk(node)
                     if isinstance(sub, ast.Call)
                     and isinstance(sub.func, ast.Name)}
            if node.name in callers or names & private_callers:
                public_kernels.add(node.name)
    missing = public_kernels - registered
    assert not missing, (
        f"public Pallas kernels {missing} are not registered in "
        "pallas_fused.interpret_smokes()")


@pytest.mark.parametrize("name", sorted(pallas_fused.interpret_smokes()))
def test_kernel_interpret_smoke(name):
    import jax

    out = pallas_fused.interpret_smokes()[name]()
    for leaf in jax.tree_util.tree_leaves(out):
        arr = np.asarray(leaf)
        assert arr.size > 0, name
        assert np.isfinite(arr).all(), f"{name} produced non-finite output"
