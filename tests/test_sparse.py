"""Fused sparse-SGD step vs the dense optax path: exact match at reg=0."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.data import synthetic_ctr
from fm_spark_tpu.sparse import make_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig, make_optimizer, make_train_step


@pytest.mark.parametrize("schedule", ["inv_sqrt", "constant"])
def test_sparse_matches_dense_sgd(schedule):
    ids, vals, labels = synthetic_ctr(256, 128, 5, seed=4)
    spec = models.FMSpec(num_features=128, rank=8, init_std=0.1)
    config = TrainConfig(learning_rate=0.3, lr_schedule=schedule, optimizer="sgd")

    dense_step = make_train_step(spec, config)
    sparse_step = make_sparse_sgd_step(spec, config)

    params_d = spec.init(jax.random.key(0))
    params_s = jax.tree_util.tree_map(jnp.copy, params_d)
    opt_state = make_optimizer(config).init(params_d)

    w = np.ones(64, np.float32)
    for i in range(4):
        sl = slice(i * 64, (i + 1) * 64)
        b = (jnp.asarray(ids[sl]), jnp.asarray(vals[sl]),
             jnp.asarray(labels[sl]), jnp.asarray(w))
        params_d, opt_state, m = dense_step(params_d, opt_state, *b)
        params_s, loss_s = sparse_step(params_s, jnp.int32(i), *b)
        np.testing.assert_allclose(float(loss_s), float(m["loss"]), rtol=1e-6)

    for key in ("w0", "w", "v"):
        np.testing.assert_allclose(
            np.asarray(params_s[key]), np.asarray(params_d[key]),
            rtol=1e-5, atol=1e-6, err_msg=key,
        )


def test_sparse_handles_duplicate_rows_in_batch():
    """Two examples sharing a feature id must both contribute (scatter-add)."""
    spec = models.FMSpec(num_features=10, rank=2, init_std=0.1)
    config = TrainConfig(learning_rate=0.1, lr_schedule="constant")
    dense_step = make_train_step(spec, config)
    sparse_step = make_sparse_sgd_step(spec, config)
    params_d = spec.init(jax.random.key(1))
    params_s = jax.tree_util.tree_map(jnp.copy, params_d)
    opt_state = make_optimizer(config).init(params_d)
    ids = jnp.asarray([[1, 2], [1, 3], [1, 2]], jnp.int32)  # id 1 in all rows
    vals = jnp.ones((3, 2))
    labels = jnp.asarray([1.0, 0.0, 1.0])
    w = jnp.ones((3,))
    params_d, _, _ = dense_step(params_d, opt_state, ids, vals, labels, w)
    params_s, _ = sparse_step(params_s, jnp.int32(0), ids, vals, labels, w)
    np.testing.assert_allclose(
        np.asarray(params_s["v"]), np.asarray(params_d["v"]), rtol=1e-5, atol=1e-7
    )


def test_sparse_rejects_wrong_family_or_optimizer():
    spec = models.FFMSpec(num_features=16, rank=2, num_fields=3)
    with pytest.raises(ValueError, match="FM family"):
        make_sparse_sgd_step(spec, TrainConfig())
    fm = models.FMSpec(num_features=16, rank=2)
    with pytest.raises(ValueError, match="SGD"):
        make_sparse_sgd_step(fm, TrainConfig(optimizer="adam"))
