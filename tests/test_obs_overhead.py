"""Tier-1 contracts for the telemetry plane's two hard promises
(ISSUE 7):

1. **Disabled-path overhead ≤1%** — library code instruments
   unconditionally (``with obs.span(...)`` in checkpoint/stream/
   supervisor, the latched ``obs.enabled()`` pattern in the trainer),
   so an UN-observed process must pay (almost) nothing. A 200-step
   synthetic train loop instrumented exactly like the hot paths is
   timed against its bare twin.

2. **SIGKILL-surviving flight recorder** — the whole point of the
   spool is that an *uncatchable* ending still leaves a parseable,
   complete last-N window on disk. A subprocess records events through
   the spool (past the compaction threshold) and SIGKILLs itself
   mid-stream; the parent asserts the window.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fm_spark_tpu import obs  # noqa: E402
from fm_spark_tpu.obs.flight import read_spool  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- overhead


def _spin(dur_s: float) -> int:
    """Deterministic busy work (a calibrated spin, not sleep: sleep's
    wake-up jitter would swamp a 1% bound)."""
    n = 0
    t_end = time.perf_counter() + dur_s
    while time.perf_counter() < t_end:
        n += 1
    return n


def _loop_bare(steps: int, step_s: float) -> float:
    t0 = time.perf_counter()
    for _ in range(steps):
        _spin(step_s)
    return time.perf_counter() - t0


def _loop_instrumented(steps: int, step_s: float) -> float:
    """The library's disabled-path instrumentation pattern per step:
    one unconditional ``with obs.span(...)`` (the stream/checkpoint
    idiom), the latched-flag check (the trainer idiom), and the
    ISSUE 14 introspection hooks — ``observe_step_time`` (the
    trainer's window feed) and ``fire`` (the sentinel/watchdog/serve
    hook) — both one module-global None check when no capture engine
    is armed — plus the ISSUE 18 request-trace hooks: ``mint_trace``
    (the front door's per-request mint, a no-op returning None when
    unconfigured) and the trace-aware exemplar observe. (The live
    endpoint, obs/export.py, is pull-model: an un-scraped process
    runs NO export code on any hot path, so there is nothing of it
    to time here.)"""
    from fm_spark_tpu.obs import introspect

    obs_on = obs.enabled()
    hist = obs.histogram("overhead_test_ms") if obs_on else None
    t0 = time.perf_counter()
    for _ in range(steps):
        ctx = obs.mint_trace()
        with obs.span("overhead/step"):
            _spin(step_s)
        if obs_on:
            hist.observe(0.0, exemplar=(ctx.trace_id
                                        if ctx is not None else None))
        introspect.observe_step_time(step_s * 1e3)
        introspect.fire("step_time_spike")
    return time.perf_counter() - t0


@pytest.mark.parametrize("steps,step_s", [(200, 0.0005)])
def test_disabled_tracing_overhead_under_1pct(steps, step_s):
    from fm_spark_tpu.obs import introspect

    obs.shutdown(reason=None)  # the disabled path is the unconfigured one
    introspect.clear()         # ...and the unarmed capture engine
    assert not obs.enabled()
    assert not introspect.active()
    # Warm both loops (bytecode/alloc effects), then take the best of 3
    # — min is the right statistic for a noise-floor comparison.
    _loop_bare(20, step_s)
    _loop_instrumented(20, step_s)
    bare = min(_loop_bare(steps, step_s) for _ in range(3))
    inst = min(_loop_instrumented(steps, step_s) for _ in range(3))
    overhead = inst / bare - 1.0
    # The contract is ≤1%; the spin calibration itself wobbles ~0.1%
    # on a loaded CI core, so the assert keeps a little of the budget.
    assert overhead <= 0.01, (
        f"disabled-path tracing overhead {overhead:.2%} over "
        f"{steps} steps (bare {bare:.4f}s vs instrumented {inst:.4f}s)")


def test_disabled_span_is_allocation_free_singleton():
    obs.shutdown(reason=None)
    assert obs.span("a") is obs.span("b")


# --------------------------------------------------------- SIGKILL drill

_DRILL = r"""
import os, signal, sys
sys.path.insert(0, {repo!r})
from fm_spark_tpu import obs

obs.configure({run_dir!r}, run_id="drill", flight_capacity=32,
              install_signals=False)
for i in range(100):          # 100 > 2*32: the spool compacts at least once
    obs.event("tick", i=i)
print("READY", flush=True)    # parent kills on this marker
os.kill(os.getpid(), signal.SIGKILL)
"""


def test_flight_spool_survives_sigkill(tmp_path):
    run_dir = str(tmp_path / "run")
    proc = subprocess.run(
        [sys.executable, "-c",
         _DRILL.format(repo=REPO, run_dir=run_dir)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    # SIGKILL death is the expected ending.
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    assert "READY" in proc.stdout

    window = read_spool(os.path.join(run_dir, "flight.jsonl"))
    ticks = [e for e in window if e.get("kind") == "tick"]
    # Complete last-N window: the final capacity's worth of events is
    # all present, in order, with contiguous sequence numbers.
    assert len(ticks) >= 32
    tail = ticks[-32:]
    assert [e["i"] for e in tail] == list(range(68, 100))
    seqs = [e["seq"] for e in window]
    assert seqs == sorted(seqs)
    assert all(b - a == 1 for a, b in zip(seqs, seqs[1:]))

    # A restarted process re-entering the run dir (the bench parent's
    # retry path) seeds its ring from the spool: window continuous.
    from fm_spark_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(32, spool_path=os.path.join(run_dir,
                                                    "flight.jsonl"))
    assert fr.events()[-1]["i"] == 99
    assert fr.record("resumed")["seq"] == seqs[-1] + 1
    fr.close()


def test_sigterm_dump_chains_and_leaves_window(tmp_path):
    """The *catchable* ending: obs.configure(install_signals=True)
    chains a dump onto SIGTERM, so the atomic flight_dump.json lands
    before death (what the flaky-attachment kills kept destroying)."""
    run_dir = str(tmp_path / "run")
    script = (
        "import os, signal, sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from fm_spark_tpu import obs\n"
        f"obs.configure({run_dir!r}, run_id='term', flight_capacity=16,\n"
        "              install_signals=True)\n"
        "for i in range(10):\n"
        "    obs.event('tick', i=i)\n"
        "print('READY', flush=True)\n"
        "signal.pause()\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        proc.kill()
    dump_path = os.path.join(run_dir, "flight_dump.json")
    assert os.path.exists(dump_path)
    with open(dump_path) as f:
        doc = json.load(f)
    assert doc["reason"].startswith("signal:")
    assert [e["i"] for e in doc["events"]
            if e["kind"] == "tick"] == list(range(10))
