"""Tier-1 wiring for the tools/resilience_lint.py COMPATIBILITY SHIM
(ISSUE 15): the six monolith rules now live in the fmlint registry
(fm_spark_tpu/analysis/, exercised per-rule in tests/test_fmlint.py);
this suite holds the shipped tree to them THROUGH the shim's historic
entry points, pins the shim's delegation, and keeps the
planted-violation coverage property for every resilience/serve module
(an exclusion bug must turn the suite red, not silently shrink the
scan).
"""

import importlib.util
import os
import sys

import pytest

from fm_spark_tpu.analysis import core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_shim():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    spec = importlib.util.spec_from_file_location(
        "resilience_lint_tool",
        os.path.join(tools, "resilience_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


SHIM_FUNCS = (
    "violations",
    "library_print_violations",
    "kernel_fallback_violations",
    "duration_time_violations",
    "bench_leg_record_violations",
    "fault_point_coverage_violations",
    "watchdog_phase_coverage_violations",
    "introspect_trigger_coverage_violations",
)


@pytest.mark.parametrize("fname", SHIM_FUNCS)
def test_shipped_tree_clean_through_every_shim_entry_point(fname):
    shim = _load_shim()
    found = getattr(shim, fname)()
    assert found == [], "\n".join(found)


def test_shim_main_is_the_full_fmlint_gate():
    shim = _load_shim()
    assert shim.main() == 0


def test_shim_rejects_legacy_scope_overrides():
    """The shim scans the shipped repo only: the historical per-call
    root/path/tests_dir overrides now fail LOUDLY instead of silently
    returning whole-repo results to a fixture-scanning caller
    (post-review hardening)."""
    shim = _load_shim()
    with pytest.raises(TypeError, match="no longer honors"):
        shim.violations("/tmp/somewhere")
    with pytest.raises(TypeError, match="no longer honors"):
        shim.fault_point_coverage_violations(tests_dir="/tmp/t")


def test_shim_exports_historical_constants():
    shim = _load_shim()
    assert os.path.isdir(shim.RESILIENCE_DIR)
    assert os.path.isdir(shim.SERVE_DIR)
    assert any(p.endswith(os.path.join("data", "stream.py"))
               for p in shim.EXTRA_FILES)


def test_shim_renders_historical_string_format(tmp_path):
    """Violation strings keep the ``path:line [func] message`` shape
    old consumers parsed — checked against a planted violation run
    through the registry rule the shim delegates to."""
    (tmp_path / "fm_spark_tpu" / "resilience").mkdir(parents=True)
    (tmp_path / "fm_spark_tpu" / "resilience" / "bad.py").write_text(
        "def transition(s):\n    print('open')\n")
    found, _ = core.run_rules(core.Context(str(tmp_path)),
                              rules=["eventlog-only"])
    rendered = [f"{f.path}:{f.line} [{f.func or '<module>'}] "
                f"{f.message}" for f in found]
    assert len(rendered) == 1
    assert rendered[0].startswith(
        "fm_spark_tpu/resilience/bad.py:2 [transition] ")
    assert "bare print" in rendered[0]


def _planted_copy(tmp_path, rel):
    """Copy a shipped module into a synthetic repo at the same
    relative path, with a violation appended."""
    src = os.path.join(REPO, rel)
    with open(src) as f:
        body = f.read()
    dst = tmp_path
    for part in rel.split("/"):
        dst = dst / part
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(body + "\n\ndef _planted_violation():\n"
                   "    print('x')\n")


def _strict_scope_modules():
    out = []
    for d in ("fm_spark_tpu/resilience", "fm_spark_tpu/serve"):
        for fname in sorted(os.listdir(os.path.join(REPO, d))):
            if fname.endswith(".py"):
                out.append(f"{d}/{fname}")
    out += ["fm_spark_tpu/data/stream.py",
            "fm_spark_tpu/data/native_stream.py",
            "fm_spark_tpu/native/__init__.py",
            "fm_spark_tpu/online.py"]
    return out


@pytest.mark.parametrize("rel", _strict_scope_modules())
def test_every_strict_scope_module_is_actually_scanned(rel, tmp_path):
    """The eventlog-only rule VISITS every module of the strict scope:
    a planted violation appended to a copy of each shipped file is
    flagged — so a future scope regression turns the suite red instead
    of silently shrinking coverage."""
    _planted_copy(tmp_path, rel)
    found, _ = core.run_rules(core.Context(str(tmp_path)),
                              rules=["eventlog-only"])
    assert any(f.path == rel and f.func == "_planted_violation"
               for f in found), [f.render() for f in found]


def test_registry_coverage_rule_sees_the_real_registries():
    """The three coverage anchors (KNOWN_POINTS / KNOWN_PHASES /
    TRIGGERS) all parse out of the shipped modules — if a refactor
    moves or renames a literal, this fails before the rule silently
    checks nothing."""
    ctx = core.Context(REPO)
    from fm_spark_tpu.analysis import rules_obs

    for kind, rel, literal in rules_obs.COVERAGE_REGISTRIES:
        got = rules_obs._literal_entries(ctx.file(rel), literal)
        assert got and got[0], f"{literal} not found in {rel}"
