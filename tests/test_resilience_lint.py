"""Tier-1 wiring for tools/resilience_lint.py (ISSUE 4 satellite):
every resilience/ state transition goes through utils/logging.EventLog
— no bare print, no ad-hoc JSON writes. The lint module owns the rules;
this suite (a) holds the shipped subsystem to them and (b) pins the
lint's own detection so a future refactor can't quietly lobotomize it.
"""

import importlib.util
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "resilience_lint_tool",
        os.path.join(REPO, "tools", "resilience_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_resilience_package_is_clean():
    lint = _load_lint()
    found = lint.violations()
    assert found == [], "\n".join(found)


def test_lint_catches_bare_print_and_adhoc_json(tmp_path):
    lint = _load_lint()
    (tmp_path / "bad.py").write_text(
        "import json, sys\n"
        "def transition(state):\n"
        "    print('circuit open')\n"
        "    sys.stderr.write('backing off\\n')\n"
        "    with open('events.json', 'w') as f:\n"
        "        json.dump({'event': 'backoff'}, f)\n"
        "    return json.dumps(state)\n"
    )
    found = lint.violations(str(tmp_path))
    assert len(found) == 4
    assert any("bare print" in v for v in found)
    assert any("json.dump)" in v for v in found)
    assert any("json.dumps)" in v for v in found)
    assert any("sys.stderr.write" in v for v in found)
    # Every violation names file, line, and enclosing function.
    assert all(v.startswith("bad.py:") and "[transition]" in v
               for v in found)


def test_lint_allowlist_is_scoped_to_the_named_function(tmp_path):
    lint = _load_lint()
    # Same call in a DIFFERENT function of the allowlisted file: flagged.
    (tmp_path / "faults.py").write_text(
        "import json\n"
        "def _next_count(point):\n"
        "    return json.dumps({point: 1})\n"   # allowlisted
        "def other(point):\n"
        "    return json.dumps({point: 1})\n"   # not allowlisted
    )
    found = lint.violations(str(tmp_path))
    assert len(found) == 1
    assert "[other]" in found[0]


def test_lint_cli_exit_status(tmp_path, capsys, monkeypatch):
    lint = _load_lint()
    assert lint.main() == 0  # the shipped package is clean
    monkeypatch.setattr(lint, "RESILIENCE_DIR", str(tmp_path))
    (tmp_path / "m.py").write_text("print('x')\n")
    monkeypatch.setattr(
        lint, "violations",
        lambda root=str(tmp_path): lint._violations_in_tree(
            __import__("ast").parse("print('x')"), "m.py"))
    assert lint.main() == 1


def test_lint_default_surface_includes_data_stream(tmp_path, monkeypatch):
    """ISSUE 5: data/stream.py's quarantine/abort transitions carry the
    same EventLog-only contract, so the DEFAULT lint surface must scan
    it — a planted violation in a swapped-in copy is flagged, proving
    the extra-files hook actually runs (not just lists)."""
    lint = _load_lint()
    assert any(p.endswith(os.path.join("data", "stream.py"))
               for p in lint.EXTRA_FILES)
    src = lint.EXTRA_FILES[0]
    with open(src) as f:
        body = f.read()
    planted = tmp_path / "stream.py"
    planted.write_text(
        body + "\n\ndef _planted_violation():\n    print('x')\n")
    monkeypatch.setattr(lint, "EXTRA_FILES", (str(planted),))
    found = lint.violations()
    assert any(v.startswith("stream.py:") and "_planted_violation" in v
               for v in found), found
    # An explicit-root call (the tmp-dir test idiom) stays scoped to
    # that root — extra files are a default-surface property.
    assert lint.violations(os.path.join(REPO, "fm_spark_tpu",
                                        "resilience")) == []


def test_duration_rule_catches_wallclock_subtraction(tmp_path):
    """ISSUE 9: time.time() inside a subtraction is a wall-clock
    DURATION — flagged in every form the codebase could write it
    (module alias, import alias, bare import, either operand,
    augmented assignment); timestamp uses stay legal."""
    lint = _load_lint()
    (tmp_path / "dur.py").write_text(
        "import time\n"
        "import time as _time\n"
        "def measure(t0, t1):\n"
        "    a = time.time() - t0\n"
        "    b = t1 - _time.time()\n"
        "    c = time() - t0\n"          # the from-import form
        "    t1 -= time.time()\n"
        "    ok = {'ts': time.time()}\n"          # timestamp: legal
        "    ok2 = time.perf_counter() - t0\n"    # monotonic: legal
        "    return a, b, c, ok, ok2\n"
    )
    import ast as _ast

    found = lint._duration_violations_in_tree(
        _ast.parse((tmp_path / "dur.py").read_text()), "dur.py")
    assert len(found) == 4
    assert all("perf_counter" in v and "[measure]" in v for v in found)


def test_duration_rule_follows_import_aliases(tmp_path):
    """'import time as t' / 'from time import time as now' must not
    evade the ban — the rule reads the file's own import aliases."""
    lint = _load_lint()
    src = (
        "import time as t\n"
        "from time import time as now\n"
        "def measure(t0):\n"
        "    a = t.time() - t0\n"
        "    b = now() - t0\n"
        "    ok = t.perf_counter() - t0\n"   # monotonic: legal
        "    return a, b, ok\n"
    )
    import ast as _ast

    found = lint._duration_violations_in_tree(_ast.parse(src), "al.py")
    assert len(found) == 2


def test_duration_rule_shipped_library_is_clean():
    lint = _load_lint()
    assert lint.duration_time_violations() == []


def test_duration_rule_walks_the_library(tmp_path):
    """The scan actually visits files under an arbitrary root."""
    lint = _load_lint()
    sub = tmp_path / "pkg"
    sub.mkdir()
    (sub / "m.py").write_text(
        "import time\ndt = time.time() - 5.0\n")
    found = lint.duration_time_violations(str(tmp_path))
    assert len(found) == 1 and "<module>" in found[0]


def test_bench_leg_record_rule_shipped_bench_is_clean():
    lint = _load_lint()
    assert lint.bench_leg_record_violations() == []


def test_bench_leg_record_rule_catches_missing_provenance(tmp_path):
    lint = _load_lint()
    bad = tmp_path / "bench.py"
    bad.write_text(
        "leg_record = {'variant': label, 'value': 1.0}\n")
    found = lint.bench_leg_record_violations(str(bad))
    assert len(found) == 1
    assert "run_id" in found[0] and "fingerprint" in found[0]
    # No leg_record literal at all: the contract has no anchor.
    none = tmp_path / "empty.py"
    none.write_text("x = 1\n")
    found = lint.bench_leg_record_violations(str(none))
    assert len(found) == 1 and "no leg_record" in found[0]


def test_new_rules_wired_into_main(monkeypatch, capsys):
    """main() runs the ISSUE 9 rules — a planted violation in either
    fails the lint exit status."""
    lint = _load_lint()
    monkeypatch.setattr(lint, "duration_time_violations",
                        lambda root=None: ["dur.py:1 planted"])
    assert lint.main() == 1
    monkeypatch.setattr(lint, "duration_time_violations",
                        lambda root=None: [])
    monkeypatch.setattr(lint, "bench_leg_record_violations",
                        lambda path=None: ["bench.py:1 planted"])
    assert lint.main() == 1


@pytest.mark.parametrize("fname", sorted(
    f for f in os.listdir(os.path.join(REPO, "fm_spark_tpu", "resilience"))
    if f.endswith(".py")
))
def test_every_resilience_module_is_covered(fname, tmp_path):
    """The lint actually VISITS every module of the real package: a
    planted violation appended to a copy of each shipped file is
    flagged — so an exclusion bug (or a skipped file) turns the suite
    red instead of silently shrinking coverage."""
    lint = _load_lint()
    src = os.path.join(lint.RESILIENCE_DIR, fname)
    with open(src) as f:
        body = f.read()
    (tmp_path / fname).write_text(
        body + "\n\ndef _planted_violation():\n    print('x')\n")
    found = lint.violations(str(tmp_path))
    assert any(v.startswith(f"{fname}:") and "_planted_violation" in v
               for v in found), found


def test_fault_point_coverage_clean_on_shipped_registry():
    """ISSUE 10 satellite: every KNOWN_POINTS entry is exercised by at
    least one tier-1 test in the shipped tree."""
    lint = _load_lint()
    found = lint.fault_point_coverage_violations()
    assert found == [], "\n".join(found)


def test_fault_point_coverage_catches_untested_point(tmp_path):
    """A new injection point with no test naming it turns the lint red
    — new fault points can't ship untested."""
    lint = _load_lint()
    faults_py = tmp_path / "faults.py"
    faults_py.write_text(
        'KNOWN_POINTS = (\n    "train_step",\n    "brand_new_point",\n)\n')
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_x.py").write_text(
        'def test_a():\n    assert "train_step"\n')
    found = lint.fault_point_coverage_violations(
        tests_dir=str(tests_dir), faults_path=str(faults_py))
    assert len(found) == 1
    assert "brand_new_point" in found[0]
    # And a registry nobody can find is itself a violation, not a pass.
    empty = tmp_path / "empty.py"
    empty.write_text("x = 1\n")
    found = lint.fault_point_coverage_violations(
        tests_dir=str(tests_dir), faults_path=str(empty))
    assert found and "no KNOWN_POINTS" in found[0]


# ----------------------------------------- watchdog phase coverage (ISSUE 12)


def test_watchdog_phase_coverage_clean_on_shipped_registry():
    """Every KNOWN_PHASES entry — including the new serve_request SLO
    phase — is exercised by at least one tier-1 test in the tree."""
    lint = _load_lint()
    found = lint.watchdog_phase_coverage_violations()
    assert found == [], "\n".join(found)


def test_watchdog_phase_coverage_catches_unarmed_phase(tmp_path):
    """A guarded phase no test names turns the lint red — deadlines
    can't ship unexercised, same policy as fault points."""
    lint = _load_lint()
    wd = tmp_path / "watchdog.py"
    wd.write_text(
        'KNOWN_PHASES = (\n    "step_window",\n    "brand_new_phase",\n)\n')
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_x.py").write_text(
        'def test_a():\n    assert "step_window"\n')
    found = lint.watchdog_phase_coverage_violations(
        tests_dir=str(tests_dir), watchdog_path=str(wd))
    assert len(found) == 1 and "brand_new_phase" in found[0]
    empty = tmp_path / "empty.py"
    empty.write_text("x = 1\n")
    found = lint.watchdog_phase_coverage_violations(
        tests_dir=str(tests_dir), watchdog_path=str(empty))
    assert found and "no KNOWN_PHASES" in found[0]


def test_serve_runtime_in_strict_eventlog_scope():
    """ISSUE 12: the serving runtime's state transitions are held to
    the EventLog-only rule — the default-scope scan covers serve/."""
    lint = _load_lint()
    assert os.path.isdir(lint.SERVE_DIR)
    # The shipped serve/ modules are clean under the full default scan.
    assert lint.violations() == []


# ------------------------------------ introspection triggers (ISSUE 14)


def test_introspect_trigger_coverage_clean_on_shipped_registry():
    """Every TRIGGERS entry in obs/introspect.py — sentinel_regressed,
    watchdog_near_miss, serve_slo_overrun, step_time_spike — is fired
    by at least one tier-1 test in the tree."""
    lint = _load_lint()
    found = lint.introspect_trigger_coverage_violations()
    assert found == [], "\n".join(found)


def test_introspect_trigger_coverage_catches_untested_trigger(tmp_path):
    """A capture trigger no test fires turns the lint red — deep-
    profiling paths can't ship unexercised, same policy as fault
    points and watchdog phases."""
    lint = _load_lint()
    intro = tmp_path / "introspect.py"
    intro.write_text(
        'TRIGGERS = (\n    "step_time_spike",\n'
        '    "brand_new_trigger",\n)\n')
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_x.py").write_text(
        'def test_a():\n    assert "step_time_spike"\n')
    found = lint.introspect_trigger_coverage_violations(
        tests_dir=str(tests_dir), introspect_path=str(intro))
    assert len(found) == 1 and "brand_new_trigger" in found[0]
    empty = tmp_path / "empty.py"
    empty.write_text("x = 1\n")
    found = lint.introspect_trigger_coverage_violations(
        tests_dir=str(tests_dir), introspect_path=str(empty))
    assert found and "no TRIGGERS" in found[0]


def test_introspect_trigger_rule_wired_into_main(monkeypatch):
    """main() runs the ISSUE 14 rule — a planted violation fails the
    lint exit status."""
    lint = _load_lint()
    monkeypatch.setattr(lint, "introspect_trigger_coverage_violations",
                        lambda **kw: ["introspect.py:1 planted"])
    assert lint.main() == 1
