"""bf16 table storage: quality must stay near fp32 (BASELINE AUC budget).

PERF.md: bf16 tables more than halve gather cost on TPU (table-byte
cliff), and BASELINE.json:5 allows bf16 factors with fp32 accumulation iff
AUC stays within 1e-3 of baseline. The risk is the in-place scatter-add:
tiny SGD updates can vanish against bf16's 8-bit mantissa. These tests
pin the quality envelope on the planted-FM synthetic task.
"""

import jax
import jax.numpy as jnp
import pytest
import numpy as np

from fm_spark_tpu import models
from fm_spark_tpu.data import synthetic_ctr, train_test_split
from fm_spark_tpu.sparse import make_field_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig, evaluate_params
from fm_spark_tpu.data.pipeline import Batches, iterate_once


def _train_auc(param_dtype, seed=0, steps=800, batch=256,
               sparse_update="scatter_add"):
    num_fields, bucket, rank = 5, 64, 8
    ids, vals, labels = synthetic_ctr(
        8000, num_fields * bucket, num_fields, rank=4, seed=seed
    )
    # Field-local ids for the FieldFM layout.
    offs = (np.arange(num_fields) * bucket).astype(np.int32)
    ids = ids - offs[None, :]
    tr, te = train_test_split(ids, vals, labels, 0.25, seed=seed)
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank, num_fields=num_fields,
        bucket=bucket, init_std=0.05, param_dtype=param_dtype,
    )
    config = TrainConfig(learning_rate=0.2, lr_schedule="constant",
                         optimizer="sgd", sparse_update=sparse_update)
    step = make_field_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(seed))
    batches = Batches(*tr, batch, seed=seed)
    for i in range(steps):
        b = batches.next_batch()
        params, _ = step(params, jnp.int32(i), *map(jnp.asarray, b))
    return evaluate_params(spec, params, iterate_once(*te, batch))["auc"]


@pytest.mark.slow
def test_bf16_tables_track_fp32_auc():
    auc32 = _train_auc("float32")
    auc16 = _train_auc("bfloat16")
    assert auc32 > 0.70, f"fp32 sanity floor failed: {auc32}"
    # Measured envelope (this task, 2026-07-29): bf16 in-place scatter-add
    # loses ~0.014 AUC to update-vanishing against the 8-bit mantissa —
    # OUTSIDE the 1e-3 budget, which is why bf16 storage is opt-in, not
    # the default (PERF.md "bf16 storage"). This test pins that envelope:
    # a collapse to ~0.5 (updates fully vanishing) must fail loudly.
    # The recovery path is sparse_update="dedup_sr" (the next test).
    assert auc16 > auc32 - 0.03, f"bf16 {auc16} vs fp32 {auc32}"


@pytest.mark.slow
def test_bf16_with_stochastic_rounding_recovers_fp32_quality():
    auc32 = _train_auc("float32")
    auc_sr = _train_auc("bfloat16", sparse_update="dedup_sr")
    # SR makes rounding unbiased: tiny updates land in expectation, so
    # bf16+SR must sit inside the BASELINE-style quality envelope.
    assert auc_sr > auc32 - 0.005, f"bf16+SR {auc_sr} vs fp32 {auc32}"


def test_bf16_updates_do_not_vanish():
    # After training, bf16 tables must have moved away from init.
    num_fields, bucket, rank = 3, 32, 4
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank, num_fields=num_fields,
        bucket=bucket, init_std=0.01, param_dtype="bfloat16",
    )
    config = TrainConfig(learning_rate=0.1, lr_schedule="constant",
                         optimizer="sgd")
    step = make_field_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(0))
    before = [np.asarray(t, np.float32).copy() for t in params["vw"]]
    rng = np.random.default_rng(0)
    for i in range(50):
        ids = rng.integers(0, bucket, size=(128, num_fields)).astype(np.int32)
        vals = np.ones((128, num_fields), np.float32)
        labels = rng.integers(0, 2, 128).astype(np.float32)
        w = np.ones((128,), np.float32)
        params, _ = step(params, jnp.int32(i), *map(jnp.asarray,
                                                    (ids, vals, labels, w)))
    moved = sum(
        float(np.abs(np.asarray(t, np.float32) - b).sum())
        for t, b in zip(params["vw"], before)
    )
    assert moved > 0.1, "bf16 scatter updates vanished"
