"""Streaming histogram AUC vs exact rank-based AUC; logloss accumulation."""

import jax.numpy as jnp
import numpy as np

from fm_spark_tpu.utils import metrics as m
from fm_spark_tpu.ops import losses


def _exact_auc(scores, labels):
    """O(n log n) rank AUC with midrank ties — the sklearn definition."""
    order = np.argsort(scores, kind="mergesort")
    s = np.asarray(scores)[order]
    y = np.asarray(labels)[order]
    # Midranks.
    ranks = np.empty_like(s, dtype=np.float64)
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and s[j + 1] == s[i]:
            j += 1
        ranks[i : j + 1] = (i + j) / 2.0 + 1.0
        i = j + 1
    pos = y > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_histogram_auc_matches_exact(rng):
    scores = rng.normal(size=(5000,)).astype(np.float32) * 2
    labels = (rng.random(5000) < 1 / (1 + np.exp(-scores))).astype(np.float32)
    state = m.init_metrics()
    per = losses.logistic_loss(jnp.asarray(scores), jnp.asarray(labels))
    state = m.update_metrics(state, jnp.asarray(scores), jnp.asarray(labels), per)
    out = m.finalize_metrics(state)
    exact = _exact_auc(scores, labels)
    assert abs(float(out["auc"]) - exact) < 2e-3
    np.testing.assert_allclose(float(out["logloss"]), float(jnp.mean(per)), rtol=1e-5)
    assert float(out["count"]) == 5000


def test_auc_streaming_invariance(rng):
    """Folding in one batch or many must give the identical histogram AUC."""
    scores = rng.normal(size=(1000,)).astype(np.float32)
    labels = rng.integers(0, 2, 1000).astype(np.float32)
    per = np.zeros(1000, np.float32)
    one = m.update_metrics(
        m.init_metrics(), jnp.asarray(scores), jnp.asarray(labels), jnp.asarray(per)
    )
    many = m.init_metrics()
    for i in range(0, 1000, 100):
        sl = slice(i, i + 100)
        many = m.update_metrics(
            many, jnp.asarray(scores[sl]), jnp.asarray(labels[sl]),
            jnp.asarray(per[sl]),
        )
    np.testing.assert_allclose(
        float(m.finalize_metrics(one)["auc"]), float(m.finalize_metrics(many)["auc"])
    )


def test_weighted_examples_ignored(rng):
    scores = rng.normal(size=(200,)).astype(np.float32)
    labels = rng.integers(0, 2, 200).astype(np.float32)
    per = np.ones(200, np.float32)
    w = np.ones(200, np.float32)
    w[100:] = 0.0
    masked = m.update_metrics(
        m.init_metrics(), jnp.asarray(scores), jnp.asarray(labels),
        jnp.asarray(per), jnp.asarray(w),
    )
    half = m.update_metrics(
        m.init_metrics(), jnp.asarray(scores[:100]), jnp.asarray(labels[:100]),
        jnp.asarray(per[:100]),
    )
    a, b = m.finalize_metrics(masked), m.finalize_metrics(half)
    np.testing.assert_allclose(float(a["auc"]), float(b["auc"]))
    np.testing.assert_allclose(float(a["count"]), 100)


def test_degenerate_single_class():
    scores = jnp.asarray([0.1, 0.2, 0.3])
    labels = jnp.ones((3,))
    state = m.update_metrics(
        m.init_metrics(), scores, labels, jnp.zeros((3,))
    )
    assert float(m.finalize_metrics(state)["auc"]) == 0.5  # defined fallback
