"""Pins for bench.py's default sweep grid (bench.default_variants).

The sweep's labels are the measurement's provenance — MEASURED.json and
every PERF.md table row is keyed by them — so a label that disagrees
with its TrainConfig silently corrupts the record (round 5 nearly
shipped exactly this: an insert-order bug put the composed variant
behind probes it was staged to precede). These tests pin label<->config
consistency and the salvage ordering without touching a device.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _grid(model, batch=1 << 17):
    head, tail = bench.default_variants(model, batch)
    return head + tail


def test_fm_label_config_consistency():
    for label, (pd, cd, layout), cfg in _grid("fm"):
        assert ("gfull" in label) == cfg.gfull_fused, label
        assert ("segtotal" in label) == cfg.segtotal_pallas, label
        assert ("fusedbwd" in label) == (cfg.fused_embed != "off"), label
        assert ("devaux" in label) == cfg.compact_device, label
        assert ("colT" in label) == (layout == "col"), label
        assert (f"compact{cfg.compact_cap}" in label) == (
            cfg.compact_cap > 0), label
        assert label.startswith(pd), label
        assert ("cd-bf16" in label) == (cd == "bfloat16"), label
        # compact aux comes from exactly one builder
        assert cfg.host_dedup != cfg.compact_device, label


def test_fm_salvage_order_composed_first():
    head, _ = bench.default_variants("fm", 1 << 17)
    cfgs = [c for _, _, c in head]
    # [0] measured winner (floor cap 12288, 1,422,411 on 2026-07-31);
    # [1] the fused Pallas backward challenger at the same floor cap
    # (ISSUE 8 — staged right after the incumbent, the round-5 selblk
    # pattern; 'require' so a no-Pallas attachment skips, never
    # silently pricing XLA under the fused label);
    # [2] the batch/10-bound cap leg (the formula-derived fallback);
    # [3] the historical-cap drift leg; [4][5] single-lever legs; [6]
    # the r3 winner closing the grid.
    assert cfgs[0].gfull_fused and cfgs[0].segtotal_pallas
    assert cfgs[0].compact_cap == 12288
    assert cfgs[1].fused_embed == "require"
    assert cfgs[1].compact_cap == 12288
    assert not cfgs[1].gfull_fused and not cfgs[1].segtotal_pallas
    assert cfgs[2].gfull_fused and cfgs[2].segtotal_pallas
    assert cfgs[2].compact_cap == 13312
    assert cfgs[3].gfull_fused and cfgs[3].segtotal_pallas
    assert cfgs[3].compact_cap == 16384
    assert cfgs[4].gfull_fused and not cfgs[4].segtotal_pallas
    assert cfgs[5].segtotal_pallas and not cfgs[5].gfull_fused
    assert not cfgs[6].gfull_fused and not cfgs[6].segtotal_pallas


def test_fm_tight_cap_bounds_measured_unique():
    # The tight cap must bound the bench batch's measured max per-field
    # unique count (Zipf 1.3, seed 0) or the staged A/B would die on
    # compact_overflow='error'; and it must be a multiple of segtotal's
    # 512 tile. Values measured 2026-07-31.
    for batch, max_unique in ((131072, 11990), (262144, 20109)):
        head, _ = bench.default_variants("fm", batch)
        tight = sorted({c.compact_cap for _, _, c in head})[0]
        assert tight % 512 == 0
        assert max_unique <= tight <= batch


def test_fm_cap_respects_small_batch():
    # No compact variant may cap above the batch (the aux builder would
    # allocate dead lanes); the tight-cap A/B additionally floors at 512
    # (segtotal's tile).
    for label, _, cfg in _grid("fm", batch=1024):
        if cfg.compact_cap:
            assert cfg.compact_cap in (512, 1024), label
            assert f"compact{cfg.compact_cap}" in label, label


def test_deepfm_grid():
    grid = _grid("deepfm")
    assert [c.optimizer for _, _, c in grid] == ["adam", "adam"]
    assert [c.gfull_fused for _, _, c in grid] == [False, True]
    for label, _, cfg in grid:
        assert ("gfull" in label) == cfg.gfull_fused, label
        assert ("segtotal" in label) == cfg.segtotal_pallas, label


def test_ffm_grid_no_compact():
    for label, _, cfg in _grid("ffm"):
        assert cfg.compact_cap == 0, "compact measured a loser on avazu"
        assert "compact" not in label
        assert ("selblk" in label) == cfg.sel_blocked, label
        assert ("selblk-pallas" in label) == (
            cfg.fused_embed != "off"), label


def test_comparable_variant_gate():
    # The MEASURED.json keep-best gate: non-default-shape labels (the
    # /b262144 batch A/B, any explicit --rank run) must never be
    # comparable with the recorded default-shape rates; every real
    # default-shape label must be.
    for bad in (
        "bfloat16/dedup_sr/compact26624/cd-bf16/gfull/segtotal/b262144",
        "float32/scatter_add/b2048",
        "bfloat16/dedup_sr/compact16384/cd-bf16/r32",
        "float32/scatter_add/b2048/r8",
    ):
        assert not bench.comparable_variant(bad), bad
    for ok in (
        "bfloat16/dedup_sr/compact16384/cd-bf16/gfull/segtotal",
        "float32/scatter_add/cd-bf16",
        "bfloat16/dedup_sr/compact16384/devaux/cd-bf16",
        "float32/dedup/compact16384",
        None,
    ):
        assert bench.comparable_variant(ok), ok


def test_fm_kaggle_grid():
    # Config 2's grid: cd-bf16-over-fp32 staged first (small-table
    # regime, the measured avazu-winner form), the criteo-winner form
    # second, bf16/dedup_sr as the tail sentinel; compact cap bounds
    # the measured 10,711 max per-field unique at B=131072.
    head, tail = bench.default_variants("fm_kaggle", 1 << 17)
    label0, (pd0, cd0, _), cfg0 = head[0]
    assert label0 == "float32/scatter_add/cd-bf16"
    assert (pd0, cd0) == ("float32", "bfloat16")
    label1, _, cfg1 = head[1]
    assert cfg1.compact_cap == 16384 and cfg1.host_dedup
    assert f"compact{cfg1.compact_cap}" in label1
    assert [c.sparse_update for _, _, c in tail] == ["dedup_sr"]


def test_ffm_salvage_order_measured_winner_first():
    head, tail = bench.default_variants("ffm", 1 << 17)
    # 816,553 on 2026-07-31 (MEASURED.json ffm_avazu): fp32 storage +
    # bf16 compute + scatter_add. Label<->config consistency matters
    # here doubly — cd-bf16 with FP32 storage is exact-storage, so the
    # label's "/cd-bf16" is the only record that compute ran in bf16.
    label, (pd, cd, layout), cfg = head[0]
    assert label == "float32/scatter_add/cd-bf16"
    assert (pd, cd) == ("float32", "bfloat16")
    assert cfg.sparse_update == "scatter_add"
    assert not cfg.host_dedup and not cfg.compact_device


@pytest.mark.slow
def test_default_grids_build_and_step():
    """Every default-sweep variant of every model must CONSTRUCT and run
    one step — label pins alone would let a variant that fails at build
    time (the class the sweep's per-variant guard logs and skips) go
    unnoticed until the driver's round-end bench. Tiny shapes; segtotal
    runs its interpret path off-TPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fm_spark_tpu import models
    from fm_spark_tpu.ops.scatter import compact_aux, dedup_aux
    from fm_spark_tpu.sparse import (
        make_field_deepfm_sparse_step,
        make_field_ffm_sparse_sgd_step,
        make_field_sparse_sgd_step,
    )

    B, F, BUCKET, RANK = 512, 4, 256, 8
    rng = np.random.default_rng(0)
    ids_np = (rng.zipf(1.3, size=(B, F)) % BUCKET).astype(np.int32)
    ids = jnp.asarray(ids_np)
    vals = jnp.ones((B, F), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, B), jnp.float32)
    weights = jnp.ones((B,), jnp.float32)

    for model in ("fm", "ffm", "deepfm", "fm_kaggle"):
        head, tail = bench.default_variants(model, B)
        assert head or tail, model
        for label, (pd, cd, layout), cfg in head + tail:
            # Mirror bench.make_spec's dtype fallback: a None compute
            # dtype means "the --compute-dtype default" (float32), NOT
            # dtype(None) — numpy canonicalizes the latter to float64.
            common = dict(
                num_features=F * BUCKET, rank=RANK, num_fields=F,
                bucket=BUCKET, init_std=0.01, param_dtype=pd,
                compute_dtype=cd or "float32",
            )
            aux = None
            if cfg.host_dedup:
                aux = (compact_aux(ids_np, cfg.compact_cap)
                       if cfg.compact_cap else dedup_aux(ids_np))
            if model == "ffm":
                spec = models.FieldFFMSpec(**common)
                step = make_field_ffm_sparse_sgd_step(spec, cfg)
            elif model == "deepfm":
                spec = models.FieldDeepFMSpec(**common, mlp_dims=(8, 8))
                step = make_field_deepfm_sparse_step(spec, cfg)
            else:
                spec = models.FieldFMSpec(
                    **common, table_layout=layout or "row")
                step = make_field_sparse_sgd_step(spec, cfg)
            params = spec.init(jax.random.key(0))
            if model == "deepfm":
                opt = step.init_opt_state(params)
                params, opt, loss = step(params, opt, jnp.int32(0), ids,
                                         vals, labels, weights, aux)
            else:
                params, loss = step(params, jnp.int32(0), ids, vals,
                                    labels, weights, aux)
            assert np.isfinite(float(loss)), f"{model}:{label}"


def test_dirty_input_leg_quarantines_exactly_the_injected_lines(tmp_path):
    """The --dirty-input leg (ISSUE 5): synthetic 3-shard dataset with
    deterministically corrupted lines streams through the quarantine
    policy; the stamped stats account for EVERY row and the dead-letter
    count equals the injected corruption."""
    logs = []
    stats = bench._dirty_input_leg(str(tmp_path), "fm", logs.append)
    assert stats["policy"] == "quarantine"
    assert stats["rows"] == 6000
    assert stats["injected_bad"] == 60
    assert stats["bad_records"] == 60
    assert stats["quarantine_exact"] is True
    assert stats["rows_per_sec"] > 0
    # Priced both ways (ISSUE 6): when the native chunk parser is
    # available the leg re-runs under it and asserts the quarantine
    # accounting is identical, not just similar.
    from fm_spark_tpu.data.native_stream import native_stream_supported

    if native_stream_supported("criteo", 39, 1 << 14):
        assert stats["rows_per_sec_native"] > 0
        assert stats["native_quarantine_exact"] is True
        assert stats["native_counters_match"] is True
    # The dead-letter journal landed beside the artifacts.
    from fm_spark_tpu.utils.logging import read_events

    events = read_events(
        os.path.join(str(tmp_path), "quarantine_fm", "deadletter.jsonl"))
    assert sum(1 for e in events if e["event"] == "bad_record") == 60
    assert logs and "quarantined" in logs[-1]


def test_fused_fallback_payload_never_keep_bests(monkeypatch, capsys):
    """The parent's MEASURED.json gate (ISSUE 8): a payload stamped
    fused_fallback — a fused-requested leg that ran the XLA path — must
    never update the recorded rate, exactly like a degraded one."""
    import json as _json

    from fm_spark_tpu import measured as measured_lib

    def _boom(*a, **kw):
        raise AssertionError("fused_fallback payload reached keep-best")

    monkeypatch.setattr(measured_lib, "update_entry", _boom)
    payload = {
        "metric": "criteo_fm_rank64_10Mfeat_samples_per_sec_per_chip",
        "value": 9e9, "unit": "samples/sec/chip",
        "variant": "bfloat16/dedup_sr/compact12288/cd-bf16/fusedbwd",
        "device": "TPU v5 lite", "fused_fallback": True,
    }
    monkeypatch.setitem(bench._SALVAGE, "line", _json.dumps(payload))
    monkeypatch.setitem(bench._SALVAGE, "emitted", False)
    bench._emit_final()  # must print the line but refuse the record
    out = capsys.readouterr().out
    assert _json.loads(out.strip().splitlines()[-1]) == payload

    # Control: the same payload WITHOUT the stamp reaches update_entry.
    called = {}
    monkeypatch.setattr(
        measured_lib, "update_entry",
        lambda entry, **kw: called.setdefault("entry", entry))
    clean = {k: v for k, v in payload.items() if k != "fused_fallback"}
    monkeypatch.setitem(bench._SALVAGE, "line", _json.dumps(clean))
    monkeypatch.setitem(bench._SALVAGE, "emitted", False)
    bench._emit_final()
    assert called, "clean payload should have reached keep-best"
