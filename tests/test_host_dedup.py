"""Host-assisted dedup (`TrainConfig.host_dedup`): the aux path must be
numerically identical to the device-sort dedup path (fp32; dedup_sr
draws SR noise at different lane positions, so bf16 equality is
distributional — pinned by the fp32 case where SR is the identity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import apply_row_updates, dedup_aux
from fm_spark_tpu.sparse import make_field_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 64, 4, 48


def test_dedup_aux_shapes_and_semantics(rng):
    ids = rng.integers(0, 10, size=(32, 3)).astype(np.int32)
    order, seg, useg, ord_first = dedup_aux(ids)
    for a in (order, seg, useg, ord_first):
        assert a.shape == (3, 32) and a.dtype == np.int32
    for f in range(3):
        uniq = np.unique(ids[:, f])
        nseg = seg[f].max() + 1
        assert nseg == uniq.size
        np.testing.assert_array_equal(np.sort(useg[f, :nseg]), uniq)
        assert (useg[f, nseg:] == np.iinfo(np.int32).max).all()
        # ord_first points at a lane that actually holds the unique id.
        for s in range(nseg):
            assert ids[ord_first[f, s], f] == useg[f, s]
        # order is the stable per-field argsort.
        np.testing.assert_array_equal(
            ids[order[f], f], np.sort(ids[:, f])
        )


def test_dedup_aux_native_matches_numpy(rng):
    """The C++ counting sort and the numpy stable argsort must agree
    bitwise (stability makes the permutation unique)."""
    from fm_spark_tpu import native
    from fm_spark_tpu.ops import scatter as scatter_lib

    if not native.available():
        pytest.skip(f"native library unavailable: {native.build_error()}")
    ids = rng.integers(0, 50, size=(257, 7)).astype(np.int32)
    got = native.dedup_aux_native(ids, 50)
    # Force the numpy fallback for the reference result.
    import unittest.mock as mock

    with mock.patch.object(native, "dedup_aux_native", lambda *a: None):
        want = scatter_lib.dedup_aux(ids)
    for g, w, name in zip(got, want, ("order", "seg", "useg", "ord_first")):
        np.testing.assert_array_equal(g, w, err_msg=name)


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_aux_apply_matches_device_dedup(rng, mode):
    table = jnp.asarray(rng.normal(size=(20, 6)), jnp.float32)
    ids_np = rng.integers(0, 20, size=(40,)).astype(np.int32)
    ids = jnp.asarray(ids_np)
    delta = jnp.asarray(rng.normal(size=(40, 6)), jnp.float32)
    old_rows = table[ids]
    key = jax.random.key(7)
    aux = tuple(jnp.asarray(a) for a in dedup_aux(ids_np))
    want = apply_row_updates(table, ids, delta, mode=mode, key=key,
                             old_rows=old_rows)
    got = apply_row_updates(table, ids, delta, mode=mode, key=key,
                            old_rows=old_rows, aux=aux)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_aux_rejects_scatter_add():
    table = jnp.zeros((4, 2))
    ids = jnp.zeros((4,), jnp.int32)
    aux = tuple(jnp.asarray(a) for a in dedup_aux(np.zeros(4, np.int32)))
    with pytest.raises(ValueError, match="dedup mode"):
        apply_row_updates(table, ids, jnp.zeros((4, 2)), mode="scatter_add",
                          aux=aux)


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_field_step_host_dedup_matches_device(rng, mode):
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, fused_linear=True,
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    ids = jnp.asarray(ids_np)
    vals = jnp.asarray(rng.normal(size=(B, F)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 2, B).astype(np.float32))
    w = jnp.ones((B,))
    cfg = dict(learning_rate=0.2, lr_schedule="inv_sqrt", optimizer="sgd",
               sparse_update=mode)
    params = spec.init(jax.random.key(0))
    params_h = jax.tree_util.tree_map(jnp.copy, params)
    step_d = make_field_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_h = make_field_sparse_sgd_step(
        spec, TrainConfig(host_dedup=True, **cfg)
    )
    aux = tuple(jnp.asarray(a) for a in dedup_aux(ids_np))
    for i in range(3):
        params, loss_d = step_d(params, jnp.int32(i), ids, vals, labels, w)
        params_h, loss_h = step_h(
            params_h, jnp.int32(i), ids, vals, labels, w, aux
        )
        np.testing.assert_allclose(float(loss_h), float(loss_d), rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_h["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-5, atol=1e-7, err_msg=f"field {f}",
        )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_ffm_step_host_dedup_matches_device(rng, mode):
    from fm_spark_tpu.sparse import make_field_ffm_sparse_sgd_step

    spec = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=3, num_fields=F, bucket=BUCKET,
        init_std=0.1,
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.2, optimizer="sgd", sparse_update=mode)
    params = spec.init(jax.random.key(1))
    params_h = jax.tree_util.tree_map(jnp.copy, params)
    step_d = make_field_ffm_sparse_sgd_step(spec, TrainConfig(**cfg))
    step_h = make_field_ffm_sparse_sgd_step(
        spec, TrainConfig(host_dedup=True, **cfg)
    )
    aux = tuple(jnp.asarray(a) for a in dedup_aux(ids_np))
    for i in range(2):
        params, _ = step_d(params, jnp.int32(i), *batch)
        params_h, _ = step_h(params_h, jnp.int32(i), *batch, aux)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_h["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )


@pytest.mark.parametrize("mode", ["dedup", "dedup_sr"])
def test_deepfm_step_host_dedup_matches_device(rng, mode):
    from fm_spark_tpu.sparse import make_field_deepfm_sparse_step

    spec = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, mlp_dims=(8, 8),
    )
    ids_np = rng.integers(0, 8, size=(B, F)).astype(np.int32)
    batch = (jnp.asarray(ids_np),
             jnp.asarray(rng.normal(size=(B, F)).astype(np.float32)),
             jnp.asarray(rng.integers(0, 2, B).astype(np.float32)),
             jnp.ones((B,)))
    cfg = dict(learning_rate=0.05, optimizer="adam", sparse_update=mode)
    params = spec.init(jax.random.key(2))
    params_h = jax.tree_util.tree_map(jnp.copy, params)
    step_d = make_field_deepfm_sparse_step(spec, TrainConfig(**cfg))
    step_h = make_field_deepfm_sparse_step(
        spec, TrainConfig(host_dedup=True, **cfg)
    )
    opt_d = step_d.init_opt_state(params)
    opt_h = step_h.init_opt_state(params_h)
    aux = tuple(jnp.asarray(a) for a in dedup_aux(ids_np))
    for i in range(2):
        params, opt_d, _ = step_d(params, opt_d, jnp.int32(i), *batch)
        params_h, opt_h, _ = step_h(params_h, opt_h, jnp.int32(i), *batch,
                                    aux)
    for f in range(F):
        np.testing.assert_allclose(
            np.asarray(params_h["vw"][f]), np.asarray(params["vw"][f]),
            rtol=1e-5, atol=1e-7,
        )


def test_host_dedup_requires_dedup_mode():
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
    )
    with pytest.raises(ValueError, match="host_dedup"):
        make_field_sparse_sgd_step(
            spec, TrainConfig(optimizer="sgd", host_dedup=True)
        )


def test_dedup_aux_batches_wrapper(rng):
    from fm_spark_tpu.data import Batches, DedupAuxBatches

    ids = rng.integers(0, 16, size=(64, 3)).astype(np.int32)
    vals = np.ones((64, 3), np.float32)
    labels = rng.integers(0, 2, 64).astype(np.float32)
    src = Batches(ids, vals, labels, batch_size=32, seed=0)
    wrapped = DedupAuxBatches(src)
    b = wrapped.next_batch()
    assert len(b) == 5
    bids, _, _, _, aux = b
    order, seg, useg, ord_first = aux
    assert order.shape == (bids.shape[1], bids.shape[0])
    # The aux actually corresponds to THIS batch's ids.
    o2, s2, u2, of2 = dedup_aux(np.asarray(bids))
    np.testing.assert_array_equal(order, o2)
    np.testing.assert_array_equal(useg, u2)


@pytest.mark.slow
def test_cli_train_host_dedup_smoke(tmp_path):
    """End-to-end: fmtpu train --host-dedup trains via the aux fast path.

    Subprocess with ONE cpu device — the suite's 8-fake-device mesh would
    route field_sparse to the sharded step, which (by design) rejects
    host_dedup."""
    import os
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (
        os.path.dirname(os.path.dirname(__file__))
        + os.pathsep + env.get("PYTHONPATH", "")
    )
    proc = subprocess.run(
        [sys.executable, "-m", "fm_spark_tpu.cli",
         "train", "--config", "criteo1tb_fm_r64", "--synthetic", "4096",
         "--steps", "15", "--batch-size", "512",
         "--strategy", "field_sparse",
         "--sparse-update", "dedup", "--host-dedup", "--prefetch", "2",
         "--test-fraction", "0.2", "--log-every", "5"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert '"eval"' in proc.stdout or "auc" in proc.stdout


def test_cli_train_host_dedup_rejects_wrong_strategy():
    from fm_spark_tpu import cli

    with pytest.raises(SystemExit, match="field_sparse"):
        cli.main([
            "train", "--config", "criteo1tb_fm_r64", "--synthetic", "1024",
            "--steps", "2", "--batch-size", "256", "--strategy", "single",
            "--sparse-update", "dedup", "--host-dedup",
        ])


def test_dedup_aux_empty_batch():
    out = dedup_aux(np.zeros((0, 3), np.int32))
    for a in out:
        assert a.shape == (3, 0) and a.dtype == np.int32
