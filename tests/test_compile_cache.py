"""Warm-start contract (ISSUE 1): the persistent compile cache and the
AOT lower/compile entries.

The load-bearing test is the CROSS-PROCESS one: a cold process
populates the cache dir, and a second process compiling the same
winner-variant step performs ZERO fresh XLA compilations (every compile
request is a cache hit) — the property that turns a flaky attachment's
short healthy window into a measurement instead of a compile stall.
Subprocesses are required: in-process, jit's own dispatch cache would
short-circuit before the persistent cache is ever consulted.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.train import TrainConfig
from fm_spark_tpu.utils import compile_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_fm_spec(**kw):
    return models.FieldFMSpec(
        num_features=3 * 32, rank=2, num_fields=3, bucket=32,
        init_std=0.01, **kw,
    )


# The winner-variant lever stack (minus segtotal_pallas, whose CPU
# interpret mode would dominate the test's runtime without changing
# what is being pinned): bf16 storage + dedup_sr + host compact + gfull.
_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
from fm_spark_tpu.utils import compile_cache
from fm_spark_tpu import models
from fm_spark_tpu.train import TrainConfig
from fm_spark_tpu.sparse import precompile_field_sparse_step

compile_cache.enable(sys.argv[1])
spec = models.FieldFMSpec(num_features=3 * 32, rank=2, num_fields=3,
                          bucket=32, init_std=0.01,
                          param_dtype="bfloat16",
                          compute_dtype="bfloat16")
config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                     optimizer="sgd", sparse_update="dedup_sr",
                     host_dedup=True, compact_cap=32, gfull_fused=True)
precompile_field_sparse_step(spec, config, 64)
print(json.dumps(compile_cache.cache_stats()))
"""


def _run_child(cache_dir) -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _CHILD, str(cache_dir)],
        capture_output=True, text=True, timeout=420, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cold_populates_then_warm_compiles_nothing(tmp_path):
    """Cold run: cache misses, entries written. Warm run (new process,
    same step): zero fresh XLA compilations — the warm-start
    acceptance criterion, asserted via cache stats."""
    cold = _run_child(tmp_path / "cc")
    assert cold["enabled"]
    assert cold["dir"] == str(tmp_path / "cc")
    assert cold["misses"] > 0
    assert cold["entries"] > 0
    assert cold["bytes"] > 0

    warm = _run_child(tmp_path / "cc")
    assert warm["misses"] == 0, (
        f"warm process recompiled: {warm}"
    )
    assert warm["hits"] >= 1
    # Nothing new was serialized — the executables were all reused.
    assert warm["entries"] == cold["entries"]


@pytest.fixture
def cache_config_guard():
    """Restore jax's cache config + the module's state after a test
    that enables the cache in-process (the suite must not keep writing
    executables into a deleted tmp dir)."""
    prev = {
        "jax_compilation_cache_dir":
            jax.config.jax_compilation_cache_dir,
        "jax_persistent_cache_min_entry_size_bytes":
            jax.config.jax_persistent_cache_min_entry_size_bytes,
        "jax_persistent_cache_min_compile_time_secs":
            jax.config.jax_persistent_cache_min_compile_time_secs,
    }
    prev_dir = compile_cache._state["dir"]
    yield
    for k, v in prev.items():
        jax.config.update(k, v)
    compile_cache._state["dir"] = prev_dir
    compile_cache.reset_stats()


def test_enable_and_stats_in_process(tmp_path, cache_config_guard):
    d = compile_cache.enable(str(tmp_path / "cc"))
    assert os.path.isdir(d)
    assert compile_cache.is_enabled()
    compile_cache.reset_stats()

    @jax.jit
    def f(x):
        return jnp.sin(x) * 3.25 + jnp.flip(x)

    f(jnp.arange(23.0)).block_until_ready()
    s = compile_cache.cache_stats()
    assert s["requests"] >= 1
    assert s["entries"] >= 1
    assert s["misses"] + s["hits"] == s["requests"]


def test_enable_from_env(tmp_path, cache_config_guard, monkeypatch):
    monkeypatch.delenv(compile_cache.DEFAULT_ENV, raising=False)
    # The no-op path must not flip the enabled state on its own.
    assert compile_cache.enable_from_env() is None
    # Conventional falsy spellings mean OFF — never "a dir named 0".
    for off in ("0", "false", "no", "OFF"):
        monkeypatch.setenv(compile_cache.DEFAULT_ENV, off)
        assert compile_cache.enable_from_env() is None
    monkeypatch.setenv(compile_cache.DEFAULT_ENV, str(tmp_path / "envcc"))
    assert compile_cache.enable_from_env() == str(tmp_path / "envcc")
    assert compile_cache.is_enabled()
    # "1" means the repo-local default dir.
    monkeypatch.setenv(compile_cache.DEFAULT_ENV, "1")
    assert compile_cache.default_cache_dir() == compile_cache.DEFAULT_DIR


def test_aot_compiled_step_matches_jit_step(rng):
    """The AOT entry's Compiled is the SAME program the training loop's
    jit dispatch builds: running both from identical state yields the
    identical loss (and the Compiled is callable with concrete args)."""
    from fm_spark_tpu.sparse import (
        make_field_sparse_sgd_step,
        precompile_field_sparse_step,
    )

    spec = _small_fm_spec()
    config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                         optimizer="sgd")
    B = 32
    ids = jnp.asarray(rng.integers(0, 32, (B, 3)).astype(np.int32))
    vals = jnp.ones((B, 3), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, B).astype(np.float32))
    weights = jnp.ones((B,), jnp.float32)

    compiled = precompile_field_sparse_step(spec, config, B)
    p1 = spec.init(jax.random.key(7))
    _, loss_aot = compiled(p1, jnp.int32(0), ids, vals, labels,
                           weights, None)

    step = make_field_sparse_sgd_step(spec, config)
    p2 = spec.init(jax.random.key(7))
    _, loss_jit = step(p2, jnp.int32(0), ids, vals, labels, weights)
    assert float(loss_aot) == pytest.approx(float(loss_jit), rel=1e-6)


def test_aot_rejects_bad_args():
    from fm_spark_tpu.sparse import lower_field_sparse_step

    spec = _small_fm_spec()
    config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                         optimizer="sgd")
    with pytest.raises(ValueError, match="steps per call"):
        lower_field_sparse_step(spec, config, 32, steps_per_call=0)


def test_sharded_aot_entries(eight_devices):
    """The field-sharded and dense-mesh AOT entries lower (and the FM
    sharded one compiles) against abstract sharded shapes — no table or
    batch ever placed on the mesh."""
    from fm_spark_tpu.parallel import (
        lower_field_sharded_step,
        lower_parallel_train_step,
        make_field_mesh,
        make_mesh,
        precompile_field_sharded_step,
    )

    mesh = make_field_mesh(8)
    spec = _small_fm_spec(param_dtype="bfloat16",
                          compute_dtype="bfloat16")
    config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                         optimizer="sgd", sparse_update="dedup_sr",
                         compact_device=True, compact_cap=32,
                         compact_overflow="drop")
    compiled = precompile_field_sharded_step(spec, config, mesh, 64)
    assert compiled is not None

    # FFM + the multistep roll: lower-only (the API/shape contract;
    # full compiles of every family would dominate the suite's budget).
    ffm = models.FieldFFMSpec(
        num_features=3 * 32, rank=2, num_fields=3, bucket=32,
        init_std=0.01, param_dtype="float32", compute_dtype="bfloat16",
    )
    sgd = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                      optimizer="sgd")
    assert lower_field_sharded_step(ffm, sgd, mesh, 64) is not None
    assert lower_field_sharded_step(
        spec, config, mesh, 64, steps_per_call=2
    ) is not None

    # Host-built aux cannot be precompiled (it rides each batch).
    with pytest.raises(ValueError, match="host-built"):
        lower_field_sharded_step(
            spec,
            TrainConfig(learning_rate=0.05, lr_schedule="constant",
                        optimizer="sgd", sparse_update="dedup_sr",
                        host_dedup=True, compact_cap=32),
            mesh, 64,
        )

    # Dense dp/row mesh step (parallel/step.py's entry).
    fm = models.FMSpec(num_features=512, rank=4, init_std=0.01)
    dmesh = make_mesh(2, 4)
    assert lower_parallel_train_step(
        fm, TrainConfig(learning_rate=0.1, optimizer="adam"), dmesh,
        "row", batch_size=64, nnz=8,
    ) is not None
