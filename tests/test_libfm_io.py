"""libFM text-format import/export: roundtrip + prediction equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.models.libfm_io import load_libfm, save_libfm


def _random_params(spec, seed=0):
    params = spec.init(jax.random.key(seed))
    rng = np.random.default_rng(seed)
    params["w0"] = jnp.asarray(rng.normal(), jnp.float32)
    params["w"] = jnp.asarray(
        rng.normal(size=(spec.num_features,)), jnp.float32
    )
    return params


def test_roundtrip_exact(tmp_path):
    spec = models.FMSpec(num_features=37, rank=5)
    params = _random_params(spec)
    path = str(tmp_path / "model.libfm")
    save_libfm(path, spec, params)
    spec2, params2 = load_libfm(path)
    assert spec2.num_features == 37 and spec2.rank == 5
    assert spec2.use_bias and spec2.use_linear
    np.testing.assert_allclose(
        np.asarray(params2["v"]), np.asarray(params["v"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(params2["w"]), np.asarray(params["w"]), rtol=1e-6
    )
    # Same predictions on both sides of the roundtrip.
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, 37, size=(64, 4)), jnp.int32)
    vals = jnp.asarray(rng.normal(size=(64, 4)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(spec.predict(params, ids, vals)),
        np.asarray(spec2.predict(params2, ids, vals)),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.parametrize("use_bias,use_linear", [(False, True), (True, False),
                                                 (False, False)])
def test_dim_sections_roundtrip(tmp_path, use_bias, use_linear):
    spec = models.FMSpec(
        num_features=10, rank=3, use_bias=use_bias, use_linear=use_linear
    )
    params = spec.init(jax.random.key(0))
    path = str(tmp_path / "m.libfm")
    save_libfm(path, spec, params)
    spec2, params2 = load_libfm(path)
    assert spec2.use_bias == use_bias
    assert spec2.use_linear == use_linear


def test_field_fm_flattens_on_export(tmp_path):
    spec = models.FieldFMSpec(
        num_features=4 * 8, rank=3, num_fields=4, bucket=8
    )
    params = spec.init(jax.random.key(0))
    path = str(tmp_path / "m.libfm")
    save_libfm(path, spec, params)
    spec2, params2 = load_libfm(path)
    assert spec2.num_features == 32 and spec2.rank == 3
    # Flat predictions from the import match field predictions.
    rng = np.random.default_rng(0)
    local_ids = jnp.asarray(rng.integers(0, 8, size=(16, 4)), jnp.int32)
    vals = jnp.ones((16, 4), jnp.float32)
    want = spec.predict(params, local_ids, vals)
    got = spec2.predict(params2, spec.to_global_ids(local_ids), vals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_external_file_parses(tmp_path):
    # A hand-written file in the exact format libFM emits.
    text = (
        "#global bias W0\n0.25\n"
        "#unary interactions Wj\n0.1\n-0.2\n0.3\n"
        "#pairwise interactions Vj,f\n"
        "0.1 0.2\n0.3 -0.4\n-0.5 0.6\n"
    )
    path = tmp_path / "ext.libfm"
    path.write_text(text)
    spec, params = load_libfm(str(path))
    assert spec.num_features == 3 and spec.rank == 2
    assert float(params["w0"]) == pytest.approx(0.25)
    assert float(params["w"][1]) == pytest.approx(-0.2)
    assert float(params["v"][2, 1]) == pytest.approx(0.6)


def test_mismatched_sections_error(tmp_path):
    path = tmp_path / "bad.libfm"
    path.write_text(
        "#unary interactions Wj\n0.1\n0.2\n"
        "#pairwise interactions Vj,f\n0.1 0.2\n"
    )
    with pytest.raises(ValueError, match="unary weights"):
        load_libfm(str(path))
    path2 = tmp_path / "bad2.libfm"
    path2.write_text("#global bias W0\n0.0\n")
    with pytest.raises(ValueError, match="missing"):
        load_libfm(str(path2))
