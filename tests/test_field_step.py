"""Field-sharded multi-chip step ≡ single-chip fused step (8-dev CPU mesh).

The Spark-idiom simulation strategy (SURVEY.md §4): the identical
shard_map/psum/all_to_all code path a real v5e-8 would run, on fake CPU
devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.parallel import (
    make_field_mesh,
    make_field_sharded_sgd_body,
    make_field_sharded_sgd_step,
    pad_field_batch,
    shard_field_batch,
    shard_field_params,
    stack_field_params,
    unstack_field_params,
)
from fm_spark_tpu.sparse import make_field_sparse_sgd_step
from fm_spark_tpu.train import TrainConfig


def _make_batch(rng, b, f, bucket):
    return (
        rng.integers(0, bucket, size=(b, f)).astype(np.int32),
        rng.uniform(0.5, 1.5, size=(b, f)).astype(np.float32),
        rng.integers(0, 2, b).astype(np.float32),
        np.ones((b,), np.float32),
    )


@pytest.mark.parametrize("n_feat,num_fields", [
    (8, 5),   # fields pad 5 → 8, three chips own only padding
    (4, 6),   # fields pad 6 → 8, uneven split of real fields
    (2, 6),   # even split
])
def test_field_sharded_matches_single_chip(eight_devices, n_feat, num_fields):
    bucket, rank, b = 32, 4, 64
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.3, lr_schedule="inv_sqrt",
                         optimizer="sgd", reg_factors=1e-3, reg_linear=1e-4,
                         reg_bias=1e-4)
    mesh = make_field_mesh(n_feat, devices=eight_devices)

    params = spec.init(jax.random.key(0))
    ref_params = jax.tree_util.tree_map(jnp.copy, params)

    sharded = shard_field_params(
        stack_field_params(spec, params, n_feat), mesh
    )
    step_sharded = make_field_sharded_sgd_step(spec, config, mesh)
    step_single = make_field_sparse_sgd_step(spec, config)

    rng = np.random.default_rng(0)
    for i in range(3):
        batch = _make_batch(rng, b, num_fields, bucket)
        sb = shard_field_batch(
            pad_field_batch(batch, num_fields, n_feat), mesh
        )
        sharded, loss_sh = step_sharded(sharded, jnp.int32(i), *sb)
        ref_params, loss_ref = step_single(
            ref_params, jnp.int32(i), *map(jnp.asarray, batch)
        )
        np.testing.assert_allclose(
            float(loss_sh), float(loss_ref), rtol=1e-5
        )

    got = unstack_field_params(spec, jax.device_get(sharded))
    np.testing.assert_allclose(
        float(got["w0"]), float(ref_params["w0"]), rtol=1e-5
    )
    for f in range(num_fields):
        np.testing.assert_allclose(
            np.asarray(got["vw"][f]), np.asarray(ref_params["vw"][f]),
            rtol=2e-4, atol=1e-6,
        )


def test_weighted_batch_matches(eight_devices):
    # Weight-0 padding rows (epoch tails) must behave identically sharded.
    num_fields, bucket, rank, n_feat, b = 6, 16, 2, 4, 32
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.2, optimizer="sgd")
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    params = spec.init(jax.random.key(2))
    ref_params = jax.tree_util.tree_map(jnp.copy, params)
    sharded = shard_field_params(
        stack_field_params(spec, params, n_feat), mesh
    )
    step_sharded = make_field_sharded_sgd_step(spec, config, mesh)
    step_single = make_field_sparse_sgd_step(spec, config)
    rng = np.random.default_rng(3)
    ids, vals, labels, weights = _make_batch(rng, b, num_fields, bucket)
    weights[b // 2:] = 0.0
    batch = (ids, vals, labels, weights)
    sb = shard_field_batch(pad_field_batch(batch, num_fields, n_feat), mesh)
    sharded, loss_sh = step_sharded(sharded, jnp.int32(0), *sb)
    ref_params, loss_ref = step_single(
        ref_params, jnp.int32(0), *map(jnp.asarray, batch)
    )
    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=1e-5)
    got = unstack_field_params(spec, jax.device_get(sharded))
    for f in range(num_fields):
        np.testing.assert_allclose(
            np.asarray(got["vw"][f]), np.asarray(ref_params["vw"][f]),
            rtol=2e-4, atol=1e-6,
        )


def test_padded_fields_stay_zero(eight_devices):
    num_fields, bucket, rank, n_feat = 5, 16, 2, 4
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.5, optimizer="sgd",
                         reg_factors=1e-2, reg_linear=1e-2)
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    sharded = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(1)), n_feat), mesh
    )
    step = make_field_sharded_sgd_step(spec, config, mesh)
    rng = np.random.default_rng(1)
    for i in range(3):
        batch = pad_field_batch(
            _make_batch(rng, 32, num_fields, bucket), num_fields, n_feat
        )
        sharded, _ = step(sharded, jnp.int32(i), *shard_field_batch(batch, mesh))
    vw = np.asarray(jax.device_get(sharded["vw"]))
    assert vw.shape[0] == 8  # 5 → padded to 8
    np.testing.assert_array_equal(vw[num_fields:], 0.0)


def test_stack_roundtrip():
    spec = models.FieldFMSpec(
        num_features=3 * 8, rank=2, num_fields=3, bucket=8
    )
    params = spec.init(jax.random.key(0))
    stacked = stack_field_params(spec, params, n_feat=2)
    assert stacked["vw"].shape == (4, 8, 3)
    back = unstack_field_params(spec, stacked)
    for f in range(3):
        np.testing.assert_array_equal(
            np.asarray(back["vw"][f]), np.asarray(params["vw"][f])
        )


def test_requires_feat_mesh(eight_devices):
    from fm_spark_tpu.parallel import make_mesh
    from fm_spark_tpu.parallel.field_step import make_field_sharded_sgd_body

    spec = models.FieldFMSpec(num_features=2 * 8, rank=2, num_fields=2,
                              bucket=8)
    mesh2d = make_mesh(2, 4, devices=eight_devices)
    with pytest.raises(ValueError, match="'feat'"):
        make_field_sharded_sgd_body(spec, TrainConfig(optimizer="sgd"), mesh2d)


# ------------------------------------------------- 2-D (feat, row) mesh


@pytest.mark.parametrize("n_feat,n_row,num_fields,mode", [
    (4, 2, 6, "scatter_add"),   # fields pad 6 → 8, bucket split in 2
    (2, 4, 5, "scatter_add"),   # uneven fields + deep row split
    (1, 8, 3, "scatter_add"),   # PURE row sharding (capacity only)
    (4, 2, 6, "dedup"),         # dedup's drop-lane path + sentinel rows
])
def test_field_sharded_2d_matches_single_chip(eight_devices, n_feat, n_row,
                                              num_fields, mode):
    bucket, rank, b = 32, 4, 64
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.3, lr_schedule="inv_sqrt",
                         optimizer="sgd", reg_factors=1e-3, reg_linear=1e-4,
                         reg_bias=1e-4, sparse_update=mode)
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    assert dict(mesh.shape) == {"feat": n_feat, "row": n_row}

    params = spec.init(jax.random.key(0))
    ref_params = jax.tree_util.tree_map(jnp.copy, params)
    sharded = shard_field_params(
        stack_field_params(spec, params, n_feat), mesh
    )
    import dataclasses

    step_sharded = make_field_sharded_sgd_step(spec, config, mesh)
    # dedup ≡ scatter_add up to reassociation, so one single-chip oracle
    # serves both parametrizations.
    step_single = make_field_sparse_sgd_step(
        spec, dataclasses.replace(config, sparse_update="scatter_add")
    )

    rng = np.random.default_rng(0)
    for i in range(3):
        batch = _make_batch(rng, b, num_fields, bucket)
        sb = shard_field_batch(
            pad_field_batch(batch, num_fields, n_feat), mesh
        )
        sharded, loss_sh = step_sharded(sharded, jnp.int32(i), *sb)
        ref_params, loss_ref = step_single(
            ref_params, jnp.int32(i), *map(jnp.asarray, batch)
        )
        np.testing.assert_allclose(
            float(loss_sh), float(loss_ref), rtol=1e-5
        )

    got = unstack_field_params(spec, jax.device_get(sharded))
    np.testing.assert_allclose(
        float(got["w0"]), float(ref_params["w0"]), rtol=1e-5
    )
    for f in range(num_fields):
        np.testing.assert_allclose(
            np.asarray(got["vw"][f]), np.asarray(ref_params["vw"][f]),
            rtol=2e-4, atol=1e-6,
        )


def test_field_sharded_2d_weighted_and_padded(eight_devices):
    # Zero-weight tail rows + padded field slots, on the 2-D mesh.
    num_fields, bucket, rank, n_feat, n_row, b = 5, 16, 2, 2, 4, 32
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.1,
    )
    config = TrainConfig(learning_rate=0.2, optimizer="sgd")
    mesh = make_field_mesh(8, devices=eight_devices, n_row=n_row)
    params = spec.init(jax.random.key(2))
    ref_params = jax.tree_util.tree_map(jnp.copy, params)
    sharded = shard_field_params(
        stack_field_params(spec, params, n_feat), mesh
    )
    step_sharded = make_field_sharded_sgd_step(spec, config, mesh)
    step_single = make_field_sparse_sgd_step(spec, config)
    rng = np.random.default_rng(3)
    ids, vals, labels, weights = _make_batch(rng, b, num_fields, bucket)
    weights[b // 2:] = 0.0
    batch = (ids, vals, labels, weights)
    sb = shard_field_batch(pad_field_batch(batch, num_fields, n_feat), mesh)
    sharded, loss_sh = step_sharded(sharded, jnp.int32(0), *sb)
    ref_params, loss_ref = step_single(
        ref_params, jnp.int32(0), *map(jnp.asarray, batch)
    )
    np.testing.assert_allclose(float(loss_sh), float(loss_ref), rtol=1e-5)
    got = unstack_field_params(spec, jax.device_get(sharded))
    for f in range(num_fields):
        np.testing.assert_allclose(
            np.asarray(got["vw"][f]), np.asarray(ref_params["vw"][f]),
            rtol=2e-4, atol=1e-6,
        )
    vw = np.asarray(jax.device_get(sharded["vw"]))
    np.testing.assert_array_equal(vw[num_fields:], 0.0)  # padding inert


def test_field_sharded_2d_bucket_divisibility(eight_devices):
    spec = models.FieldFMSpec(num_features=2 * 12, rank=2, num_fields=2,
                              bucket=12)
    mesh = make_field_mesh(8, devices=eight_devices, n_row=8)
    with pytest.raises(ValueError, match="divide evenly"):
        make_field_sharded_sgd_body(
            spec, TrainConfig(optimizer="sgd"), mesh
        )


def test_field_sharded_2d_dedup_sr_learns(eight_devices):
    # bf16 + stochastic rounding through the 2-D sentinel path: per
    # (field, row-shard) SR keys, loss must fall, padding stays zero.
    num_fields, bucket, rank, n_feat, n_row, b = 3, 32, 4, 2, 4, 64
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank, num_fields=num_fields,
        bucket=bucket, init_std=0.1, param_dtype="bfloat16",
    )
    config = TrainConfig(learning_rate=0.3, lr_schedule="constant",
                         optimizer="sgd", sparse_update="dedup_sr")
    mesh = make_field_mesh(8, devices=eight_devices, n_row=n_row)
    sharded = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(0)), n_feat), mesh
    )
    step = make_field_sharded_sgd_step(spec, config, mesh)
    from fm_spark_tpu.data import synthetic_ctr

    ids_g, vals, labels = synthetic_ctr(b * 20, num_fields * bucket,
                                        num_fields, seed=0)
    offs = (np.arange(num_fields) * bucket).astype(np.int32)
    ids_l = ids_g - offs[None, :]
    losses = []
    for i in range(20):
        sl = slice(i * b, (i + 1) * b)
        batch = pad_field_batch(
            (ids_l[sl], vals[sl], labels[sl], np.ones((b,), np.float32)),
            num_fields, n_feat,
        )
        sharded, loss = step(sharded, jnp.int32(i),
                             *shard_field_batch(batch, mesh))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


def test_field_sharded_dedup_sr_runs_and_learns(eight_devices):
    # dedup_sr inside shard_map (per-chip SR keys via axis_index): loss
    # must fall and tables must move; exact equality is not expected
    # (SR noise), so this is a smoke + learning check.
    num_fields, bucket, rank, n_feat, b = 6, 32, 4, 4, 64
    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank, num_fields=num_fields,
        bucket=bucket, init_std=0.1, param_dtype="bfloat16",
    )
    config = TrainConfig(learning_rate=0.3, lr_schedule="constant",
                         optimizer="sgd", sparse_update="dedup_sr")
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    sharded = shard_field_params(
        stack_field_params(spec, spec.init(jax.random.key(0)), n_feat), mesh
    )
    step = make_field_sharded_sgd_step(spec, config, mesh)
    rng = np.random.default_rng(0)
    from fm_spark_tpu.data import synthetic_ctr

    ids_g, vals, labels = synthetic_ctr(b * 20, num_fields * bucket,
                                        num_fields, seed=0)
    offs = (np.arange(num_fields) * bucket).astype(np.int32)
    ids_l = ids_g - offs[None, :]
    losses = []
    for i in range(20):
        sl = slice(i * b, (i + 1) * b)
        batch = pad_field_batch(
            (ids_l[sl], vals[sl], labels[sl], np.ones((b,), np.float32)),
            num_fields, n_feat,
        )
        sharded, loss = step(sharded, jnp.int32(i),
                             *shard_field_batch(batch, mesh))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses


@pytest.mark.parametrize("n_row", [1, 2], ids=["feat4", "feat2xrow2"])
@pytest.mark.slow
def test_sharded_eval_matches_canonical(rng, n_row):
    """evaluate_field_sharded must equal evaluate_params on the canonical
    params — same histogram-AUC metric, no table gather."""
    from fm_spark_tpu.data import iterate_once
    from fm_spark_tpu.parallel.field_step import (
        evaluate_field_sharded,
        make_field_mesh,
        shard_field_params,
        stack_field_params,
    )
    from fm_spark_tpu.train import evaluate_params

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    F, bucket, k, n = 5, 32, 4, 300
    spec = models.FieldFMSpec(
        num_features=F * bucket, rank=k, num_fields=F, bucket=bucket,
        init_std=0.3,
    )
    params = spec.init(jax.random.key(5))
    mesh = make_field_mesh(4, n_row=n_row)
    sharded = shard_field_params(
        stack_field_params(spec, params, mesh.shape["feat"]), mesh
    )
    ids = rng.integers(0, bucket, size=(n, F)).astype(np.int32)
    vals = rng.normal(size=(n, F)).astype(np.float32)
    labels = rng.integers(0, 2, n).astype(np.float32)

    want = evaluate_params(spec, params, iterate_once(ids, vals, labels, 64))
    got = evaluate_field_sharded(
        spec, mesh, sharded, iterate_once(ids, vals, labels, 64)
    )
    for key in ("auc", "logloss", "rmse", "count"):
        np.testing.assert_allclose(got[key], want[key], rtol=1e-5,
                                   atol=1e-6, err_msg=key)


def test_deepfm_sharded_eval_matches_canonical(rng):
    from fm_spark_tpu.data import iterate_once
    from fm_spark_tpu.parallel.field_step import (
        evaluate_field_sharded,
        make_field_mesh,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
    )
    from fm_spark_tpu.train import evaluate_params

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    F, bucket, k, n = 5, 32, 4, 300
    spec = models.FieldDeepFMSpec(
        num_features=F * bucket, rank=k, num_fields=F, bucket=bucket,
        init_std=0.3, mlp_dims=(8, 8),
    )
    params = spec.init(jax.random.key(6))
    mesh = make_field_mesh(4)
    sharded = shard_field_deepfm_params(
        stack_field_deepfm_params(spec, params, mesh.shape["feat"]), mesh
    )
    ids = rng.integers(0, bucket, size=(n, F)).astype(np.int32)
    vals = rng.normal(size=(n, F)).astype(np.float32)
    labels = rng.integers(0, 2, n).astype(np.float32)

    want = evaluate_params(spec, params, iterate_once(ids, vals, labels, 64))
    got = evaluate_field_sharded(
        spec, mesh, sharded, iterate_once(ids, vals, labels, 64)
    )
    for key in ("auc", "logloss", "rmse", "count"):
        np.testing.assert_allclose(got[key], want[key], rtol=1e-5,
                                   atol=1e-6, err_msg=key)
