"""Pins for fm_spark_tpu.utils.cpuguard (the dead-attachment hang guard).

The guard is what keeps every cpu-targeted surface (tests, bench_quality,
bench_convergence, bench_wire_spot, __graft_entry__.dryrun_multichip,
bench.py / cli.main under JAX_PLATFORMS=cpu) from hanging forever in
``jax.devices()`` while the session's TPU tunnel is down — see the
2026-07-31 PERF.md note. These tests run with the backend already up
(conftest), so they pin the API contract, not the hang itself.
"""

import os

from fm_spark_tpu.utils.cpuguard import force_cpu_platform


def test_noop_without_cpu_request(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert force_cpu_platform() is False


def test_harmless_after_backend_init(monkeypatch):
    # conftest initialized the cpu backend long ago; the guard must not
    # raise and must leave the 8-fake-device mesh intact either way.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    force_cpu_platform()
    import jax

    assert len(jax.devices()) >= 8
    assert jax.devices()[0].platform == "cpu"


def test_accelerator_factories_absent():
    # conftest drops the plugin factories before backend init; the guard
    # does the same for non-pytest surfaces. No plugin factory (axon, or a
    # future plugin name) may survive into a cpu-pinned process — but
    # "tpu" must stay registered, or Pallas's import-time tpu lowering
    # registration dies with "unknown platform tpu" (cpuguard docstring).
    from jax._src import xla_bridge

    assert set(xla_bridge._backend_factories) <= {"cpu", "tpu"}
    assert "axon" not in xla_bridge._backend_factories


def test_unconditional_mode_ignores_env(monkeypatch):
    # The env gate must decide whether the cpu pin is even ATTEMPTED:
    # with only_if_env=False the guard must try the config update despite
    # a non-cpu env; with the default gate and a non-cpu env it must not.
    import jax

    calls = []
    monkeypatch.setattr(
        jax.config, "update",
        lambda *a, **k: calls.append(a),
    )
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    assert force_cpu_platform() is False
    assert calls == []  # gated out before touching the config
    assert force_cpu_platform(only_if_env=False) is True
    assert ("jax_platforms", "cpu") in [tuple(c) for c in calls]
    assert os.environ["JAX_PLATFORMS"] == "axon"  # env never mutated
