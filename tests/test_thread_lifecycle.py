"""Thread-lifecycle audit (ISSUE 15 satellite): every thread the
package starts is daemonized AND joined on its shutdown path, so a
clean close leaves no live package thread behind — pinned by
enumerating threads after shutdown, not by reading the code. Plus the
targeted regression tests for the two genuine thread-safety fixes the
fmlint pass surfaced (watchdog overrun counter, reload follower
outcome counters).
"""

import threading
import time

import pytest

from fm_spark_tpu import obs
from fm_spark_tpu.data.pipeline import Prefetcher
from fm_spark_tpu.obs import export as obs_export
from fm_spark_tpu.resilience.watchdog import WatchdogTable


def _nondaemon_threads():
    return sorted(t.name for t in threading.enumerate()
                  if not t.daemon and t.is_alive())


def _fm_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("fm-spark") and t.is_alive()]


class _CountSource:
    def __init__(self):
        self.n = 0

    def next_batch(self):
        self.n += 1
        time.sleep(0.001)
        return self.n


def test_static_rule_every_package_thread_daemon_or_joined():
    """The AST half of the audit: the fmlint thread-lifecycle rule is
    clean over the real package — no Thread/Timer without daemon=True
    or a shutdown-path join."""
    from fm_spark_tpu.analysis import core

    found, _ = core.run_rules(core.Context(), rules=["thread-lifecycle"])
    assert found == [], [f.render() for f in found]


def test_no_live_nondaemon_threads_after_clean_shutdown(tmp_path):
    """The runtime half (the satellite's pin): spin up every
    package-owned thread population this suite can construct cheaply —
    prefetcher producer, metrics endpoint, watchdog exit-mode monitor —
    drive them, shut them all down cleanly, and enumerate: the
    non-daemon thread set is exactly what it was before, and no
    fm-spark-named thread survives."""
    before = _nondaemon_threads()

    # Prefetcher producer thread.
    pf = Prefetcher(_CountSource(), depth=2)
    assert pf.next_batch() >= 1

    # Live-metrics endpoint (ThreadingHTTPServer + serve_forever).
    server = obs_export.start_metrics_server(port=0)
    assert server.port > 0

    # Watchdog exit-mode monitor (armed => monitor thread runs).
    exits = []
    table = WatchdogTable({"step_window": 30.0}, action="exit",
                          _exit=exits.append)
    with table.phase("step_window"):
        pass
    # The obs plane itself (trace sink / flight spool are not threads,
    # but shutdown() is the lifecycle boundary under test).
    obs.configure(str(tmp_path / "obs"), run_id="r-threads")

    pf.close()
    table.close()
    obs.shutdown()

    deadline = time.monotonic() + 5.0
    while _fm_threads() and time.monotonic() < deadline:
        time.sleep(0.02)
    leftover = _fm_threads()
    assert leftover == [], f"live fm-spark threads after shutdown: " \
                           f"{[t.name for t in leftover]}"
    assert _nondaemon_threads() == before
    assert not pf._thread.is_alive()
    assert exits == []  # the monitor never fired on a healthy phase


def test_obs_shutdown_stops_the_metrics_endpoint(tmp_path):
    """obs.shutdown() is a shutdown path (ISSUE 15): the endpoint's
    serve_forever thread must not outlive the run — and configure()'s
    internal reason=None replace must NOT kill a server mid-process."""
    obs.configure(str(tmp_path / "a"), run_id="r-a")
    server = obs_export.start_metrics_server(port=0)
    thread = server._thread
    assert thread.is_alive()
    # Re-configure (a new run in the same process): server survives.
    obs.configure(str(tmp_path / "b"), run_id="r-b")
    assert thread.is_alive()
    obs.shutdown()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert obs_export._server is None


def test_watchdog_close_joins_the_monitor_thread():
    table = WatchdogTable({"step_window": 30.0}, action="exit",
                          _exit=lambda rc: None, poll_s=0.01)
    with table.phase("step_window"):
        monitor = table._monitor
        assert monitor is not None and monitor.is_alive()
    table.close()
    assert not monitor.is_alive()
    assert table._monitor is None


# ------------------------------------------------- fix regressions (fmlint)


def test_watchdog_overrun_counter_is_race_safe():
    """Regression for the fmlint thread-lock-discipline finding: the
    exit-mode monitor thread and raise-mode phase exits can note
    overruns concurrently — the counter increment now runs under the
    table lock, so N concurrent notes count exactly N."""
    table = WatchdogTable({"step_window": 1.0}, action="raise")
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        for _ in range(per_thread):
            table._note_overrun("step_window", 1.0, 2.0)

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert table.hangs_detected == n_threads * per_thread


def test_watchdog_near_miss_counter_is_race_safe():
    """Same defect class, near-miss side (post-review): any thread
    exiting a guarded phase can note a near-miss — the counter and
    the per-phase dump throttle now share the table lock."""
    table = WatchdogTable({"step_window": 1.0}, action="raise")
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        for _ in range(per_thread):
            table._note_near_miss("step_window", 1.0, 0.9)

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert table.near_misses == n_threads * per_thread


def test_reload_follower_counters_are_race_safe(tmp_path):
    """Regression for the fmlint finding on ReloadFollower.failures:
    a direct poll_once() caller racing the poll loop must not drop
    counts — the counters now increment under a dedicated lock."""
    from fm_spark_tpu.serve.reload import ReloadFollower

    class _Gen:
        params = {"w": 1.0}
        step = 0

    class _Engine:
        def generation(self):
            return _Gen()

    follower = ReloadFollower(_Engine(), str(tmp_path), poll_s=60.0)
    n_threads, per_thread = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        for _ in range(per_thread):
            follower._fail("synthetic", target_step=1, served=0)

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert follower.failures == n_threads * per_thread
    follower.stop()


@pytest.fixture(autouse=True)
def _clean_slate():
    """Never leak a metrics server or obs run into other tests."""
    yield
    obs_export.stop_metrics_server()
    obs.shutdown(reason=None)
