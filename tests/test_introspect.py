"""Live introspection plane (ISSUE 14): the trigger-fired deep-capture
engine, the step-spike detector, the per-step cost model, and the
scrapeable live-metrics endpoint.

The load-bearing contracts:

- **bundle anatomy** — a fired capture leaves
  ``captures/<trigger>_<seq>/`` with an atomic ``capture.json``
  manifest, a metrics snapshot, and the flight window (the satellite:
  a capture always has its flight context); a torn bundle (no
  manifest) is skipped by every reader;
- **rate limiting** — ``max_per_trigger`` and ``min_interval_s`` bound
  the capture set; suppressed fires are counted, never silent;
- **trigger coverage** — every registry entry fires here or in
  test_serve/test_obs_overhead: ``sentinel_regressed`` (the sentinel
  hook + the subprocess drill), ``watchdog_near_miss`` (a phase past
  80% of its deadline), ``serve_slo_overrun`` (the subprocess serve
  drill), ``step_time_spike`` (the trailing-p99 detector);
- **exactly-one drills** — a synthetic sentinel regression and a serve
  SLO overrun each produce EXACTLY ONE rate-limited bundle in
  subprocess drills (the tier-1 acceptance);
- **the endpoint** — ``/metrics`` serves the Prometheus text dump
  (native histogram buckets, run_id labels) and ``/healthz`` the JSON
  liveness doc, over a real HTTP round-trip.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from fm_spark_tpu import obs  # noqa: E402
from fm_spark_tpu.obs import export, introspect  # noqa: E402
from fm_spark_tpu.obs.introspect import (  # noqa: E402
    CaptureEngine,
    StepSpikeDetector,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    introspect.clear()
    export.stop_metrics_server()
    yield
    obs.shutdown(reason=None)
    introspect.clear()
    export.stop_metrics_server()


# ------------------------------------------------------- capture engine


def test_capture_bundle_anatomy(tmp_path):
    run_dir = str(tmp_path / "run")
    obs.configure(run_dir, run_id="cap1")
    introspect.configure(run_dir, run_id="cap1", profile=False)
    obs.event("tick", i=7)
    bundle = introspect.fire("watchdog_near_miss", phase="ckpt_commit",
                             frac=0.91)
    assert bundle is not None
    names = sorted(os.listdir(bundle))
    assert names == ["capture.json", "flight.json", "metrics.json"]
    with open(os.path.join(bundle, "capture.json")) as f:
        manifest = json.load(f)
    assert manifest["trigger"] == "watchdog_near_miss"
    assert manifest["run_id"] == "cap1"
    assert manifest["context"] == {"phase": "ckpt_commit", "frac": 0.91}
    assert manifest["profiler"] == {"status": "disabled"}
    # The flight context rode along (the ISSUE 14 satellite).
    with open(os.path.join(bundle, "flight.json")) as f:
        flight = json.load(f)
    assert any(e.get("kind") == "tick" for e in flight["events"])
    # The fire itself is on the flight timeline + counters.
    assert obs.registry().counter("introspect.captures_total").value == 1
    assert any(e["kind"] == "capture_fired"
               for e in obs.fault_timeline())


def test_rate_limit_max_per_trigger_and_interval(tmp_path):
    eng = CaptureEngine(str(tmp_path), max_per_trigger=2,
                        min_interval_s=0.0, profile=False)
    assert eng.fire("step_time_spike", step_ms=9.0) is not None
    assert eng.fire("step_time_spike", step_ms=9.0) is not None
    # Third of the same trigger: suppressed (max_per_trigger).
    assert eng.fire("step_time_spike", step_ms=9.0) is None
    assert eng.suppressed == 1
    # A DIFFERENT trigger still fires — limits are per trigger.
    assert eng.fire("sentinel_regressed", leg="x") is not None

    clock = {"t": 100.0}
    eng2 = CaptureEngine(str(tmp_path / "b"), max_per_trigger=5,
                         min_interval_s=30.0, profile=False,
                         _monotonic=lambda: clock["t"])
    assert eng2.fire("step_time_spike") is not None
    clock["t"] += 10.0  # inside the interval: suppressed
    assert eng2.fire("step_time_spike") is None
    clock["t"] += 30.0  # past it: fires
    assert eng2.fire("step_time_spike") is not None
    assert eng2.suppressed == 1


def test_unknown_trigger_rejected_eagerly(tmp_path):
    eng = CaptureEngine(str(tmp_path), profile=False)
    with pytest.raises(ValueError, match="unknown introspection"):
        eng.fire("totally_made_up")


def test_disabled_fire_is_noop_and_module_fire_never_raises():
    introspect.clear()
    assert introspect.fire("sentinel_regressed", leg="x") is None
    assert introspect.observe_step_time(1.0) is None
    assert not introspect.active()


def test_list_captures_skips_torn_bundle(tmp_path):
    eng = CaptureEngine(str(tmp_path), min_interval_s=0.0,
                        profile=False)
    good = eng.fire("sentinel_regressed", leg="a")
    # A torn bundle: directory exists, manifest never landed (a crash
    # between mkdir and the atomic manifest replace).
    torn = tmp_path / "captures" / "step_time_spike_001"
    torn.mkdir(parents=True)
    (torn / "metrics.json").write_text("{}")
    found = introspect.list_captures(str(tmp_path))
    assert [m["dir"] for m in found] == [good]


def test_profiler_arm_path_with_loaded_jax(tmp_path):
    """With jax loaded, the capture arms a BOUNDED profiler trace (a
    daemon timer stops it). Tolerant of a backend that refuses to
    profile — the manifest then records the failure, never raises."""
    import jax  # noqa: F401 — ensure it is in sys.modules

    eng = CaptureEngine(str(tmp_path), profile=True, trace_s=0.05)
    bundle = eng.fire("step_time_spike", step_ms=1.0)
    assert bundle is not None
    with open(os.path.join(bundle, "capture.json")) as f:
        status = json.load(f)["profiler"]["status"]
    assert status == "armed" or status.startswith("failed:")
    # Bounded: the timer releases the profiler either way.
    deadline = time.monotonic() + 10.0
    while eng._profiler_busy and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not eng._profiler_busy


# --------------------------------------------------- step-spike trigger


def test_spike_detector_trailing_p99():
    det = StepSpikeDetector(window=16, factor=3.0, min_history=4)
    for _ in range(3):
        assert not det.observe(10.0)  # under min_history: never fires
    for _ in range(5):
        assert not det.observe(10.0)
    assert not det.observe(25.0)      # 2.5x: inside the band
    assert det.observe(100.0)         # 10x the trailing p99: spike
    # The spike entered the window (a level shift becomes the new
    # normal instead of firing forever).
    assert 100.0 in det._vals


def test_observe_step_time_fires_step_time_spike(tmp_path):
    introspect.configure(str(tmp_path), profile=False,
                         min_interval_s=0.0, spike_min_history=4)
    for _ in range(6):
        assert introspect.observe_step_time(10.0) is None
    bundle = introspect.observe_step_time(500.0)
    assert bundle is not None and "step_time_spike" in bundle
    with open(os.path.join(bundle, "capture.json")) as f:
        ctx = json.load(f)["context"]
    assert ctx["step_ms"] == 500.0
    assert ctx["trailing_p99_ms"] == 10.0


# ------------------------------------------------ sentinel regression


def _regression_ledger(path):
    from fm_spark_tpu.obs.ledger import PerfLedger, measurement_fingerprint
    from fm_spark_tpu.obs.sentinel import Sentinel

    fp = measurement_fingerprint(variant="v", model="fm", batch=64,
                                 device_kind="cpu", n_chips=1)
    ledger = PerfLedger(path)
    sentinel = Sentinel(ledger)
    for v in (1000.0, 1010.0, 990.0, 1005.0, 995.0):
        sentinel.observe({"kind": "bench_leg", "leg": "t", "run_id": "r",
                          "variant": "v", "value": v,
                          "fingerprint": fp})
    return sentinel, fp


def test_sentinel_regressed_fires_capture_and_healthz_status(tmp_path):
    introspect.configure(str(tmp_path), profile=False,
                         min_interval_s=0.0)
    sentinel, fp = _regression_ledger(str(tmp_path / "ledger.jsonl"))
    block = sentinel.observe({"kind": "bench_leg", "leg": "t",
                              "run_id": "r", "variant": "v",
                              "value": 400.0, "fingerprint": fp})
    assert block["verdict"] == "regressed"
    found = introspect.list_captures(str(tmp_path))
    assert [m["trigger"] for m in found] == ["sentinel_regressed"]
    assert found[0]["context"]["leg"] == "t"
    # The /healthz status carries the last verdict (any kind).
    assert export.status()["last_sentinel"]["verdict"] == "regressed"
    assert export.status()["last_sentinel"]["leg"] == "t"


_SENTINEL_DRILL = r"""
import os, sys
sys.path.insert(0, {repo!r})
from fm_spark_tpu.obs import introspect
from fm_spark_tpu.obs.ledger import PerfLedger, measurement_fingerprint
from fm_spark_tpu.obs.sentinel import Sentinel

run_dir = {run_dir!r}
introspect.configure(run_dir, run_id="drill", max_per_trigger=1,
                     min_interval_s=0.0, profile=False)
fp = measurement_fingerprint(variant="v", model="fm", batch=64,
                             device_kind="cpu", n_chips=1)
sentinel = Sentinel(PerfLedger(os.path.join(run_dir, "ledger.jsonl")))
for v in (1000.0, 1010.0, 990.0, 1005.0, 995.0):
    sentinel.observe({{"kind": "bench_leg", "leg": "t", "run_id": "r",
                       "variant": "v", "value": v, "fingerprint": fp}})
# TWO synthetic regressions: the rate limiter must keep exactly one
# bundle.
for v in (400.0, 380.0):
    block = sentinel.observe({{"kind": "bench_leg", "leg": "t",
                               "run_id": "r", "variant": "v",
                               "value": v, "fingerprint": fp}})
    assert block["verdict"] == "regressed", block
bundles = introspect.list_captures(run_dir)
print("BUNDLES", len(bundles), bundles[0]["trigger"])
"""


def test_subprocess_sentinel_regression_exactly_one_bundle(tmp_path):
    """The tier-1 acceptance drill: a synthetic sentinel regression in
    a subprocess produces EXACTLY ONE rate-limited capture bundle."""
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    proc = subprocess.run(
        [sys.executable, "-c",
         _SENTINEL_DRILL.format(repo=REPO, run_dir=run_dir)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "BUNDLES 1 sentinel_regressed" in proc.stdout
    found = introspect.list_captures(run_dir)
    assert len(found) == 1
    assert found[0]["trigger"] == "sentinel_regressed"


def test_profiler_skipped_when_jax_not_loaded(tmp_path, monkeypatch):
    """A jax-free process (the bench parent's shape) still gets a
    metrics+flight bundle, with the profiler skip RECORDED — the
    lookup goes through sys.modules, never an import."""
    import sys as _sys

    monkeypatch.setitem(_sys.modules, "jax", None)
    eng = CaptureEngine(str(tmp_path), profile=True)
    bundle = eng.fire("sentinel_regressed", leg="x")
    with open(os.path.join(bundle, "capture.json")) as f:
        assert (json.load(f)["profiler"]["status"]
                == "skipped: jax not loaded")


_SERVE_SLO_DRILL = r"""
import json, os, sys, time
sys.path.insert(0, {repo!r})
from fm_spark_tpu import models, obs
from fm_spark_tpu.obs import introspect
from fm_spark_tpu.resilience import watchdog
from fm_spark_tpu.serve import PredictEngine
import jax, numpy as np

run_dir = {run_dir!r}
obs.configure(run_dir, run_id="slo", install_signals=False)
introspect.configure(run_dir, run_id="slo", max_per_trigger=1,
                     min_interval_s=0.0, profile=False)
spec = models.FieldFMSpec(num_features=4 * 64, rank=4, num_fields=4,
                          bucket=64, init_std=0.1)
params = spec.init(jax.random.key(0))
eng = PredictEngine(spec, params, buckets=(1,), latency_budget_ms=0.0)
eng.warmup()
real = eng._compiled[1]
def slow(p, i, v):
    time.sleep(0.08)
    return real(p, i, v)
eng._compiled[1] = slow
watchdog.configure({{"serve_request": 0.01}}, action="raise")
ids = np.zeros((1, 4), np.int32)
vals = np.ones((1, 4), np.float32)
overruns = 0
for _ in range(2):           # TWO overruns -> exactly ONE bundle
    try:
        eng.submit(ids, vals).result(30)
    except watchdog.HangDetected:
        overruns += 1
eng.close()
bundles = introspect.list_captures(run_dir)
dump = json.load(open(os.path.join(run_dir, "flight_dump.json")))
print(json.dumps({{
    "overruns": overruns, "bundles": len(bundles),
    "trigger": bundles[0]["trigger"],
    "slo_counter": obs.registry().counter(
        "serve.slo_overruns_total").value,
    "dump_reason": dump["reason"],
}}))
"""


def test_subprocess_serve_slo_overrun_exactly_one_bundle(tmp_path):
    """The serving half of the acceptance drill: two serve SLO
    overruns (the serve_request watchdog armed at the SLO) produce
    exactly one rate-limited ``serve_slo_overrun`` bundle, and the
    flight dump (the capture-context satellite) landed."""
    run_dir = str(tmp_path / "run")
    proc = subprocess.run(
        [sys.executable, "-c",
         _SERVE_SLO_DRILL.format(repo=REPO, run_dir=run_dir)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["overruns"] == 2
    assert out["bundles"] == 1
    assert out["trigger"] == "serve_slo_overrun"
    assert out["slo_counter"] == 2
    # The default-path dump's final reason is the SECOND overrun's
    # watchdog verdict (hang_detected dumps are never throttled — a
    # blown deadline is a fault, not a near-miss); the suppressed
    # serve-side dump did not overwrite it, and the accepted capture
    # holds its own flight.json copy regardless.
    assert out["dump_reason"] == "hang_detected"
    bundle_flight = os.path.join(run_dir, "captures",
                                 "serve_slo_overrun_001", "flight.json")
    assert os.path.exists(bundle_flight)


# -------------------------------------------------- watchdog near-miss


def test_watchdog_near_miss_fires_capture_and_flight_dump(tmp_path):
    from fm_spark_tpu.resilience import watchdog

    run_dir = str(tmp_path / "run")
    obs.configure(run_dir, run_id="nm", install_signals=False)
    introspect.configure(run_dir, run_id="nm", profile=False,
                         min_interval_s=0.0)
    # Wide margins: the sleep must land in (80%, 100%] of the deadline
    # even with scheduler overshoot on a loaded CI core.
    table = watchdog.configure({"ckpt_commit": 0.5}, action="raise")
    try:
        with watchdog.phase("ckpt_commit"):
            time.sleep(0.42)   # ~84% of the deadline: a near-miss
        assert table.near_misses == 1
        assert table.hangs_detected == 0
        found = introspect.list_captures(run_dir)
        assert [m["trigger"] for m in found] == ["watchdog_near_miss"]
        ctx = found[0]["context"]
        assert ctx["phase"] == "ckpt_commit"
        assert 0.8 < ctx["frac"] <= 1.0
        # Flight dump on a near-miss (the ISSUE 14 satellite).
        with open(os.path.join(run_dir, "flight_dump.json")) as f:
            assert json.load(f)["reason"] == "watchdog_near_miss"
        assert any(e["kind"] == "watchdog_near_miss"
                   for e in obs.fault_timeline())
        # A fast phase is NOT a near-miss.
        with watchdog.phase("ckpt_commit"):
            pass
        assert table.near_misses == 1
    finally:
        watchdog.clear()


def test_near_miss_heavy_evidence_throttled_when_unarmed(tmp_path):
    """Without a capture engine, back-to-back near-misses of the same
    phase are COUNTED each time but journal+dump at most once per
    throttle interval — a phase living at 85% of its deadline must
    never fsync per occurrence."""
    from fm_spark_tpu.resilience import watchdog

    class _Journal:
        def __init__(self):
            self.events = []

        def emit(self, event, **fields):
            self.events.append(event)

    introspect.clear()
    journal = _Journal()
    table = watchdog.configure({"ckpt_commit": 0.4}, action="raise",
                               journal=journal)
    try:
        for _ in range(3):
            with watchdog.phase("ckpt_commit"):
                time.sleep(0.34)   # ~85% of the deadline each time
        assert table.near_misses == 3
        assert journal.events.count("watchdog_near_miss") == 1
    finally:
        watchdog.clear()


# ------------------------------------------------------ cost attribution


def test_step_cost_model_families_and_shapes():
    fm = introspect.step_cost_model("fm", batch=1024, rank=64)
    assert set(fm["families"]) == {"gather", "interact", "update",
                                   "segsum"}
    assert fm["bytes_total"] == sum(fm["families"].values())
    assert fm["families"]["segsum"] == 0          # no compact cap
    assert fm["assumptions"]["fields"] == 39

    compact = introspect.step_cost_model("fm", batch=131072, rank=64,
                                         cap=16384)
    # The compact lever's whole point: the update term shrinks from
    # B lanes to cap lanes per field.
    assert compact["families"]["update"] \
        < introspect.step_cost_model("fm", batch=131072,
                                     rank=64)["families"]["update"]
    assert compact["families"]["segsum"] > 0

    ffm = introspect.step_cost_model("ffm", batch=1024, rank=16)
    # FFM's field-aware sel set dominates: F x larger than FM's
    # elementwise interaction at the same shape.
    assert ffm["families"]["interact"] > \
        introspect.step_cost_model("fm", batch=1024, rank=16,
                                   fields=23)["families"]["interact"]
    assert ffm["assumptions"]["fields"] == 23

    bf16 = introspect.step_cost_model("fm", batch=1024, rank=64,
                                      param_bytes=2)
    assert bf16["families"]["gather"] < fm["families"]["gather"]


# ------------------------------------------------------- live endpoint


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_metrics_endpoint_round_trip(tmp_path):
    obs.configure(str(tmp_path / "run"), run_id="ep1",
                  install_signals=False)
    reg = obs.registry()
    reg.counter("ingest.rows_ok_total").add(3)
    reg.histogram("step_time_ms", buckets=(10.0, 100.0)).observe(42.0)
    reg.gauge("serve/staleness_steps").set(2)
    srv = export.start_metrics_server(0)
    try:
        status, ctype, text = _get(f"{srv.url}/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        # Native histogram exposition, run_id-labelled samples.
        assert ('fm_spark_ingest_rows_ok_total{run_id="ep1"} 3'
                in text)
        assert ('fm_spark_step_time_ms_bucket{run_id="ep1",le="100"} 1'
                in text)
        assert ('fm_spark_step_time_ms_bucket{run_id="ep1",le="+Inf"} 1'
                in text)

        status, ctype, body = _get(f"{srv.url}/healthz")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["status"] == "ok"
        assert doc["run_id"] == "ep1"
        assert doc["staleness_steps"] == 2
        assert doc["captures"] == 0
        # A scrape is READ-ONLY: the gauges /healthz asked about but
        # this process never set must not be conjured into the
        # registry (they would pollute every later snapshot).
        snap = reg.snapshot()
        assert "serve/generation_step" not in snap["gauges"]
        assert "online/auc" not in snap["gauges"]

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{srv.url}/nope")
        assert ei.value.code == 404
    finally:
        export.stop_metrics_server()
    # Stopped: the port no longer answers.
    with pytest.raises(Exception):
        _get(f"{srv.url}/healthz", timeout=2)


def test_start_metrics_server_replaces_previous():
    a = export.start_metrics_server(0)
    b = export.start_metrics_server(0)
    try:
        assert a.port != b.port or a is not b
        status, _, _ = _get(f"{b.url}/healthz")
        assert status == 200
        with pytest.raises(Exception):
            _get(f"{a.url}/healthz", timeout=2)
    finally:
        export.stop_metrics_server()
