"""End-to-end trainer tests (SURVEY.md §4: tiny dataset must beat an AUC
floor) plus reference-semantics checks on the update rule."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import compat, models
from fm_spark_tpu.data import Batches, iterate_once, synthetic_ctr, train_test_split
from fm_spark_tpu.train import FMTrainer, TrainConfig, make_train_step


@pytest.mark.slow
def test_e2e_synthetic_auc_floor():
    """A correct FM trainer must recover planted structure: AUC > 0.70."""
    ids, vals, labels = synthetic_ctr(8000, 200, 5, rank=3, seed=0)
    train, test = train_test_split(ids, vals, labels, 0.25, seed=1)
    spec = models.FMSpec(num_features=200, rank=8, init_std=0.05)
    config = TrainConfig(
        num_steps=600, batch_size=512, learning_rate=0.5,
        optimizer="adagrad", lr_schedule="constant",
        reg_factors=1e-4, seed=0, log_every=200,
    )
    trainer = FMTrainer(spec, config)
    trainer.fit(Batches(*train, config.batch_size, seed=0))
    out = trainer.evaluate(iterate_once(*test, 1024))
    assert out["auc"] > 0.70, out
    assert out["logloss"] < 0.65, out


def test_loss_decreases():
    ids, vals, labels = synthetic_ctr(2000, 100, 4, seed=1)
    spec = models.FMSpec(num_features=100, rank=4)
    config = TrainConfig(num_steps=200, batch_size=256, learning_rate=0.3,
                         log_every=50, seed=0)
    trainer = FMTrainer(spec, config)
    trainer.fit(Batches(ids, vals, labels, 256, seed=0))
    hist = trainer.loss_history
    assert hist[-1] < hist[0]


def test_sgd_reference_rule_values():
    """One step of the default optimizer == w − stepSize/√1·(g + r·w)."""
    spec = models.FMSpec(num_features=10, rank=2, init_std=0.1)
    config = TrainConfig(learning_rate=0.2, lr_schedule="inv_sqrt",
                         optimizer="sgd", reg_linear=0.01, reg_factors=0.05)
    from fm_spark_tpu.train import make_optimizer
    from fm_spark_tpu.ops import losses

    params = spec.init(jax.random.key(0))
    ids = jnp.asarray([[0, 1], [2, 3], [4, 5], [6, 7]], jnp.int32)
    vals = jnp.ones((4, 2))
    labels = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    step = make_train_step(spec, config)
    opt_state = make_optimizer(config).init(params)

    def loss_f(p):
        return jnp.mean(losses.logistic_loss(spec.scores(p, ids, vals), labels))

    grads = jax.grad(loss_f)(params)
    expect_v = params["v"] - 0.2 * (grads["v"] + 0.05 * params["v"])
    expect_w = params["w"] - 0.2 * (grads["w"] + 0.01 * params["w"])
    new_params, _, _ = step(
        dict(params), opt_state, ids, vals, labels, jnp.ones((4,))
    )
    np.testing.assert_allclose(new_params["v"], expect_v, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(new_params["w"], expect_w, rtol=1e-5, atol=1e-6)


def test_compat_fmwithsgd_classification():
    ids, vals, labels = synthetic_ctr(4000, 150, 4, seed=2)
    model = compat.FMWithSGD.train(
        (ids, vals, labels),
        task="classification",
        numIterations=300,
        stepSize=0.5,
        miniBatchFraction=0.1,
        dim=(True, True, 6),
        regParam=(0.0, 1e-4, 1e-4),
        initStd=0.05,
    )
    out = compat.evaluate(model, (ids, vals, labels))
    assert out["auc"] > 0.65, out
    preds = model.predict(ids[:10], vals[:10])
    assert preds.shape == (10,) and np.all((preds >= 0) & (preds <= 1))


def test_compat_regression_clips(tmp_path):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50, size=(500, 3)).astype(np.int32)
    vals = np.ones((500, 3), np.float32)
    labels = rng.uniform(1.0, 5.0, size=(500,)).astype(np.float32)
    model = compat.FMWithSGD.train(
        (ids, vals, labels), task="regression", numIterations=50,
        stepSize=0.05, dim=(True, True, 2),
    )
    assert model.spec.min_target >= 1.0 and model.spec.max_target <= 5.0
    preds = model.predict(ids[:50], vals[:50])
    assert np.all(preds >= model.spec.min_target - 1e-6)
    assert np.all(preds <= model.spec.max_target + 1e-6)
    model.save(str(tmp_path / "m"))
    m2 = compat.FMModel.load(str(tmp_path / "m"))
    np.testing.assert_allclose(m2.predict(ids[:5], vals[:5]), preds[:5], rtol=1e-6)


def test_fit_exhausted_iterable_raises():
    ids, vals, labels = synthetic_ctr(100, 50, 3, seed=0)
    spec = models.FMSpec(num_features=50, rank=2)
    trainer = FMTrainer(spec, TrainConfig(num_steps=100, batch_size=32))
    with pytest.raises(ValueError, match="exhausted"):
        trainer.fit(iterate_once(ids, vals, labels, 32))


def test_field_fm_dense_path_regularizes_vw():
    spec = models.FieldFMSpec(num_features=40, rank=4, num_fields=5, bucket=8,
                              init_std=0.1)
    config = TrainConfig(learning_rate=0.0, reg_factors=0.1, reg_linear=0.2)
    step = make_train_step(spec, config)
    # lr=0 -> params unchanged, but grad_norm must reflect the reg term.
    params = spec.init(jax.random.key(0))
    from fm_spark_tpu.train import make_optimizer
    opt_state = make_optimizer(config).init(params)
    ids = jnp.zeros((4, 5), jnp.int32)
    vals = jnp.zeros((4, 5))  # zero inputs -> zero data gradient
    _, _, m = step(params, opt_state, ids, vals, jnp.zeros((4,)), jnp.ones((4,)))
    assert float(m["grad_norm"]) > 0.0  # pure reg gradient present


def test_regression_rmse_uses_clipped_predictions():
    import numpy as np
    from fm_spark_tpu.train import evaluate_params
    spec = models.FMSpec(num_features=10, rank=2, task="regression",
                         min_target=0.0, max_target=1.0)
    params = spec.init(jax.random.key(0))
    params["w0"] = jnp.float32(50.0)  # raw scores ~50, clipped to 1.0
    ids = np.zeros((8, 2), np.int32)
    vals = np.zeros((8, 2), np.float32)
    labels = np.ones((8,), np.float32)
    out = evaluate_params(spec, params,
                          [(ids, vals, labels, np.ones(8, np.float32))])
    assert out["rmse"] < 1e-5  # clipped prediction == label exactly


def test_eval_every_logs_heldout_metrics():
    import io

    from fm_spark_tpu import models
    from fm_spark_tpu.data import Batches, iterate_once, synthetic_ctr
    from fm_spark_tpu.train import FMTrainer, TrainConfig
    from fm_spark_tpu.utils.logging import MetricsLogger

    ids, vals, labels = synthetic_ctr(2000, 200, 4, seed=0)
    spec = models.FMSpec(num_features=200, rank=4, init_std=0.05)
    config = TrainConfig(num_steps=30, batch_size=256, learning_rate=0.2,
                         eval_every=10, log_every=10)
    trainer = FMTrainer(spec, config)
    stream = io.StringIO()
    trainer.logger = MetricsLogger(stream=stream)
    trainer.fit(
        Batches(ids, vals, labels, 256, seed=0),
        eval_batches=lambda: iterate_once(ids, vals, labels, 512),
    )
    out = stream.getvalue()
    eval_lines = [l for l in out.splitlines() if "eval_auc" in l]
    assert len(eval_lines) == 3  # steps 10, 20, 30
    import json as _json

    last = _json.loads(eval_lines[-1])
    assert 0.0 <= last["eval_auc"] <= 1.0
    assert last["eval_count"] == 2000
