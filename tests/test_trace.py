"""Distributed request tracing across the serving fleet (ISSUE 18).

The load-bearing contracts:

- **header codec is junk-proof** — ``X-FM-Trace`` comes from an
  untrusted peer; malformed/oversized values parse to None, never an
  exception in the replica's request path;
- **keep-alive dispatch** — the fleet parent parks replica
  connections and reuses them (``dispatch_reused_connection_total``
  counts the wins); a stale parked socket costs ONE retry on a fresh
  dial, not a failed request;
- **torn input renders, never crashes** — trace_report skips junk
  JSONL lines and flags a trace whose dispatch erred or whose replica
  hops are missing (the SIGKILL'd-replica shape) as INCOMPLETE;
- **clock skew is corrected** — replica spans are laid on the
  parent's timeline via the NTP-style dispatch/handle estimate, so a
  5-second replica clock error doesn't become a 5-second "hop";
- **the acceptance drill** — a real ``--fleet 2`` CLI run under
  loadgen (with a mid-request replica kill and a byte-torn span file)
  merges into traces with >= 4 hops across >= 3 PIDs, the p99
  exemplar's trace_id resolves to a full merged trace, and
  run_doctor names the dominant hop of the slowest trace.
"""

import importlib.util
import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time
import http.client
import http.server

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fm_spark_tpu import models, obs  # noqa: E402
from fm_spark_tpu.obs.trace import TraceContext  # noqa: E402
from fm_spark_tpu.resilience import faults  # noqa: E402
from fm_spark_tpu.serve import loadgen  # noqa: E402
from fm_spark_tpu.serve import fleet as fleet_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name: str):
    path = os.path.join(REPO, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- context codec


def test_trace_context_header_round_trip():
    ctx = TraceContext("abc123")
    assert ctx.to_header() == "abc123;"
    rt = TraceContext.from_header(ctx.to_header())
    assert rt.trace_id == "abc123" and rt.parent_span_id is None

    rt = TraceContext.from_header("abc123;dead-beef")
    assert rt.trace_id == "abc123"
    assert rt.parent_span_id == "dead-beef"
    assert TraceContext.from_header(rt.to_header()).parent_span_id == \
        "dead-beef"


def test_trace_context_rejects_junk():
    # None/empty/malformed/oversized/wrong-typed header values all
    # parse to None — the replica must never 500 on a hostile header.
    for junk in (None, "", ";", "  ;  ", "bad$id;x", ";orphan-parent",
                 "a" * 200 + ";x", 42, 3.14, b"x;y", ["x"]):
        assert TraceContext.from_header(junk) is None, junk
    # A bad PARENT token is dropped but the trace id survives: half a
    # link beats a torn trace.
    rt = TraceContext.from_header("abc123;bad$parent")
    assert rt.trace_id == "abc123" and rt.parent_span_id is None


def test_trace_context_child_links_downstream():
    ctx = TraceContext("t1")
    child = ctx.child("aaa-1")
    assert child is not ctx
    assert child.trace_id == "t1" and child.parent_span_id == "aaa-1"
    # span_id None (tracing disabled at this hop): the chain degrades
    # to the upstream parent rather than breaking.
    assert ctx.child(None) is ctx


def test_mint_trace_sampling_and_disabled_path(tmp_path):
    obs.shutdown(reason=None)
    # Unconfigured process: no trace, no urandom cost (the <=1% bound
    # in test_obs_overhead rides this early-out).
    assert obs.mint_trace() is None
    assert obs.mint_trace(sample=1.0) is None
    obs.configure(str(tmp_path / "run"), run_id="mint",
                  install_signals=False)
    try:
        minted = {obs.mint_trace().trace_id for _ in range(8)}
        assert len(minted) == 8, "trace ids must be unique"
        assert all(TraceContext.from_header(f"{t};") for t in minted)
        # sample=0.0 keeps nothing; deterministic, not probabilistic.
        assert all(obs.mint_trace(sample=0.0) is None
                   for _ in range(32))
    finally:
        obs.shutdown(reason=None)


# -------------------------------------------------- exemplars + rollup


def test_histogram_exemplars_tail_buckets_remember_traces():
    from fm_spark_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = reg.histogram("req_ms", buckets=(1.0, 10.0))
    h.observe(0.5)                        # untagged: bucket stays bare
    h.observe(5.0, exemplar="t-mid")
    h.observe(9999.0, exemplar="t-tail")
    h.observe(8888.0, exemplar="t-tail2")  # LAST in the bucket wins
    ex = h.exemplars()
    assert "1" not in ex
    assert ex["10"] == {"value": 5.0, "trace_id": "t-mid"}
    assert ex["+Inf"] == {"value": 8888.0, "trace_id": "t-tail2"}
    assert h.summary()["exemplars"] == ex

    # OpenMetrics exposition carries the exemplar suffix — the
    # trace_id a Grafana panel shows next to the p99 line.
    text = reg.prometheus_text()
    assert 'trace_id="t-tail2"' in text
    assert " # {" in text

    # bucket_snapshot is the raw form the fleet rollup ships.
    snap = reg.bucket_snapshot()
    assert snap["req_ms"]["exemplars"] == ex
    assert snap["req_ms"]["counts"] == [1, 1, 2]


def test_render_fleet_metrics_labels_and_bucket_sums():
    from fm_spark_tpu.obs.export import render_fleet_metrics

    assert render_fleet_metrics(None) == ""
    assert render_fleet_metrics({"replicas": {}}) == ""

    def rep(requests, counts, count, total):
        return {
            "pid": 1,
            "snapshot": {"counters": {"serve.requests_total": requests},
                         "gauges": {"engine.depth": 1.5}},
            "buckets": {"serve/request_ms": {
                "bounds": [1.0, 10.0], "counts": counts,
                "count": count, "sum": total, "exemplars": {}}},
        }

    text = render_fleet_metrics({"replicas": {
        0: rep(5, [1, 2, 3], 6, 42.0),
        1: rep(7, [0, 1, 1], 2, 8.0),
        2: "not a dict — a half-scraped replica must not break /metrics",
    }})
    assert 'fm_spark_fleet_serve_requests_total{replica="0"} 5' in text
    assert 'fm_spark_fleet_serve_requests_total{replica="1"} 7' in text
    assert 'fm_spark_fleet_engine_depth{replica="0"} 1.5' in text
    # One TYPE line per metric, not per replica.
    assert text.count(
        "# TYPE fm_spark_fleet_serve_requests_total counter") == 1
    # Histogram aggregate: raw bucket counts summed element-wise,
    # re-exposed cumulatively ([1,3,4] -> 1, 4, +Inf 8).
    assert 'fm_spark_fleet_serve_request_ms_bucket{le="1"} 1' in text
    assert 'fm_spark_fleet_serve_request_ms_bucket{le="10"} 4' in text
    assert 'fm_spark_fleet_serve_request_ms_bucket{le="+Inf"} 8' in text
    assert "fm_spark_fleet_serve_request_ms_count 8" in text
    assert "fm_spark_fleet_serve_request_ms_sum 50" in text


# ------------------------------------------------- keep-alive dispatch


class _ReplicaStub(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length") or 0))
        self.server.trace_headers.append(
            self.headers.get(obs.TRACE_HEADER))
        body = json.dumps({"ok": True}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def test_dispatch_keepalive_reuses_and_survives_stale_socket():
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _ReplicaStub)
    srv.trace_headers = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    pool = fleet_mod.ConnectionPool("127.0.0.1", port)
    ctr = obs.counter("fleet.dispatch_reused_connection_total")
    c0 = ctr.value
    try:
        st, _doc = fleet_mod._http_json(
            "127.0.0.1", port, "POST", "/predict", body={"x": 1},
            pool=pool, trace=TraceContext("tid1", "par-1"))
        assert st == 200
        assert ctr.value == c0, "first dispatch dials fresh"

        st, _doc = fleet_mod._http_json(
            "127.0.0.1", port, "POST", "/predict", body={"x": 2},
            pool=pool, trace=TraceContext("tid1", "par-2"))
        assert st == 200
        assert ctr.value == c0 + 1, "second dispatch rides the parked socket"
        # Both hops carried the context header (what the fmlint
        # trace-propagation rule pins statically).
        assert srv.trace_headers == ["tid1;par-1", "tid1;par-2"]

        # A replica that died between dispatches leaves a dead parked
        # socket: park one wired to a peer that's already gone and the
        # next dispatch must retry ONCE on a fresh dial and succeed.
        lst = socket.create_server(("127.0.0.1", 0))
        stale = http.client.HTTPConnection("127.0.0.1", port)
        stale.sock = socket.create_connection(lst.getsockname())
        peer, _addr = lst.accept()
        peer.close()
        lst.close()
        pool.give(stale)
        st, doc = fleet_mod._http_json(
            "127.0.0.1", port, "POST", "/predict", body={"x": 3},
            pool=pool)
        assert st == 200 and doc == {"ok": True}
        assert ctr.value == c0 + 1, "the stale-retry dial is not a reuse"
    finally:
        pool.close()
        srv.shutdown()
        srv.server_close()


def test_connection_pool_bounds_idle():
    pool = fleet_mod.ConnectionPool("127.0.0.1", 1, max_idle=2)
    conns = [pool.fresh() for _ in range(3)]
    for c in conns:
        pool.give(c)              # third one is closed, not parked
    assert len(pool._idle) == 2
    c, reused = pool.take()
    assert reused and c is conns[1], "LIFO: hottest socket first"
    pool.close()
    assert pool.take()[1] is False, "closed pool still dials fresh"


# --------------------------------------------------- trace_report unit

CLIENT_PID, PARENT_PID, REPLICA_PID = 0xCCC, 0xAAA, 0xBBB
#: Replica wall clock runs 5 s ahead of the parent's in the synthetic
#: fixture; the NTP-style estimate must recover exactly this.
SKEW_S = 5.0


def _span(pid, seq, name, trace, t_start, dur_ms, **attrs):
    return {"event": "span", "name": name,
            "span_id": f"{pid:x}-{seq:x}", "t_start": t_start,
            "dur_ms": dur_ms, "trace": trace, **attrs}


def _write_jsonl(path, docs, tail=b""):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        for d in docs:
            f.write((json.dumps(d) + "\n").encode())
        f.write(tail)


def _synthetic_root(tmp_path) -> str:
    """Three per-process run dirs under one obs root: a complete trace
    ``aaa111`` (replica clock +5 s skewed) and a torn trace ``bbb222``
    whose replica died mid-request (dispatch erred, replica hops never
    written)."""
    root = str(tmp_path / "obs")
    _write_jsonl(os.path.join(root, "client", "trace.jsonl"), [
        _span(CLIENT_PID, 1, "client/request", "aaa111", 99.99, 130.0),
        {"event": "metric", "name": "noise"},       # non-span: ignored
        _span(CLIENT_PID, 2, "client/request", None, 99.0, 1.0),
    ])
    _write_jsonl(os.path.join(root, "parent", "trace.jsonl"), [
        _span(PARENT_PID, 1, "frontdoor/admit", "aaa111", 99.995, 1.0),
        _span(PARENT_PID, 2, "frontdoor/request", "aaa111", 100.0,
              120.0),
        _span(PARENT_PID, 3, "fleet/dispatch", "aaa111", 100.01, 100.0,
              replica=0),
        # Trace bbb222: the replica was killed mid-handle. Its spans
        # never hit disk; the parent's dispatch carries the error.
        _span(PARENT_PID, 4, "frontdoor/admit", "bbb222", 200.0, 1.0),
        _span(PARENT_PID, 5, "frontdoor/request", "bbb222", 200.0,
              50.0),
        _span(PARENT_PID, 6, "fleet/dispatch", "bbb222", 200.001, 49.0,
              replica=1, error="RemoteDisconnected"),
    ])
    # The replica's file ends in a torn line AND raw junk — the shape
    # a SIGKILL leaves behind. Both must be skipped, not fatal.
    _write_jsonl(
        os.path.join(root, "replica", "trace.jsonl"),
        [_span(REPLICA_PID, 1, "replica/handle", "aaa111",
               100.03 + SKEW_S, 60.0,
               remote_parent=f"{PARENT_PID:x}-3"),
         _span(REPLICA_PID, 2, "serve/coalesce", "aaa111",
               100.04 + SKEW_S, 40.0, queue_ms=5.0, exec_ms=30.0,
               split_ms=2.0)],
        tail=b'{"event": "span", "name": "replica/ha\nnot json at all\n')
    _write_jsonl(os.path.join(root, "parent", "metrics.jsonl"), [
        {"histograms": {"frontdoor/request_ms": {"exemplars": {
            "+Inf": {"value": 10.0, "trace_id": "stale-snapshot"}}}}},
        {"histograms": {"frontdoor/request_ms": {"exemplars": {
            "100": {"value": 42.0, "trace_id": "bbb222"},
            "+Inf": {"value": 120.0, "trace_id": "aaa111"}}}}},
    ])
    return root


def test_trace_report_merges_and_corrects_skew(tmp_path):
    tr = _load_tool("trace_report")
    root = _synthetic_root(tmp_path)

    skew = tr.estimate_skew(tr.collect(root))
    assert skew[(PARENT_PID, REPLICA_PID)] == pytest.approx(SKEW_S,
                                                            abs=1e-6)

    merged = tr.merge(root)
    assert set(merged) == {"aaa111", "bbb222"}

    full = merged["aaa111"]
    assert full["hops"] == 6
    assert full["pids"] == sorted([PARENT_PID, REPLICA_PID, CLIENT_PID])
    assert not full["incomplete"]
    # Uncorrected, the skewed replica spans would stretch this to ~5 s;
    # corrected, the client's round trip bounds the trace.
    assert full["total_ms"] == pytest.approx(130.0, abs=0.01)

    bd = tr.breakdown(full)
    assert bd["client"] == 130.0
    assert bd["admission"] == 1.0
    assert bd["frontdoor"] == pytest.approx(20.0)   # request - dispatch
    assert bd["transport"] == pytest.approx(40.0)   # dispatch - handle
    assert bd["replica"] == pytest.approx(20.0)     # handle - coalesce
    assert (bd["coalesce_wait"], bd["execute"], bd["split"]) == \
        (5.0, 30.0, 2.0)
    assert bd["dominant"] == "transport"


def test_trace_report_flags_torn_trace_and_resolves_exemplar(tmp_path):
    tr = _load_tool("trace_report")
    root = _synthetic_root(tmp_path)
    merged = tr.merge(root)

    torn = merged["bbb222"]
    assert torn["incomplete"]
    assert torn["error_hops"] == ["fleet/dispatch"]
    assert set(torn["missing"]) == {"replica/handle", "serve/coalesce"}

    ex = tr.tail_exemplar(root)
    assert ex == {"trace_id": "aaa111", "value": 120.0, "le": "+Inf"}

    out = tr.render_trace(merged["aaa111"])
    assert "<-- dominant" in out and "dispatch transport" in out
    out = tr.render_trace(torn)
    assert "INCOMPLETE" in out and "fleet/dispatch (error)" in out
    assert "(missing)" in out

    full = tr.render(merged, root=root)
    assert "tail exemplar: trace aaa111" in full
    assert "resolves to a merged trace" in full
    assert "1 trace(s) incomplete" in full


def test_trace_report_cli(tmp_path, capsys):
    tr = _load_tool("trace_report")
    root = _synthetic_root(tmp_path)
    assert tr.main([root]) == 0
    out = capsys.readouterr().out
    assert "# Request traces (2 merged)" in out
    assert tr.main([root, "--trace", "bbb222"]) == 0
    assert "INCOMPLETE" in capsys.readouterr().out
    assert tr.main([root, "--trace", "nope"]) == 1
    assert tr.main([str(tmp_path / "missing")]) == 2


def test_run_doctor_tracing_section_on_synthetic_root(tmp_path):
    doctor = _load_tool("run_doctor")
    root = _synthetic_root(tmp_path)
    tracing = doctor.tracing_diagnose(os.path.join(root, "parent"))
    assert tracing["n_traces"] == 2 and tracing["incomplete"] == 1
    assert tracing["top"][0]["trace_id"] == "aaa111"
    assert tracing["top"][0]["dominant"] == "transport"
    assert tracing["exemplar"]["resolved"] is True

    notes = doctor.tracing_findings(tracing)
    joined = "\n".join(notes)
    assert "dominant hop transport" in joined
    assert "1 of 2 trace(s) INCOMPLETE" in joined

    # An exemplar pointing at a trace nobody's span file holds is a
    # finding, not a pass: the writer died before its first flush.
    tracing["exemplar"] = {"trace_id": "ghost", "value": 1.0,
                           "le": "+Inf", "resolved": False}
    assert any("does NOT resolve" in n
               for n in doctor.tracing_findings(tracing))


# ---------------------------------------- the fleet acceptance drill


def _drain(stream, sink: "queue.Queue[str]"):
    for line in iter(stream.readline, ""):
        sink.put(line)
    sink.put("")


def _next_doc(sink, key, deadline_s, proc, stderr_path):
    """The next stdout JSON line carrying ``key``, within a budget."""
    t_end = time.monotonic() + deadline_s
    while True:
        left = t_end - time.monotonic()
        if left <= 0 or proc.poll() is not None:
            with open(stderr_path, errors="replace") as f:
                err = f.read()[-4000:]
            raise AssertionError(
                f"no {key!r} line from the serve process "
                f"(rc={proc.poll()}); stderr tail:\n{err}")
        try:
            line = sink.get(timeout=min(left, 1.0))
        except queue.Empty:
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and key in doc:
            return doc


def test_fleet_tracing_end_to_end(tmp_path):
    """ISSUE 18 acceptance: a ``--fleet 2`` CLI run under loadgen —
    with a replica SIGKILL'd mid-request and a span file torn at the
    byte level afterwards — must still merge into at least one trace
    with >= 4 hops spanning >= 3 PIDs (client, front-door parent,
    replica), flag the killed request's trace INCOMPLETE, resolve the
    p99 exemplar's trace_id to a full merged trace, count reused
    dispatch connections, and show up in run_doctor with a dominant
    hop."""
    spec = models.FieldFMSpec(num_features=4 * 64, rank=4, num_fields=4,
                              bucket=64, init_std=0.1)
    model_dir = str(tmp_path / "model")
    models.save_model(model_dir, spec, spec.init(jax.random.key(0)))
    obs_root = str(tmp_path / "obs")

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           # The kill plan rides the environment into the REPLICAS
           # (the parent never arms the replica_kill point): the 4th
           # handled request across the fleet dies mid-flight.
           faults.ENV_PLAN: "replica_kill@4=exit:9",
           faults.ENV_STATE: str(tmp_path / "fault_state.json")}
    stderr_path = str(tmp_path / "serve.stderr")
    proc = subprocess.Popen(
        [sys.executable, "-m", "fm_spark_tpu.cli", "serve",
         "--fleet", "2", "--model", model_dir, "--buckets", "1,4",
         "--obs-dir", obs_root, "--compile-cache",
         str(tmp_path / "cache"), "--frontdoor-port", "0",
         "--trace-sample", "1.0", "--latency-budget-ms", "0",
         "--reload-poll-s", "0"],
        stdout=subprocess.PIPE, stderr=open(stderr_path, "w"),
        text=True, cwd=REPO, env=env)
    sink: "queue.Queue[str]" = queue.Queue()
    threading.Thread(target=_drain, args=(proc.stdout, sink),
                     daemon=True).start()
    run_id = None
    try:
        run_id = _next_doc(sink, "run_id", 60, proc,
                           stderr_path)["run_id"]
        door = _next_doc(sink, "frontdoor", 300, proc,
                         stderr_path)["frontdoor"]
        host, port = door["url"].split("//", 1)[1].split(":")

        # The loadgen runs IN THIS PROCESS with its own obs run dir
        # under the same root — its client/request spans are the
        # trace's third PID.
        obs.shutdown(reason=None)
        obs.configure(os.path.join(obs_root, "client0"),
                      run_id="client0", install_signals=False)
        try:
            sched = loadgen.make_schedule(
                "flash_crowd", 5, duration_s=0.6, base_rps=30.0,
                rows=2, deadline_ms=8000.0)
            assert sched.n_requests > 4  # the kill fires mid-burst
            summary = loadgen.run_loadgen(
                host, int(port), sched, str(tmp_path / "tap.jsonl"),
                nnz=spec.num_fields, num_features=spec.num_features,
                threads=4, attempt_timeout_s=60.0)
            assert summary["by_outcome"].get("ok", 0) > 4, summary
        finally:
            obs.shutdown(reason="loadgen done")

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0
    finally:
        proc.kill()
        proc.wait(timeout=30)

    # The torn-file drill on REAL output: rip the tail of one replica
    # span file mid-record. The merge must shrug, not crash.
    replica_traces = [
        os.path.join(obs_root, d, "trace.jsonl")
        for d in os.listdir(obs_root)
        if d not in (run_id, "client0")
        and os.path.exists(os.path.join(obs_root, d, "trace.jsonl"))]
    assert replica_traces, "replicas wrote no span files"
    with open(replica_traces[0], "ab") as f:
        f.write(b'{"event": "span", "name": "replica/hand')

    tr = _load_tool("trace_report")
    merged = tr.merge(obs_root)
    assert merged, "no traces merged from the fleet run"

    # >= 4 hops across >= 3 processes, including THIS process (the
    # client) and the CLI parent (front door + fleet).
    full = [t for t in merged.values()
            if t["hops"] >= 4 and len(t["pids"]) >= 3]
    assert full, {tid: (t["hops"], t["pids"])
                  for tid, t in merged.items()}
    assert any(os.getpid() in t["pids"] and proc.pid in t["pids"]
               for t in full)
    # Every trace names a dominant hop.
    assert all(tr.breakdown(t)["dominant"] for t in full)

    # The killed request's trace survives INCOMPLETE (errored dispatch
    # hop and/or replica hops that never hit the dead replica's file).
    assert any(t["incomplete"] for t in merged.values()), \
        "replica_kill left no incomplete trace"

    # The p99 exemplar resolves to one concrete, fully-merged trace.
    ex = tr.tail_exemplar(obs_root)
    assert ex is not None, "front door exported no exemplars"
    assert ex["trace_id"] in merged
    assert merged[ex["trace_id"]]["hops"] >= 4

    # Keep-alive dispatch earned reuses on the real fleet path.
    with open(os.path.join(obs_root, run_id, "metrics.jsonl"),
              errors="replace") as f:
        last = [json.loads(ln) for ln in f if ln.strip()][-1]
    assert last["counters"].get(
        "fleet.dispatch_reused_connection_total", 0) >= 1

    # run_doctor stitches it into the diagnosis: section + dominant
    # hop of the slowest trace.
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_doctor.py"),
         "--run-id", run_id, obs_root],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Request tracing" in out.stdout
    assert "dominant hop" in out.stdout
