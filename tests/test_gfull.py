"""gfull_fused backward ≡ the concat g_full construction, to ≤8 ULP.

PERF.md round-4 lever: TrainConfig.gfull_fused rebuilds each field's
fused row update as one elementwise expression (s1/colmask form) instead
of ``concat([g_v, g_l])``. The two are the same arithmetic — ×1.0 and a
select are IEEE-exact — but XLA may CONTRACT the two graphs differently
(fma fusion), so the bar is a tight ULP bound (see _assert_ulp), not
bit-equality. That tolerance class is what lets the flag flip on purely
perf evidence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_spark_tpu import models
from fm_spark_tpu.ops.scatter import compact_aux
from fm_spark_tpu.sparse import (
    make_field_ffm_sparse_sgd_body,
    make_field_deepfm_sparse_step,
    make_field_sparse_sgd_step,
    make_sparse_sgd_step,
)
from fm_spark_tpu.train import TrainConfig

F, BUCKET, K, B = 5, 32, 4, 48
CAP = 24


def _spec(use_linear=True):
    return models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.1, use_linear=use_linear,
    )


def _batches(rng, n=3):
    out = []
    for _ in range(n):
        # Narrow id range → plenty of in-batch duplicates (the dedup
        # modes' interesting regime); CAP bounds the unique count.
        ids = rng.integers(0, BUCKET // 2, size=(B, F)).astype(np.int32)
        vals = rng.uniform(0.5, 1.5, size=(B, F)).astype(np.float32)
        labels = rng.integers(0, 2, B).astype(np.float32)
        weights = np.ones((B,), np.float32)
        weights[-4:] = 0.0  # padding rows exercise the touched mask
        out.append((ids, vals, labels, weights))
    return out


def _run(spec, config, batches):
    step = make_field_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(7))
    losses = []
    for i, (ids, vals, labels, weights) in enumerate(batches):
        aux = None
        if config.host_dedup:
            aux = jax.device_put(
                compact_aux(ids, config.compact_cap)
                if config.compact_cap else None
            )
        params, loss = step(
            params, jnp.int32(i), jnp.asarray(ids), jnp.asarray(vals),
            jnp.asarray(labels), jnp.asarray(weights), aux,
        )
        losses.append(float(loss))
    return jax.device_get(params), losses


def _assert_ulp(a, b, max_ulp=8, msg=""):
    # ≤8 ULP: the two graphs are the same arithmetic, but XLA contracts
    # them differently (fma), and the ~1-ULP per-element noise compounds
    # through the dedup modes' segment sums and across steps (observed
    # max: 4 ULP after 3 steps). 8 ULP ≈ rtol 1e-6 — far inside any
    # training-relevant tolerance while still pinning the formulation.
    # atol floor 1e-9: near-zero params turn sub-nano absolute diffs
    # into large ULP counts (cancellation in the update sum) — observed
    # 80 "ULP" on a 4e-5 element whose absolute diff was 3e-10.
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype, msg
    d = np.abs(a.astype(np.float64) - b.astype(np.float64))
    if not d.any():
        return
    ulp = np.where(
        d < 1e-9, 0.0, d / np.spacing(np.maximum(np.abs(a), np.abs(b)))
    )
    assert ulp.max() <= max_ulp, f"{msg}: max {ulp.max()} ULP"


MODES = {
    "scatter_add": dict(sparse_update="scatter_add"),
    "dedup": dict(sparse_update="dedup"),
    "dedup_sr": dict(sparse_update="dedup_sr"),
    "compact_host": dict(sparse_update="dedup", host_dedup=True,
                         compact_cap=CAP),
    "compact_host_sr": dict(sparse_update="dedup_sr", host_dedup=True,
                            compact_cap=CAP),
    "compact_device": dict(sparse_update="dedup", compact_device=True,
                           compact_cap=CAP),
}
REGS = {
    "noreg": dict(),
    "factors": dict(reg_factors=1e-3),
    "linear": dict(reg_linear=1e-4),
    "both": dict(reg_factors=1e-3, reg_linear=1e-4),
}


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("reg", ["noreg", "both"])
def test_gfull_one_step_ulp_tight(mode, reg):
    # ONE step: contraction noise cannot compound through the training
    # dynamics, so the bound is a handful of ULP.
    spec = _spec()
    batches = _batches(np.random.default_rng(0), n=1)
    base = dict(learning_rate=0.3, lr_schedule="inv_sqrt",
                optimizer="sgd", **MODES[mode], **REGS[reg])
    p_ref, l_ref = _run(spec, TrainConfig(**base), batches)
    p_gf, l_gf = _run(spec, TrainConfig(**base, gfull_fused=True), batches)
    np.testing.assert_allclose(l_ref, l_gf, rtol=1e-6)
    _assert_ulp(p_ref["w0"], p_gf["w0"], msg="w0")
    for f in range(F):
        _assert_ulp(p_ref["vw"][f], p_gf["vw"][f], msg=f"vw[{f}]")


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("reg", ["noreg", "both"])
def test_gfull_multi_step_close(mode, reg):
    # THREE steps: the ~1-ULP per-step noise feeds back through the
    # params (observed up to ~100 ULP at lr 0.3), so the multi-step bar
    # is a conventional tight allclose, not ULP.
    spec = _spec()
    batches = _batches(np.random.default_rng(0))
    base = dict(learning_rate=0.3, lr_schedule="inv_sqrt",
                optimizer="sgd", **MODES[mode], **REGS[reg])
    p_ref, l_ref = _run(spec, TrainConfig(**base), batches)
    p_gf, l_gf = _run(spec, TrainConfig(**base, gfull_fused=True), batches)
    np.testing.assert_allclose(l_ref, l_gf, rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            p_ref["vw"][f], p_gf["vw"][f], rtol=1e-5, atol=1e-8,
            err_msg=f"vw[{f}]")


@pytest.mark.parametrize("reg", list(REGS))
def test_gfull_reg_splits_bitwise(reg):
    # Every reg split (factors-only must not leak into the linear column
    # and vice versa — the rv vector's whole job).
    spec = _spec()
    batches = _batches(np.random.default_rng(1), n=1)
    base = dict(learning_rate=0.2, optimizer="sgd", **REGS[reg])
    p_ref, _ = _run(spec, TrainConfig(**base), batches)
    p_gf, _ = _run(spec, TrainConfig(**base, gfull_fused=True), batches)
    for f in range(F):
        np.testing.assert_allclose(
            p_ref["vw"][f], p_gf["vw"][f], rtol=1e-5, atol=1e-8,
            err_msg=f"vw[{f}]")


def test_gfull_no_linear_bitwise():
    spec = _spec(use_linear=False)
    batches = _batches(np.random.default_rng(2), n=1)
    base = dict(learning_rate=0.2, optimizer="sgd", reg_factors=1e-3)
    p_ref, _ = _run(spec, TrainConfig(**base), batches)
    p_gf, _ = _run(spec, TrainConfig(**base, gfull_fused=True), batches)
    for f in range(F):
        _assert_ulp(p_ref["vw"][f], p_gf["vw"][f], msg=f"vw[{f}]")


def test_gfull_sharded_bitwise(eight_devices):
    # Same mesh, flag on vs off → identical collective schedule, so the
    # sharded results must be bit-identical too.
    from fm_spark_tpu.parallel import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        pad_field_batch,
        shard_field_batch,
        shard_field_params,
        stack_field_params,
        unstack_field_params,
    )

    n_feat = 4
    spec = _spec()
    config = dict(learning_rate=0.3, optimizer="sgd",
                  reg_factors=1e-3, reg_linear=1e-4)
    mesh = make_field_mesh(n_feat, devices=eight_devices)
    init = spec.init(jax.random.key(3))
    outs = []
    for gf in (False, True):
        params = shard_field_params(
            stack_field_params(
                spec, jax.tree_util.tree_map(jnp.copy, init), n_feat),
            mesh,
        )
        step = make_field_sharded_sgd_step(
            spec, TrainConfig(**config, gfull_fused=gf), mesh)
        rng = np.random.default_rng(4)
        for i, batch in enumerate(_batches(rng, n=1)):
            sb = shard_field_batch(
                pad_field_batch(batch, F, n_feat), mesh)
            params, loss = step(params, jnp.int32(i), *sb)
        outs.append(
            (unstack_field_params(spec, jax.device_get(params)),
             float(loss)))
    (p_ref, l_ref), (p_gf, l_gf) = outs
    np.testing.assert_allclose(l_ref, l_gf, rtol=1e-6)
    _assert_ulp(p_ref["w0"], p_gf["w0"], msg="w0")
    for f in range(F):
        _assert_ulp(p_ref["vw"][f], p_gf["vw"][f], msg=f"vw[{f}]")


def test_gfull_rejected_where_unimplemented(eight_devices):
    config = TrainConfig(optimizer="sgd", gfull_fused=True)
    ffm = models.FieldFFMSpec(
        num_features=F * BUCKET, rank=2, num_fields=F, bucket=BUCKET)
    with pytest.raises(ValueError, match="gfull_fused"):
        make_field_ffm_sparse_sgd_body(ffm, config)
    flat = models.FMSpec(num_features=100, rank=2)
    with pytest.raises(ValueError, match="gfull_fused"):
        make_sparse_sgd_step(flat, config)
    from fm_spark_tpu.parallel import make_field_mesh
    from fm_spark_tpu.parallel.field_step import (
        make_field_ffm_sharded_body,
    )

    mesh = make_field_mesh(4, devices=eight_devices)
    with pytest.raises(ValueError, match="gfull_fused"):
        make_field_ffm_sharded_body(ffm, config, mesh)


@pytest.mark.parametrize("reg", ["noreg", "both"])
def test_gfull_deepfm_single_chip(reg):
    # DeepFM (round 4): the deep-head pullback rides _gfull_grads'
    # `extra` tensor (one pad, no per-field concat). The shared ·x
    # right-distributes in the fused form, so the bar is a tight
    # allclose, not ULP (one extra reassociation per element).
    deep = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(8, 8), init_std=0.1)
    batches = _batches(np.random.default_rng(5), n=2)
    base = dict(learning_rate=0.05, optimizer="adam", **REGS[reg])

    def run(gf):
        step = make_field_deepfm_sparse_step(
            deep, TrainConfig(**base, gfull_fused=gf))
        params = deep.init(jax.random.key(11))
        opt = step.init_opt_state(params)
        for i, (ids, vals, labels, weights) in enumerate(batches):
            params, opt, loss = step(
                params, opt, jnp.int32(i), jnp.asarray(ids),
                jnp.asarray(vals), jnp.asarray(labels),
                jnp.asarray(weights))
        return jax.device_get(params), float(loss)

    p_ref, l_ref = run(False)
    p_gf, l_gf = run(True)
    np.testing.assert_allclose(l_ref, l_gf, rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            p_ref["vw"][f], p_gf["vw"][f], rtol=1e-5, atol=1e-7,
            err_msg=f"vw[{f}]")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                atol=1e-7),
        p_ref["mlp"], p_gf["mlp"])


@pytest.mark.parametrize("n_row", [1, 2])
def test_gfull_deepfm_sharded(eight_devices, n_row):
    from fm_spark_tpu.parallel import make_field_mesh
    from fm_spark_tpu.parallel.field_step import (
        make_field_deepfm_sharded_step,
        shard_field_deepfm_params,
        stack_field_deepfm_params,
        unstack_field_deepfm_params,
    )

    n_feat = 4
    deep = models.FieldDeepFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        mlp_dims=(8,), init_std=0.1)
    mesh = make_field_mesh(n_feat * n_row, devices=eight_devices,
                           n_row=n_row)
    from fm_spark_tpu.parallel import (
        pad_field_batch,
        shard_field_batch,
    )

    batches = _batches(np.random.default_rng(6), n=2)
    base = dict(learning_rate=0.05, optimizer="adam",
                reg_factors=1e-3, reg_linear=1e-4)

    def run(gf):
        step = make_field_deepfm_sharded_step(
            deep, TrainConfig(**base, gfull_fused=gf), mesh)
        params = shard_field_deepfm_params(
            stack_field_deepfm_params(
                deep, deep.init(jax.random.key(12)), n_feat), mesh)
        opt = step.init_opt_state(params)
        for i, batch in enumerate(batches):
            sb = shard_field_batch(pad_field_batch(batch, F, n_feat),
                                   mesh)
            params, opt, loss = step(params, opt, jnp.int32(i), *sb)
        return (unstack_field_deepfm_params(deep, jax.device_get(params)),
                float(loss))

    p_ref, l_ref = run(False)
    p_gf, l_gf = run(True)
    np.testing.assert_allclose(l_ref, l_gf, rtol=1e-6)
    for f in range(F):
        np.testing.assert_allclose(
            p_ref["vw"][f], p_gf["vw"][f], rtol=1e-5, atol=1e-7,
            err_msg=f"vw[{f}]")


def test_gfull_requires_fused_linear():
    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        fused_linear=False,
    )
    with pytest.raises(ValueError, match="fused_linear"):
        make_field_sparse_sgd_step(
            spec, TrainConfig(optimizer="sgd", gfull_fused=True))
