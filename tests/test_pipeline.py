"""Batch pipeline: determinism, resume, epoch coverage, padding."""

import numpy as np

from fm_spark_tpu.data import Batches, iterate_once, synthetic_ctr, train_test_split


def _data(n=103, nnz=4, f=40):
    return synthetic_ctr(n, f, nnz, seed=3)


def test_epoch_covers_every_example_once():
    ids, vals, labels = _data()
    b = Batches(ids, vals, labels, batch_size=20, seed=1)
    seen = []
    # 103 examples → 6 batches (last padded with 17 zero-weight slots).
    for _ in range(6):
        bi, bv, bl, bw = b.next_batch()
        order = np.flatnonzero(bw > 0)
        seen.extend(bi[order][:, 0].tolist())
    assert len(seen) == 103
    assert b.epoch == 1 and b.index == 0


def test_determinism_and_resume():
    ids, vals, labels = _data()
    b1 = Batches(ids, vals, labels, batch_size=16, seed=7)
    for _ in range(3):
        b1.next_batch()
    state = b1.state()
    want = [b1.next_batch() for _ in range(4)]
    b2 = Batches(ids, vals, labels, batch_size=16, seed=7)
    b2.restore(state)
    got = [b2.next_batch() for _ in range(4)]
    for (a_ids, a_vals, a_l, a_w), (c_ids, c_vals, c_l, c_w) in zip(want, got):
        np.testing.assert_array_equal(a_ids, c_ids)
        np.testing.assert_array_equal(a_l, c_l)
        np.testing.assert_array_equal(a_w, c_w)


def test_restore_wrong_seed_raises():
    ids, vals, labels = _data()
    b = Batches(ids, vals, labels, batch_size=16, seed=1)
    import pytest

    with pytest.raises(ValueError):
        b.restore({"epoch": 0, "index": 0, "seed": 2})


def test_epochs_reshuffle():
    ids, vals, labels = _data(n=64)
    b = Batches(ids, vals, labels, batch_size=64, seed=0)
    e0 = b.next_batch()[0].copy()
    e1 = b.next_batch()[0].copy()
    assert not np.array_equal(e0, e1)
    assert set(map(tuple, e0)) == set(map(tuple, e1))  # same examples


def test_iterate_once_padding():
    ids, vals, labels = _data(n=50)
    batches = list(iterate_once(ids, vals, labels, 16))
    assert len(batches) == 4
    assert all(b[0].shape[0] == 16 for b in batches)
    total = sum(int(b[3].sum()) for b in batches)
    assert total == 50


def test_train_test_split_disjoint_and_total():
    ids, vals, labels = _data(n=100)
    (tr_i, _, tr_l), (te_i, _, te_l) = train_test_split(ids, vals, labels, 0.25, seed=0)
    assert tr_i.shape[0] == 75 and te_i.shape[0] == 25
    assert tr_l.shape[0] + te_l.shape[0] == 100


def test_batches_rejects_impossible_config():
    import pytest
    ids, vals, labels = _data(n=10)
    with pytest.raises(ValueError, match="exceeds dataset"):
        Batches(ids, vals, labels, batch_size=64, drop_remainder=True)
    with pytest.raises(ValueError, match="empty"):
        Batches(ids[:0], vals[:0], labels[:0], batch_size=4)


# ------------------------------------------------------------- Prefetcher


def test_prefetcher_same_stream_and_state_resume():
    from fm_spark_tpu.data import Prefetcher

    ids, vals, labels = _data(n=200)
    ref = Batches(ids, vals, labels, batch_size=32, seed=7)
    src = Batches(ids, vals, labels, batch_size=32, seed=7)
    with Prefetcher(src, depth=3) as pf:
        states = []
        for _ in range(9):
            a = ref.next_batch()
            b = pf.next_batch()
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, np.asarray(y))
            states.append(pf.state())
        # Resume from the state after batch 5: restore a FRESH source
        # first, then wrap — the stream must continue at batch 6.
        resumed = Batches(ids, vals, labels, batch_size=32, seed=7)
        resumed.restore(states[4])
    with Prefetcher(resumed, depth=3) as pf2:
        ref2 = Batches(ids, vals, labels, batch_size=32, seed=7)
        ref2.restore(states[4])
        for _ in range(5):
            a = ref2.next_batch()
            b = pf2.next_batch()
            for x, y in zip(a, b):
                np.testing.assert_array_equal(x, np.asarray(y))


def test_prefetcher_propagates_producer_error():
    import pytest

    from fm_spark_tpu.data import Prefetcher

    class Boom:
        def __init__(self):
            self.n = 0

        def next_batch(self):
            self.n += 1
            if self.n > 2:
                raise RuntimeError("producer crashed")
            return (np.zeros(3),)

        def state(self):
            return {"n": self.n}

    with Prefetcher(Boom(), depth=1) as pf:
        pf.next_batch()
        pf.next_batch()
        with pytest.raises(RuntimeError, match="producer crashed"):
            pf.next_batch()


def test_prefetcher_finite_source_stop_iteration():
    import pytest

    from fm_spark_tpu.data import Prefetcher

    class Finite:
        def __init__(self):
            self.n = 0

        def next_batch(self):
            if self.n >= 3:
                raise StopIteration
            self.n += 1
            return (np.full(2, self.n),)

    with Prefetcher(Finite(), depth=2) as pf:
        got = [int(pf.next_batch()[0][0]) for _ in range(3)]
        assert got == [1, 2, 3]
        with pytest.raises(StopIteration):
            pf.next_batch()
        # Exhausted iterators must KEEP raising (not deadlock on the
        # empty queue of a dead producer).
        with pytest.raises(StopIteration):
            pf.next_batch()


def test_prefetcher_close_unblocks_producer():
    from fm_spark_tpu.data import Prefetcher

    ids, vals, labels = _data(n=200)
    src = Batches(ids, vals, labels, batch_size=16, seed=0)
    pf = Prefetcher(src, depth=1)  # tiny queue → producer blocks on put
    pf.next_batch()
    pf.close()  # must not hang
    assert not pf._thread.is_alive()
    # Use-after-close errors instead of deadlocking on the dead queue.
    import pytest

    with pytest.raises(RuntimeError, match="closed"):
        pf.next_batch()


def test_prefetcher_close_is_idempotent():
    import pytest

    from fm_spark_tpu.data import Prefetcher

    ids, vals, labels = _data(n=100)
    pf = Prefetcher(Batches(ids, vals, labels, batch_size=16, seed=0),
                    depth=1)
    pf.next_batch()
    pf.close()
    pf.close()  # second close: no hang, no error, thread stays down
    assert not pf._thread.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        pf.next_batch()


def test_prefetcher_producer_error_keeps_reraising_without_blocking():
    """A producer crash must re-raise on EVERY subsequent next_batch()
    — the terminal sentinel is enqueued exactly once, so a second call
    that blocked on the dead queue would hang the training loop."""
    import pytest

    from fm_spark_tpu.data import Prefetcher

    class Boom:
        def __init__(self):
            self.n = 0

        def next_batch(self):
            self.n += 1
            if self.n > 1:
                raise RuntimeError("producer crashed")
            return (np.zeros(3),)

        def state(self):
            return {"n": self.n}

    with Prefetcher(Boom(), depth=1) as pf:
        pf.next_batch()
        for _ in range(3):
            with pytest.raises(RuntimeError, match="producer crashed"):
                pf.next_batch()


def test_prefetcher_restore_after_start_raises_documented_error():
    import pytest

    from fm_spark_tpu.data import Prefetcher

    ids, vals, labels = _data(n=64)
    src = Batches(ids, vals, labels, batch_size=16, seed=3)
    with Prefetcher(src, depth=2) as pf:
        with pytest.raises(RuntimeError, match="BEFORE constructing"):
            pf.restore({"epoch": 0, "index": 0, "seed": 3})


# ------------------------------------------------------- BernoulliBatches


def test_bernoulli_batches_reference_sampling_semantics():
    from fm_spark_tpu.data import BernoulliBatches

    ids, vals, labels = _data(n=4000)
    p = 0.25
    b = BernoulliBatches(ids, vals, labels, p, seed=5)
    masks = []
    for _ in range(6):
        bi, bv, bl, w = b.next_batch()
        # Full fixed shape every step; arrays untouched, only the mask
        # varies.
        assert bi.shape == ids.shape and w.shape == (4000,)
        assert set(np.unique(w)) <= {0.0, 1.0}
        masks.append(w)
    # Fresh independent Bernoulli draw each iteration (reference
    # data.sample(false, frac, seed+i)): masks differ, each ~ p·N.
    for i in range(5):
        assert not np.array_equal(masks[i], masks[i + 1])
        assert abs(masks[i].sum() / 4000 - p) < 0.05
    # Deterministic per (seed, step) and exactly resumable.
    b2 = BernoulliBatches(ids, vals, labels, p, seed=5)
    b2.restore({"step": 3, "seed": 5, "fraction": p})
    np.testing.assert_array_equal(b2.next_batch()[3], masks[3])
    # Different seed → different stream.
    b3 = BernoulliBatches(ids, vals, labels, p, seed=6)
    assert not np.array_equal(b3.next_batch()[3], masks[0])


def test_bernoulli_batches_validation():
    import pytest

    from fm_spark_tpu.data import BernoulliBatches

    ids, vals, labels = _data(n=10)
    with pytest.raises(ValueError, match="fraction"):
        BernoulliBatches(ids, vals, labels, 0.0)
    with pytest.raises(ValueError, match="fraction"):
        BernoulliBatches(ids, vals, labels, 1.5)
    b = BernoulliBatches(ids, vals, labels, 0.5, seed=1)
    with pytest.raises(ValueError, match="different seed"):
        b.restore({"step": 0, "seed": 9, "fraction": 0.5})
