"""Perf-provenance ledger contracts (ISSUE 9): append-only JSONL with
required provenance fields, torn-tail-tolerant reads, and fingerprint
cohort keys that split exactly on the comparability-defining fields
(and NOT on attachment weather)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fm_spark_tpu.obs.ledger import (  # noqa: E402
    PerfLedger,
    default_ledger_path,
    fingerprint_key,
    measurement_fingerprint,
)


def _fp(**kw):
    base = dict(variant="v1", model="fm", batch=1024, steps=20,
                device_kind="TPU v5 lite", n_chips=1,
                jax_version="0.9.9", libtpu_version="tpu-x")
    base.update(kw)
    return measurement_fingerprint(**base)


def _rec(value=1.0, leg="legA", run_id="r1", **kw):
    return {"kind": "bench_leg", "leg": leg, "run_id": run_id,
            "value": value, "fingerprint": kw.pop("fp", None) or _fp(),
            **kw}


def test_append_and_read_roundtrip(tmp_path):
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    r1 = led.append(_rec(value=100.0))
    r2 = led.append(_rec(value=200.0))
    assert r1["ts"] and r2["ts"]
    got = led.records()
    assert [r["value"] for r in got] == [100.0, 200.0]
    # Append order IS history order.
    assert got[0]["ts"] <= got[1]["ts"]


def test_append_refuses_unattributable_records(tmp_path):
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    for missing in ("kind", "leg", "run_id", "fingerprint"):
        rec = _rec()
        del rec[missing]
        with pytest.raises(ValueError, match=missing):
            led.append(rec)
    # A fingerprint without its cohort key is just as unattributable.
    rec = _rec()
    rec["fingerprint"] = {"variant": "v1"}
    with pytest.raises(ValueError, match="key"):
        led.append(rec)
    assert led.records() == []  # nothing half-written


def test_records_skips_torn_and_junk_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    led = PerfLedger(str(path))
    led.append(_rec(value=1.0))
    with open(path, "a") as f:
        f.write('{"torn": \n')
        f.write("[1, 2, 3]\n")  # parseable but not a dict
    led.append(_rec(value=2.0))
    assert [r["value"] for r in led.records()] == [1.0, 2.0]


def test_records_filters(tmp_path):
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    fp_a, fp_b = _fp(variant="a"), _fp(variant="b")
    led.append(_rec(value=1.0, leg="legA", run_id="r1", fp=fp_a))
    led.append(_rec(value=2.0, leg="legA", run_id="r2", fp=fp_b))
    led.append(_rec(value=3.0, leg="legB", run_id="r1", fp=fp_a))
    assert len(led.records(leg="legA")) == 2
    assert len(led.records(run_id="r1")) == 2
    assert len(led.records(kind="bench_leg")) == 3
    assert len(led.records(kind="attachment_probe")) == 0
    assert [r["value"] for r in led.cohort("legA", fp_a["key"])] == [1.0]


def test_missing_file_is_empty_history(tmp_path):
    assert PerfLedger(str(tmp_path / "nope.jsonl")).records() == []


def test_fingerprint_key_splits_on_comparability_fields():
    base = _fp()
    # Same inputs -> same key (stable across processes by construction).
    assert _fp()["key"] == base["key"]
    # Each comparability-defining field forks the cohort...
    assert _fp(variant="other")["key"] != base["key"]
    assert _fp(batch=2048)["key"] != base["key"]
    assert _fp(device_kind="cpu")["key"] != base["key"]
    assert _fp(n_chips=8)["key"] != base["key"]
    assert _fp(jax_version="0.9.8")["key"] != base["key"]
    assert _fp(degraded=True)["key"] != base["key"]
    assert _fp(fused_fallback=True)["key"] != base["key"]
    # ...but attachment WEATHER does not: a flaky-day measurement must
    # stay comparable with its healthy-day cohort (weather is evidence
    # for the sentinel, not a cohort splitter).
    assert _fp(attachment_health="down")["key"] == base["key"]


def test_fingerprint_key_matches_module_helper():
    fp = _fp()
    assert fingerprint_key(fp) == fp["key"]


def test_default_ledger_path_is_the_cross_run_convention(tmp_path):
    assert default_ledger_path(str(tmp_path)) == str(
        tmp_path / "obs" / "ledger.jsonl")
    # Repo default: beside the per-run obs dirs.
    assert default_ledger_path().endswith(
        os.path.join("artifacts", "obs", "ledger.jsonl"))


def test_append_creates_parent_dirs(tmp_path):
    led = PerfLedger(str(tmp_path / "a" / "b" / "ledger.jsonl"))
    led.append(_rec())
    assert len(led.records()) == 1


def test_ledger_record_json_serializable(tmp_path):
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    rec = led.append(_rec(value=None, error="rc=3"))
    line = json.loads(open(led.path).read())
    assert line["value"] is None and line["error"] == "rc=3"
    assert rec["fingerprint"]["key"] == line["fingerprint"]["key"]


def test_chaos_fingerprint_splits_cohort_only_when_set():
    """ISSUE 10 satellite: chaos-drill legs form their OWN cohort (a
    run under injected faults is a different program), but the flag is
    folded into the key asymmetrically so every historical (pre-chaos)
    cohort key stays byte-stable."""
    real = _fp()
    drill = _fp(chaos=True)
    assert drill["chaos"] is True and real["chaos"] is False
    assert drill["key"] != real["key"]
    # Key stability: a fingerprint dict with no chaos field at all (a
    # pre-ISSUE-10 ledger row) keys identically to chaos=False.
    legacy = dict(real)
    del legacy["chaos"]
    assert fingerprint_key(legacy) == real["key"]


def test_quality_eval_kind_is_cohort_isolated(tmp_path):
    """ISSUE 13 satellite: quality_eval records (the online loop's
    day-over-day AUC) live in their own leg namespace AND kind — a
    kind/leg query for bench or serving cohorts never sees them, and
    vice versa, so an AUC series can never pollute a throughput
    trailing band."""
    led = PerfLedger(str(tmp_path / "ledger.jsonl"))
    qfp = _fp(variant="quality/demo/ftrl")
    led.append({"kind": "quality_eval", "leg": "quality/demo/ftrl",
                "run_id": "r1", "value": 0.71, "day": 1,
                "fingerprint": qfp})
    led.append(_rec(value=1_000_000.0))              # bench_leg, legA
    led.append({"kind": "serve_bench", "leg": "serve_b64",
                "run_id": "r1", "value": 9000.0, "fingerprint": _fp()})
    assert [r["value"] for r in led.records(kind="quality_eval")] \
        == [0.71]
    assert all(r["kind"] == "bench_leg"
               for r in led.records(kind="bench_leg"))
    assert led.records(leg="quality/demo/ftrl", kind="bench_leg") == []
    # The cohort unit (leg, fingerprint key) holds for quality rows.
    assert [r["day"] for r in led.cohort("quality/demo/ftrl",
                                         qfp["key"])] == [1]
