"""End-to-end fault-injected bench scenarios on the CPU backend (ISSUE 2
acceptance): a sweep that suffers an injected INIT HANG (child 1, caught
by the watchdog → rc=3 → parent retry) and then a MID-SWEEP DEVICE LOSS
(child 2, leg 2 — retried by the per-leg supervisor) still exits 0 with
a non-null parseable artifact and a health journal recording every
transition; a ``--resume-sweep`` restart then runs ONLY the remaining
legs. The all-attempts-dead path is covered too: the error JSON must
transport the best-known headline via its ``last_measured`` block.

Model ``fm_kaggle`` at batch 128 is the cheapest registered sweep (same
choice as tests/test_bench_fast_first.py); the two sweeps share one
compile cache so the resume restart is a warm re-entry — exactly the
production pairing the flag was built for.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run_bench(args, env, timeout):
    return subprocess.run(
        [sys.executable, BENCH] + args,
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        # Designed sleeps (parent/supervisor backoffs) shrink 4x by
        # default here — every assertion below is about BEHAVIOR
        # (events journaled, retries counted, verdicts classified),
        # never about how long a backoff waited. Deadlines, watchdog
        # windows, and measured durations are NOT scaled
        # (fm_spark_tpu/utils/sleeps.py). Override the env to rehearse
        # production timing.
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "FM_SPARK_TEST_SLEEP_SCALE": os.environ.get(
                 "FM_SPARK_TEST_SLEEP_SCALE", "0.25"),
             **env},
    )


def _last_json(stdout):
    lines = [ln for ln in stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON line on stdout:\n{stdout[-2000:]}"
    return json.loads(lines[-1])


def _health_events(art, model):
    """The run's health journal, merged across attempts. Since ISSUE 7
    the journal lives under the per-run telemetry convention
    (<artifacts>/obs/<run_id>/health_<model>.jsonl); the old flat path
    is still read for back-compat."""
    paths = sorted((art / "obs").glob(f"*/health_{model}.jsonl"),
                   key=lambda p: p.stat().st_mtime)
    old = art / f"health_{model}.jsonl"
    if old.exists():
        paths.insert(0, old)
    assert paths, f"no health journal for {model} under {art}"
    events = []
    for p in paths:
        with open(p) as f:
            events.extend(json.loads(ln) for ln in f if ln.strip())
    return events


def test_sweep_survives_init_hang_then_device_loss_and_resumes(tmp_path):
    art = tmp_path / "art"
    cc = str(tmp_path / "cc")
    common = ["--fast-first", "--model", "fm_kaggle",
              "--batch", "128", "--steps", "2",
              "--compile-cache", cc, "--artifacts-dir", str(art)]

    # Phase 1: child 1's backend init hangs (watchdog exits it rc=3),
    # the parent retries, child 2 loses the device on sweep leg 2 and
    # the supervisor retries the leg. The run must still exit 0 with a
    # complete, parseable sweep.
    proc = _run_bench(
        common + ["--attempts", "2", "--attempt-timeout", "300",
                  "--total-deadline", "420", "--init-timeout", "8"],
        env={
            "FM_SPARK_FAULTS":
                "backend_init@1=hang:120;sweep_leg@2=device_loss",
            "FM_SPARK_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        },
        timeout=460,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    final = _last_json(proc.stdout)
    assert final["value"] is not None and final["value"] > 0
    assert final.get("error") is None
    assert final["legs_completed"] >= 2

    # The watchdog-killed child printed its provisional error line, and
    # that line already transported the best-known headline.
    first = json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.startswith("{")][0])
    if first.get("error"):
        assert first["last_measured"]["value"] > 0
        assert first["last_measured"]["stale"] is True

    # ISSUE 7 acceptance: the result JSON carries the run's telemetry
    # block — step-time percentiles across the completed legs (the
    # fast-first leg included), the ingest-rate field, and the fault
    # timeline with the injected device loss the supervisor retried.
    assert final["run_id"]
    tel = final["telemetry"]
    assert tel["run_id"] == final["run_id"]
    st = tel["step_time_ms"]
    assert st["count"] >= final["legs_completed"]
    assert all(st[p] is not None and st[p] > 0
               for p in ("p50", "p95", "p99"))
    assert "ingest_rows_per_sec" in tel
    assert "device_memory" in tel
    kinds = [e["kind"] for e in tel["fault_events"]]
    assert "failure" in kinds and "backoff" in kinds

    # ISSUE 9 acceptance: the result JSON carries the promoted leg's
    # sentinel verdict block plus a verdict per completed leg, and the
    # per-run ledger recorded every leg with the injected-device-loss
    # weather on the retried one.
    assert final["sentinel"]["verdict"] in (
        "improved", "flat", "regressed", "attachment_transient",
        "insufficient_history")
    assert set(final["all_verdicts"]) == set(final["all_variants"])
    ledger_path = art / "obs" / "ledger.jsonl"
    assert ledger_path.exists()
    rows = [json.loads(ln) for ln in
            ledger_path.read_text().splitlines()]
    rows = [r for r in rows if r.get("run_id") == final["run_id"]]
    legs = [r for r in rows if r.get("kind") == "bench_leg"]
    assert len(legs) == final["legs_completed"]
    # ISSUE 14: every completed leg ALSO landed one cost_attribution
    # record (measured step time x bytes-moved model).
    cost = [r for r in rows if r.get("kind") == "cost_attribution"]
    assert len(cost) == final["legs_completed"]
    # Leg 2 survived a retried device loss: its fingerprint records the
    # weather; the other legs were clean.
    healths = [r["fingerprint"]["attachment_health"] for r in legs]
    assert "flaky" in healths and "healthy" in healths

    # ...and obs_report renders a report straight from this run's obs
    # dir: per-leg phase rows, the step-time percentile table, and the
    # retry narrative, all from one directory.
    run_dir = art / "obs" / final["run_id"]
    assert run_dir.is_dir()
    report = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         str(run_dir)],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert report.returncode == 0, report.stderr[-2000:]
    assert final["run_id"] in report.stdout
    assert "bench/leg" in report.stdout
    assert "step_time_ms" in report.stdout
    assert "failure" in report.stdout and "backoff" in report.stdout

    # Health journal: init timeout on child 1; child 2 came up, lost the
    # device on a leg, probed, backed off, and retried. Both attempts
    # share the parent-minted run id, so ONE journal holds the story.
    events = _health_events(art, "fm_kaggle")
    names = [e["event"] for e in events]
    assert "backend_init_timeout" in names
    assert "backend_init_up" in names
    assert "failure" in names and "backoff" in names
    fail = next(e for e in events if e["event"] == "failure")
    assert "InjectedDeviceLoss" in fail["error"]
    assert fail["retryable"] is True

    # Phase 2: --resume-sweep restart with a truncated artifact (as if
    # the window died after leg 1) runs ONLY the remaining legs, warm
    # through the shared compile cache.
    sweep_path = art / "sweep_fm_kaggle.jsonl"
    records = sweep_path.read_text().strip().splitlines()
    n_total = len(records)
    assert n_total >= 2
    sweep_path.write_text(records[0] + "\n")
    kept = json.loads(records[0])

    proc2 = _run_bench(
        common + ["--resume-sweep", "--attempts", "1",
                  "--attempt-timeout", "240", "--total-deadline", "300"],
        env={}, timeout=330,
    )
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    final2 = _last_json(proc2.stdout)
    assert final2["value"] is not None
    assert final2["resumed_legs"] == 1
    assert final2["legs_completed"] == n_total
    assert kept["variant"] in final2["all_variants"]
    # The banked leg's sentinel verdict rides the resume (reloaded from
    # its sweep record, never re-judged against its own history).
    assert final2["all_verdicts"][kept["variant"]] == kept["verdict"]
    # Only the remaining legs were re-measured and appended.
    new_records = [json.loads(ln) for ln in
                   sweep_path.read_text().strip().splitlines()]
    assert len(new_records) == n_total
    assert [r["variant"] for r in new_records].count(kept["variant"]) == 1


def test_error_artifact_carries_last_measured(tmp_path):
    """A round where EVERY attempt dies before measuring still emits a
    machine-readable best-known headline (the satellite: VERDICT r5
    next-round #1 — a dead-attachment round must degrade, not null)."""
    proc = _run_bench(
        ["--attempts", "2", "--attempt-timeout", "60",
         "--total-deadline", "110", "--artifacts-dir",
         str(tmp_path / "art")],
        env={
            "FM_SPARK_FAULTS":
                "backend_init@1=exit:3;backend_init@2=exit:3",
            "FM_SPARK_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        },
        timeout=150,
    )
    assert proc.returncode == 1
    final = _last_json(proc.stdout)
    assert final["value"] is None
    assert "rc=3" in final["error"]
    last = final["last_measured"]
    # The carried record is MEASURED.json's headline, provenance intact.
    assert last["value"] > 0 and last["stale"] is True
    assert last["variant"] and last["date"]
    assert "MEASURED.json" in last["provenance"]


def test_resume_sweep_never_loads_degraded_leg_records(tmp_path):
    """A degraded (shrunk-denominator) leg record must not ride
    --resume-sweep into a fresh, undegraded payload: its inflated
    per-chip rate would win max() without the degraded stamp and slip
    past the MEASURED.json keep-best guard. Degraded legs re-measure."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    art = tmp_path / "art"
    art.mkdir()
    records = [
        {"variant": "a", "value": 100.0, "device": "cpu", "ts": 5.0,
         "dt_s": 1.0, "loss": 0.5},
        {"variant": "b", "value": 400.0, "device": "cpu", "ts": 6.0,
         "dt_s": 1.0, "loss": 0.5, "degraded": True, "chips": 2},
    ]
    with open(art / "sweep_fm.jsonl", "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    out = bench._completed_legs(str(art), "fm", {"a", "b"},
                                device_kind="cpu")
    assert set(out) == {"a"}  # the degraded leg is re-measured


def test_parent_classifies_permanent_and_stops_early(tmp_path):
    """ISSUE 4 satellite: identical consecutive child failures (the
    BENCH_r05 rc=3 run) classify PERMANENT — the parent stops burning
    its deadline on further attempts/backoffs and the error JSON
    surfaces ``permanent: true``."""
    proc = _run_bench(
        ["--attempts", "5", "--attempt-timeout", "60",
         "--total-deadline", "240",
         "--artifacts-dir", str(tmp_path / "art")],
        env={
            "FM_SPARK_FAULTS": ";".join(
                f"backend_init@{i}=exit:3" for i in range(1, 6)),
            "FM_SPARK_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        },
        timeout=280,
    )
    assert proc.returncode == 1
    final = _last_json(proc.stdout)
    assert final["value"] is None
    assert final["permanent"] is True
    # Stopped at the classification threshold (3 identical), not the
    # attempt budget (5): attempts 4 and 5 never ran.
    assert "classified permanent after 3" in final["error"]
    assert "attempt 4" not in final["error"]
    assert "skipping backoff" in proc.stderr  # 2-identical probe fast path


def test_elastic_degraded_sweep_completes_on_shrunk_mesh(tmp_path):
    """ISSUE 4 acceptance: an injected PERMANENT device loss (three
    identical consecutive failures on the leg) with ``--elastic`` on a
    forced 8-device CPU host completes the measurement on a shrunk mesh
    and emits a valid result JSON with ``degraded: true`` and per-chip
    throughput re-normalized to the 4 survivors — instead of an
    error-only artifact."""
    art = tmp_path / "art"
    proc = _run_bench(
        ["--model", "fm_kaggle", "--batch", "128", "--steps", "2",
         "--elastic", "--max-shrinks", "2",
         "--attempts", "1", "--attempt-timeout", "300",
         "--total-deadline", "420", "--artifacts-dir", str(art)],
        env={
            "FM_SPARK_FAULTS":
                "sweep_leg@1=device_loss;sweep_leg@2=device_loss;"
                "sweep_leg@3=device_loss",
            "FM_SPARK_FAULTS_STATE": str(tmp_path / "faults_state.json"),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
        timeout=460,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    final = _last_json(proc.stdout)
    assert final["value"] is not None and final["value"] > 0
    assert final.get("error") is None
    assert final["degraded"] is True
    assert final["chips"] == 4 and final["shrinks"] == 1

    # The health journal narrates the whole degradation: the three
    # identical failures, the shrink 8 -> 4, and the re-armed breaker.
    events = _health_events(art, "fm_kaggle")
    names = [e["event"] for e in events]
    assert names.count("failure") == 3
    assert "supervisor_reset" in names
    shrink = next(e for e in events if e["event"] == "mesh_shrink")
    assert shrink["from_chips"] == 8 and shrink["to_chips"] == 4

    # The per-leg sweep record carries the degraded provenance, and the
    # rate is normalized per SURVIVING chip.
    with open(art / "sweep_fm_kaggle.jsonl") as f:
        rec = json.loads(f.readline())
    assert rec["degraded"] is True and rec["chips"] == 4
    # value == steps*batch/dt/4 survivors. dt_s is persisted rounded to
    # 3 decimals and a warm CPU leg can run in single-digit ms, so the
    # bound is loose — it only needs to rule out the WRONG denominator
    # (a /8 normalization would miss by a factor of 2).
    assert abs(rec["value"] * 4 * rec["dt_s"] / (2 * 128) - 1) < 0.25


def test_retried_leg_never_double_appends_ledger_record(tmp_path):
    """ISSUE 9 crash window: an attempt can die AFTER the sentinel
    appended a leg's ledger record but BEFORE _persist_incremental
    banked it — the retried (--resume-sweep, like every parent
    respawn) attempt then re-measures the leg. The re-measured rate
    must be judged WITHOUT appending a duplicate (run_id, variant)
    row it would then be judged against."""
    from fm_spark_tpu.obs import ledger as lg

    art = tmp_path / "art"
    run_id = "20260801-000000-ptest"
    label = "float32/scatter_add/cd-bf16/b128"
    metric = "kaggle_fm_rank32_1Mfeat_samples_per_sec_per_chip"
    led = lg.PerfLedger(str(art / "obs" / "ledger.jsonl"))
    led.append({
        "kind": "bench_leg", "leg": metric, "run_id": run_id,
        "variant": label, "value": 31000.0, "unit": "samples/sec/chip",
        "sentinel": {"verdict": "insufficient_history",
                     "reason": "aborted-attempt record",
                     "n_history": 0, "median": None, "mad": None,
                     "z": None, "cohort": "exact"},
        "fingerprint": lg.measurement_fingerprint(
            variant=label, model="fm_kaggle", batch=128, steps=2),
    })
    proc = _run_bench(
        ["--fast-first", "--model", "fm_kaggle", "--batch", "128",
         "--steps", "2", "--attempts", "1", "--attempt-timeout", "300",
         "--total-deadline", "380", "--artifacts-dir", str(art),
         "--run-id", run_id, "--resume-sweep"],
        env={}, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    final = _last_json(proc.stdout)
    rows = [json.loads(ln) for ln in
            (art / "obs" / "ledger.jsonl").read_text().splitlines()]
    mine = [r for r in rows if r.get("run_id") == run_id
            and r.get("variant") == label
            and r.get("kind") == "bench_leg"]
    assert len(mine) == 1, "duplicate (run_id, variant) ledger record"
    # The cost_attribution append rides the same dedup (ISSUE 14): a
    # resumed leg never lands a second cost record either.
    cost_mine = [r for r in rows if r.get("run_id") == run_id
                 and r.get("variant") == label
                 and r.get("kind") == "cost_attribution"]
    assert len(cost_mine) <= 1, "duplicate cost_attribution record"
    # The re-measured rate was judged fresh (against a history of just
    # the aborted attempt's row — insufficient) without re-appending.
    assert final["all_verdicts"][label] == "insufficient_history"
    # The OTHER legs were measured fresh and appended normally.
    others = [r for r in rows if r.get("run_id") == run_id
              and r.get("variant") != label
              and r.get("kind") == "bench_leg"]
    assert len(others) == final["legs_completed"] - 1


@pytest.mark.slow
def test_sigterm_mid_sweep_salvages_with_faults_active(tmp_path):
    """The SIGTERM fault injection composes with the salvage path: the
    `sigterm` action fired from INSIDE the child mid-sweep must still
    leave the parent's salvaged result line and an exit 0 (the
    fast-first SIGTERM contract, driven deterministically by the fault
    layer instead of an external kill)."""
    art = tmp_path / "art"
    proc = _run_bench(
        ["--fast-first", "--model", "fm_kaggle", "--batch", "128",
         "--steps", "2", "--compile-cache", str(tmp_path / "cc"),
         "--artifacts-dir", str(art),
         "--attempts", "1", "--attempt-timeout", "300",
         "--total-deadline", "400"],
        env={
            # Kill the PARENT (the process group leader of the pipeline
            # the driver would kill) after the child's 2nd leg starts;
            # the child's own stdout already carried leg 1's line.
            "FM_SPARK_FAULTS": "sweep_leg@2=sigterm",
            "FM_SPARK_FAULTS_STATE": str(tmp_path / "faults_state.json"),
        },
        timeout=430,
    )
    # The sigterm lands in the CHILD process (the injection point runs
    # there), which dies without a further result line; the parent sees
    # a child death after leg 1 completed and salvages it.
    final = _last_json(proc.stdout)
    assert final["value"] is not None and final["value"] > 0
    assert (art / "keepbest_fm_kaggle.json").exists()
