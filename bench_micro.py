"""Microbenchmarks behind PERF.md's measured facts 1-5.

Each subcommand reproduces one design-driving measurement so the
architecture rationale stays checkable on any attachment:

  dispatch   fact 1: per-dispatch host/tunnel overhead (trivial scalar
             add, timed per call) and the fori_loop amortization.
  gather     fact 2: per-index gather rate vs table BYTES (the ~34MB
             cliff that motivates per-field sub-tables).
  scatter    fact 3: scatter-add rate vs operand size (the ~128MB cliff
             and per-index bound that motivate single-owner sub-tables).
  matmul     fact 4: MXU peak check (compute is not the binding
             constraint).
  cast       fact 5: dense streaming bandwidth (why per-step shadow
             recasts are off the table).
  all        run everything.

Prints one JSON line per measurement: {"bench": ..., "config": ...,
"value": ..., "unit": ...}. Timing uses a device->host transfer as the
completion fence (block_until_ready returns early on this attachment,
PERF.md timing note).
"""

import argparse
import json
import sys
import time


def _log(msg):
    print(f"bench_micro: {msg}", file=sys.stderr, flush=True)


def _out(bench, config, value, unit):
    print(json.dumps({"bench": bench, "config": config,
                      "value": round(value, 3), "unit": unit}), flush=True)


def _fence(x):
    """Reliable completion fence: device->host transfer of one scalar."""
    import jax.numpy as jnp

    return float(jnp.ravel(x)[0])


def bench_dispatch(args):
    import jax
    import jax.numpy as jnp
    from jax import lax

    one = jnp.float32(1.0)

    @jax.jit
    def add(x):
        return x + 1.0

    @jax.jit
    def add_n(x, n):
        return lax.fori_loop(0, n, lambda i, c: c + 1.0, x)

    _fence(add(one))           # compile
    _fence(add_n(one, jnp.int32(2)))
    t0 = time.perf_counter()
    x = one
    for _ in range(args.calls):
        x = add(x)
    _fence(x)
    per_call = (time.perf_counter() - t0) / args.calls
    _out("dispatch", {"calls": args.calls}, per_call * 1e3,
         "ms/dispatch")

    t0 = time.perf_counter()
    _fence(add_n(one, jnp.int32(args.calls)))
    per_iter = (time.perf_counter() - t0) / args.calls
    _out("dispatch_fori", {"iters": args.calls}, per_iter * 1e6,
         "us/iter (same adds inside one fori_loop program)")


def _gather_once(rows, width, dtype, n_idx, seed=0):
    import jax
    import jax.numpy as jnp
    import numpy as np

    table = jnp.zeros((rows, width), dtype)
    ids = jnp.asarray(
        np.random.default_rng(seed).integers(0, rows, n_idx), jnp.int32
    )

    @jax.jit
    def g(t, i):
        return jnp.sum(t[i].astype(jnp.float32))

    _fence(g(table, ids))  # compile
    t0 = time.perf_counter()
    _fence(g(table, ids))
    return time.perf_counter() - t0


def bench_gather(args):
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "bfloat16"),
                        (1 << 18, "float32"), (1 << 19, "float32"),
                        (1 << 20, "float32")]:
        dt = _gather_once(rows, args.width, dtype, args.n_idx)
        tbl_mb = rows * args.width * (2 if dtype == "bfloat16" else 4) / 2**20
        _out("gather", {"rows": rows, "width": args.width, "dtype": dtype,
                        "table_mb": round(tbl_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_scatter(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "float32"),
                        (1 << 19, "float32"), (1 << 20, "float32")]:
        table = jnp.zeros((rows, args.width), dtype)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, rows, args.n_idx),
            jnp.int32,
        )
        upd = jnp.ones((args.n_idx, args.width), dtype)

        @jax.jit
        def sc(t, i, u):
            return t.at[i].add(u, mode="drop")

        _fence(sc(table, ids, upd))  # compile
        t0 = time.perf_counter()
        _fence(sc(table, ids, upd))
        dt = time.perf_counter() - t0
        op_mb = rows * args.width * 4 / 2**20
        _out("scatter", {"rows": rows, "width": args.width, "dtype": dtype,
                         "operand_mb": round(op_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_matmul(args):
    import jax
    import jax.numpy as jnp

    n = args.size
    a = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(x):
        return x @ x

    _fence(mm(a))  # compile
    t0 = time.perf_counter()
    _fence(mm(a))
    dt = time.perf_counter() - t0
    _out("matmul", {"size": n, "dtype": "bfloat16"},
         2 * n**3 / dt / 1e12, "TFLOP/s")


def bench_cast(args):
    import jax
    import jax.numpy as jnp

    tables = [jnp.ones((args.rows, args.width), jnp.float32)
              for _ in range(args.tables)]
    total_gb = args.tables * args.rows * args.width * 4 / 2**30

    @jax.jit
    def cast_all(ts):
        return [t.astype(jnp.bfloat16) for t in ts]

    _fence(cast_all(tables)[0])  # compile
    t0 = time.perf_counter()
    _fence(cast_all(tables)[0])
    dt = time.perf_counter() - t0
    _out("cast", {"tables": args.tables, "rows": args.rows,
                  "width": args.width, "read_gb": round(total_gb, 2)},
         total_gb / dt, "GB/s (fp32 read side)")


BENCHES = {
    "dispatch": bench_dispatch,
    "gather": bench_gather,
    "scatter": bench_scatter,
    "matmul": bench_matmul,
    "cast": bench_cast,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("bench", choices=[*BENCHES, "all"])
    ap.add_argument("--calls", type=int, default=30)
    ap.add_argument("--n-idx", type=int, default=5_242_880,
                    help="gather/scatter index count (~B*F at the "
                    "headline batch)")
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--tables", type=int, default=39)
    ap.add_argument("--size", type=int, default=8192)
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    _log(f"device: {jax.devices()[0].device_kind}")
    for name in (BENCHES if args.bench == "all" else [args.bench]):
        _log(f"running {name}...")
        BENCHES[name](args)


if __name__ == "__main__":
    main()
