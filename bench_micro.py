"""Microbenchmarks behind PERF.md's measured facts 1-5.

Each subcommand reproduces one design-driving measurement so the
architecture rationale stays checkable on any attachment:

  dispatch   fact 1: per-dispatch host/tunnel overhead (trivial scalar
             add, timed per call) and the fori_loop amortization.
  gather     fact 2: per-index gather rate vs table BYTES (the ~34MB
             cliff that motivates per-field sub-tables).
  scatter    fact 3: scatter-add rate vs operand size (the ~128MB cliff
             and per-index bound that motivate single-owner sub-tables).
  matmul     fact 4: MXU peak check (compute is not the binding
             constraint).
  cast       fact 5: dense streaming bandwidth (why per-step shadow
             recasts are off the table).
  all        run everything.

Prints one JSON line per measurement: {"bench": ..., "config": ...,
"value": ..., "unit": ...}. Timing uses a device->host transfer as the
completion fence (block_until_ready returns early on this attachment,
PERF.md timing note).
"""

import argparse
import json
import sys
import time


def _log(msg):
    print(f"bench_micro: {msg}", file=sys.stderr, flush=True)


def _out(bench, config, value, unit):
    print(json.dumps({"bench": bench, "config": config,
                      "value": round(value, 3), "unit": unit}), flush=True)


def _fence(x):
    """Reliable completion fence: device->host transfer of one scalar."""
    import jax.numpy as jnp

    return float(jnp.ravel(x)[0])


def bench_dispatch(args):
    import jax
    import jax.numpy as jnp
    from jax import lax

    one = jnp.float32(1.0)

    @jax.jit
    def add(x):
        return x + 1.0

    @jax.jit
    def add_n(x, n):
        return lax.fori_loop(0, n, lambda i, c: c + 1.0, x)

    _fence(add(one))           # compile
    _fence(add_n(one, jnp.int32(2)))
    t0 = time.perf_counter()
    x = one
    for _ in range(args.calls):
        x = add(x)
    _fence(x)
    per_call = (time.perf_counter() - t0) / args.calls
    _out("dispatch", {"calls": args.calls}, per_call * 1e3,
         "ms/dispatch")

    t0 = time.perf_counter()
    _fence(add_n(one, jnp.int32(args.calls)))
    per_iter = (time.perf_counter() - t0) / args.calls
    _out("dispatch_fori", {"iters": args.calls}, per_iter * 1e6,
         "us/iter (same adds inside one fori_loop program)")


def _gather_once(rows, width, dtype, n_idx, seed=0):
    import jax
    import jax.numpy as jnp
    import numpy as np

    table = jnp.zeros((rows, width), dtype)
    ids = jnp.asarray(
        np.random.default_rng(seed).integers(0, rows, n_idx), jnp.int32
    )

    @jax.jit
    def g(t, i):
        return jnp.sum(t[i].astype(jnp.float32))

    _fence(g(table, ids))  # compile
    t0 = time.perf_counter()
    _fence(g(table, ids))
    return time.perf_counter() - t0


def bench_gather(args):
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "bfloat16"),
                        (1 << 18, "float32"), (1 << 19, "float32"),
                        (1 << 20, "float32")]:
        dt = _gather_once(rows, args.width, dtype, args.n_idx)
        tbl_mb = rows * args.width * (2 if dtype == "bfloat16" else 4) / 2**20
        _out("gather", {"rows": rows, "width": args.width, "dtype": dtype,
                        "table_mb": round(tbl_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_scatter(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "float32"),
                        (1 << 19, "float32"), (1 << 20, "float32")]:
        table = jnp.zeros((rows, args.width), dtype)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, rows, args.n_idx),
            jnp.int32,
        )
        upd = jnp.ones((args.n_idx, args.width), dtype)

        @jax.jit
        def sc(t, i, u):
            return t.at[i].add(u, mode="drop")

        _fence(sc(table, ids, upd))  # compile
        t0 = time.perf_counter()
        _fence(sc(table, ids, upd))
        dt = time.perf_counter() - t0
        op_mb = rows * args.width * 4 / 2**20
        _out("scatter", {"rows": rows, "width": args.width, "dtype": dtype,
                         "operand_mb": round(op_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_matmul(args):
    import jax
    import jax.numpy as jnp

    n = args.size
    a = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(x):
        return x @ x

    _fence(mm(a))  # compile
    t0 = time.perf_counter()
    _fence(mm(a))
    dt = time.perf_counter() - t0
    _out("matmul", {"size": n, "dtype": "bfloat16"},
         2 * n**3 / dt / 1e12, "TFLOP/s")


def bench_cast(args):
    import jax
    import jax.numpy as jnp

    tables = [jnp.ones((args.rows, args.width), jnp.float32)
              for _ in range(args.tables)]
    total_gb = args.tables * args.rows * args.width * 4 / 2**30

    @jax.jit
    def cast_all(ts):
        return [t.astype(jnp.bfloat16) for t in ts]

    _fence(cast_all(tables)[0])  # compile
    t0 = time.perf_counter()
    _fence(cast_all(tables)[0])
    dt = time.perf_counter() - t0
    _out("cast", {"tables": args.tables, "rows": args.rows,
                  "width": args.width, "read_gb": round(total_gb, 2)},
         total_gb / dt, "GB/s (fp32 read side)")


def bench_dedup(args):
    """Probes behind the host-assisted dedup lever (PERF.md round 3):
    the headline step's 39-field update cost under each write strategy,
    all fields in ONE jitted program (matching the fused step's shape).

    Answers two real-chip questions the design hinges on:
    (a) does XLA scatter get cheaper when duplicate lanes become
        OOB-drop no-ops (unique-only writes)?
    (b) how much of the device-side dedup cost is the argsort that a
        host prefetch thread could precompute?
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width, b = args.tables, args.rows, args.width + 1, args.n_idx
    rng = np.random.default_rng(0)
    ids_np = (rng.zipf(1.3, size=(b, F)) % rows).astype(np.int32)
    uniq_frac = np.mean(
        [np.unique(ids_np[:, f]).size for f in range(F)]
    ) / b
    ids = jnp.asarray(ids_np)
    upd = jnp.full((b, width), 1e-3, jnp.float32)
    tables = [jnp.zeros((rows, width), jnp.float32) for _ in range(F)]

    # Host-side aux (what the prefetch thread would ship): per-field sort
    # order and run-start mask, one vectorized numpy pass for all fields.
    order_np = np.argsort(ids_np, axis=0, kind="stable").astype(np.int32)
    sid_np = np.take_along_axis(ids_np, order_np, axis=0)
    run_np = np.concatenate(
        [np.ones((1, F), bool), sid_np[1:] != sid_np[:-1]], axis=0
    )
    order = jnp.asarray(order_np)
    run_start = jnp.asarray(run_np)
    sid_dev = jnp.asarray(sid_np)
    # Compacted per-field segment map: seg[p] = segment index of sorted
    # lane p; useg[s] = the unique id segment s writes to (OOB-padded) —
    # both host-computable, so the device never sorts or re-expands.
    seg_np = run_np.cumsum(axis=0).astype(np.int32) - 1
    useg_np = np.full((b, F), rows, np.int32)
    for f in range(F):
        u = sid_np[run_np[:, f], f]
        useg_np[: u.size, f] = u
    seg_dev = jnp.asarray(seg_np)
    useg = jnp.asarray(useg_np)

    def timed(name, fn, *xs, extra=None):
        f = jax.jit(fn)  # returns ALL tables — nothing is DCE'd

        def run():
            return _fence(jax.tree_util.tree_leaves(f(*xs))[0])

        run()  # compile
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        cfg = {"fields": F, "rows": rows, "width": width, "batch": b,
               "uniq_frac": round(float(uniq_frac), 3)}
        if extra:
            cfg.update(extra)
        _out(f"dedup_{name}", cfg, dt * 1e3, "ms/step-equivalent")
        return dt

    def scatter_all(ts, idx):
        return [t.at[idx[:, f]].add(upd, mode="drop")
                for f, t in enumerate(ts)]

    timed("scatter_zipf", scatter_all, tables, ids)
    # Duplicate lanes routed out-of-bounds: same index count, unique
    # writes only — isolates whether dropped lanes are cheaper.
    oob_ids = jnp.where(run_start, sid_dev, rows)
    timed("scatter_dropped_dups", scatter_all, tables, oob_ids)

    def argsort_all(idx):
        return [jnp.argsort(idx[:, f]) for f in range(F)]

    timed("argsort_only", argsort_all, ids)

    def dedup_device_all(ts, idx):
        from fm_spark_tpu.ops.scatter import apply_row_updates
        return [apply_row_updates(t, idx[:, f], upd, mode="dedup")
                for f, t in enumerate(ts)]

    timed("device_full", dedup_device_all, tables, ids)

    def dedup_hostaux_all(ts, o, sg, u):
        # Device work: ONE batch-to-batch gather (delta reorder), one
        # segment_sum, one unique-target scatter. No sort, no [seg]
        # re-expansion.
        out = []
        for f, t in enumerate(ts):
            sdelta = upd[o[:, f]]
            summed = jax.ops.segment_sum(sdelta, sg[:, f], num_segments=b)
            out.append(t.at[u[:, f]].add(summed, mode="drop"))
        return out

    timed("hostaux", dedup_hostaux_all, tables, order, seg_dev, useg)


BENCHES = {
    "dispatch": bench_dispatch,
    "gather": bench_gather,
    "scatter": bench_scatter,
    "matmul": bench_matmul,
    "cast": bench_cast,
    "dedup": bench_dedup,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("bench", choices=[*BENCHES, "all"])
    ap.add_argument("--calls", type=int, default=30)
    ap.add_argument("--n-idx", type=int, default=5_242_880,
                    help="gather/scatter index count (~B*F at the "
                    "headline batch)")
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--tables", type=int, default=39)
    ap.add_argument("--size", type=int, default=8192)
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    _log(f"device: {jax.devices()[0].device_kind}")
    for name in (BENCHES if args.bench == "all" else [args.bench]):
        _log(f"running {name}...")
        BENCHES[name](args)


if __name__ == "__main__":
    main()
