"""Microbenchmarks behind PERF.md's measured facts 1-5.

Each subcommand reproduces one design-driving measurement so the
architecture rationale stays checkable on any attachment:

  dispatch   fact 1: per-dispatch host/tunnel overhead (trivial scalar
             add, timed per call) and the fori_loop amortization.
  gather     fact 2: per-index gather rate vs table BYTES (the ~34MB
             cliff that motivates per-field sub-tables).
  scatter    fact 3: scatter-add rate vs operand size (the ~128MB cliff
             and per-index bound that motivate single-owner sub-tables).
  matmul     fact 4: MXU peak check (compute is not the binding
             constraint).
  cast       fact 5: dense streaming bandwidth (why per-step shadow
             recasts are off the table).
  all        run everything.

Prints one JSON line per measurement: {"bench": ..., "config": ...,
"value": ..., "unit": ...}. Timing uses a device->host transfer as the
completion fence (block_until_ready returns early on this attachment,
PERF.md timing note).
"""

import argparse
import json
import sys
import time


def _log(msg):
    print(f"bench_micro: {msg}", file=sys.stderr, flush=True)


def _out(bench, config, value, unit):
    print(json.dumps({"bench": bench, "config": config,
                      "value": round(value, 3), "unit": unit}), flush=True)


def _fence(x):
    """Reliable completion fence: device->host transfer of one scalar."""
    import jax.numpy as jnp

    return float(jnp.ravel(x)[0])


def _make_timed(prefix, base_cfg, unit):
    """Shared timing protocol for every probe: jit the thunk, run once
    (compile + warmup), time one fenced run, emit one JSON line. Single
    definition so a protocol change (extra warmup, median-of-N) lands in
    every probe at once. The jitted fn must RETURN everything it touches
    (nothing may be DCE'd)."""
    import jax

    def timed(name, fn, *xs, extra=None):
        f = jax.jit(fn)

        def run():
            return _fence(jax.tree_util.tree_leaves(f(*xs))[0])

        run()  # compile
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        cfg = dict(base_cfg)
        if extra:
            cfg.update(extra)
        _out(f"{prefix}_{name}", cfg, dt * 1e3, unit)
        return dt

    return timed


def bench_dispatch(args):
    import jax
    import jax.numpy as jnp
    from jax import lax

    one = jnp.float32(1.0)

    @jax.jit
    def add(x):
        return x + 1.0

    @jax.jit
    def add_n(x, n):
        return lax.fori_loop(0, n, lambda i, c: c + 1.0, x)

    _fence(add(one))           # compile
    _fence(add_n(one, jnp.int32(2)))
    t0 = time.perf_counter()
    x = one
    for _ in range(args.calls):
        x = add(x)
    _fence(x)
    per_call = (time.perf_counter() - t0) / args.calls
    _out("dispatch", {"calls": args.calls}, per_call * 1e3,
         "ms/dispatch")

    t0 = time.perf_counter()
    _fence(add_n(one, jnp.int32(args.calls)))
    per_iter = (time.perf_counter() - t0) / args.calls
    _out("dispatch_fori", {"iters": args.calls}, per_iter * 1e6,
         "us/iter (same adds inside one fori_loop program)")


def _gather_once(rows, width, dtype, n_idx, seed=0):
    import jax
    import jax.numpy as jnp
    import numpy as np

    table = jnp.zeros((rows, width), dtype)
    ids = jnp.asarray(
        np.random.default_rng(seed).integers(0, rows, n_idx), jnp.int32
    )

    @jax.jit
    def g(t, i):
        return jnp.sum(t[i].astype(jnp.float32))

    _fence(g(table, ids))  # compile
    t0 = time.perf_counter()
    _fence(g(table, ids))
    return time.perf_counter() - t0


def bench_gather(args):
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "bfloat16"),
                        (1 << 18, "float32"), (1 << 19, "float32"),
                        (1 << 20, "float32")]:
        dt = _gather_once(rows, args.width, dtype, args.n_idx)
        tbl_mb = rows * args.width * (2 if dtype == "bfloat16" else 4) / 2**20
        _out("gather", {"rows": rows, "width": args.width, "dtype": dtype,
                        "table_mb": round(tbl_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_scatter(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    for rows, dtype in [(1 << 17, "float32"), (1 << 18, "float32"),
                        (1 << 19, "float32"), (1 << 20, "float32")]:
        table = jnp.zeros((rows, args.width), dtype)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(0, rows, args.n_idx),
            jnp.int32,
        )
        upd = jnp.ones((args.n_idx, args.width), dtype)

        @jax.jit
        def sc(t, i, u):
            return t.at[i].add(u, mode="drop")

        _fence(sc(table, ids, upd))  # compile
        t0 = time.perf_counter()
        _fence(sc(table, ids, upd))
        dt = time.perf_counter() - t0
        op_mb = rows * args.width * 4 / 2**20
        _out("scatter", {"rows": rows, "width": args.width, "dtype": dtype,
                         "operand_mb": round(op_mb, 1), "n_idx": args.n_idx},
             args.n_idx / dt / 1e6, "M idx/s")


def bench_matmul(args):
    import jax
    import jax.numpy as jnp

    n = args.size
    a = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def mm(x):
        return x @ x

    _fence(mm(a))  # compile
    t0 = time.perf_counter()
    _fence(mm(a))
    dt = time.perf_counter() - t0
    _out("matmul", {"size": n, "dtype": "bfloat16"},
         2 * n**3 / dt / 1e12, "TFLOP/s")


def bench_cast(args):
    import jax
    import jax.numpy as jnp

    tables = [jnp.ones((args.rows, args.width), jnp.float32)
              for _ in range(args.tables)]
    total_gb = args.tables * args.rows * args.width * 4 / 2**30

    @jax.jit
    def cast_all(ts):
        return [t.astype(jnp.bfloat16) for t in ts]

    _fence(cast_all(tables)[0])  # compile
    t0 = time.perf_counter()
    _fence(cast_all(tables)[0])
    dt = time.perf_counter() - t0
    _out("cast", {"tables": args.tables, "rows": args.rows,
                  "width": args.width, "read_gb": round(total_gb, 2)},
         total_gb / dt, "GB/s (fp32 read side)")


def bench_dedup(args):
    """Probes behind the host-assisted dedup lever (PERF.md round 3):
    the headline step's 39-field update cost under each write strategy,
    all fields in ONE jitted program (matching the fused step's shape).

    Answers two real-chip questions the design hinges on:
    (a) does XLA scatter get cheaper when duplicate lanes become
        OOB-drop no-ops (unique-only writes)?
    (b) how much of the device-side dedup cost is the argsort that a
        host prefetch thread could precompute?
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width, b = args.tables, args.rows, args.width + 1, args.n_idx
    rng = np.random.default_rng(0)
    ids_np = (rng.zipf(1.3, size=(b, F)) % rows).astype(np.int32)
    uniq_frac = np.mean(
        [np.unique(ids_np[:, f]).size for f in range(F)]
    ) / b
    ids = jnp.asarray(ids_np)
    upd = jnp.full((b, width), 1e-3, jnp.float32)
    tables = [jnp.zeros((rows, width), jnp.float32) for _ in range(F)]

    # Host-side aux (what the prefetch thread would ship): per-field sort
    # order and run-start mask, one vectorized numpy pass for all fields.
    order_np = np.argsort(ids_np, axis=0, kind="stable").astype(np.int32)
    sid_np = np.take_along_axis(ids_np, order_np, axis=0)
    run_np = np.concatenate(
        [np.ones((1, F), bool), sid_np[1:] != sid_np[:-1]], axis=0
    )
    order = jnp.asarray(order_np)
    run_start = jnp.asarray(run_np)
    sid_dev = jnp.asarray(sid_np)
    # Compacted per-field segment map: seg[p] = segment index of sorted
    # lane p; useg[s] = the unique id segment s writes to (OOB-padded) —
    # both host-computable, so the device never sorts or re-expands.
    seg_np = run_np.cumsum(axis=0).astype(np.int32) - 1
    useg_np = np.full((b, F), rows, np.int32)
    for f in range(F):
        u = sid_np[run_np[:, f], f]
        useg_np[: u.size, f] = u
    seg_dev = jnp.asarray(seg_np)
    useg = jnp.asarray(useg_np)

    timed = _make_timed(
        "dedup",
        {"fields": F, "rows": rows, "width": width, "batch": b,
         "uniq_frac": round(float(uniq_frac), 3)},
        "ms/step-equivalent",
    )

    def scatter_all(ts, idx):
        return [t.at[idx[:, f]].add(upd, mode="drop")
                for f, t in enumerate(ts)]

    timed("scatter_zipf", scatter_all, tables, ids)
    # Duplicate lanes routed out-of-bounds: same index count, unique
    # writes only — isolates whether dropped lanes are cheaper.
    oob_ids = jnp.where(run_start, sid_dev, rows)
    timed("scatter_dropped_dups", scatter_all, tables, oob_ids)

    def argsort_all(idx):
        return [jnp.argsort(idx[:, f]) for f in range(F)]

    timed("argsort_only", argsort_all, ids)

    def dedup_device_all(ts, idx):
        from fm_spark_tpu.ops.scatter import apply_row_updates
        return [apply_row_updates(t, idx[:, f], upd, mode="dedup")
                for f, t in enumerate(ts)]

    timed("device_full", dedup_device_all, tables, ids)

    def dedup_hostaux_all(ts, o, sg, u):
        # Device work: ONE batch-to-batch gather (delta reorder), one
        # segment_sum, one unique-target scatter. No sort, no [seg]
        # re-expansion.
        out = []
        for f, t in enumerate(ts):
            sdelta = upd[o[:, f]]
            summed = jax.ops.segment_sum(sdelta, sg[:, f], num_segments=b)
            out.append(t.at[u[:, f]].add(summed, mode="drop"))
        return out

    timed("hostaux", dedup_hostaux_all, tables, order, seg_dev, useg)


def bench_split(args):
    """Probe behind the sub-split lever: each headline field table is
    262144x65 fp32 = 68MB — ABOVE the ~34MB gather cliff (fact 2). Does
    storing each field as S row-slabs (each under the cliff) win, given
    gather then costs S x b lanes at the fast rate instead of b at the
    slow rate, and scatter costs S x b lanes with (S-1)/S of them
    OOB-dropped?  Run with --n-idx 131072 for the headline shape.

    Emits, for S in {1, 2, 4}: the 39-field gather time and scatter time
    of one step-equivalent. The OOB question (are dropped scatter lanes
    charged?) falls out of scatter_s1 vs scatter_s2/s4.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width, b = args.tables, args.rows, args.width + 1, args.n_idx
    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        (rng.zipf(1.3, size=(b, F)) % rows).astype(np.int32)
    )
    upd = jnp.full((b, width), 1e-3, jnp.float32)

    timed = _make_timed(
        "split", {"fields": F, "rows": rows, "width": width, "batch": b},
        "ms/step-equivalent",
    )

    for s in (1, 2, 4):
        half = rows // s
        shift = int(np.log2(half))
        assert half * s == rows and 1 << shift == half
        slabs = [
            [jnp.zeros((half, width), jnp.float32) for _ in range(s)]
            for _ in range(F)
        ]
        slab_mb = half * width * 4 / 2**20

        def gather_all(ts, idx, s=s, shift=shift, half=half):
            # Per field: S masked gathers from slab-local ids + a select
            # chain — every id has exactly one owning slab.
            out = []
            for f, field_slabs in enumerate(ts):
                i = idx[:, f]
                hi, lo = i >> shift, i & (half - 1)
                r = None
                for j, t in enumerate(field_slabs):
                    rj = t[jnp.where(hi == j, lo, 0)]
                    r = rj if r is None else jnp.where(
                        (hi == j)[:, None], rj, r
                    )
                out.append(jnp.sum(r))
            return out

        def scatter_all(ts, idx, s=s, shift=shift, half=half):
            # Per field: S drop-scatters; non-owned lanes go OOB.
            out = []
            for f, field_slabs in enumerate(ts):
                i = idx[:, f]
                hi, lo = i >> shift, i & (half - 1)
                for j, t in enumerate(field_slabs):
                    out.append(
                        t.at[jnp.where(hi == j, lo, half)].add(
                            upd, mode="drop"
                        )
                    )
            return out

        timed(f"gather_s{s}", gather_all, slabs, ids,
              extra={"slabs": s, "slab_mb": round(slab_mb, 1)})
        timed(f"scatter_s{s}", scatter_all, slabs, ids,
              extra={"slabs": s, "slab_mb": round(slab_mb, 1)})


def bench_compact(args):
    """Probe behind the COMPACT host-dedup lever (round-2 finding: OOB-
    dropped scatter lanes are charged like live ones — dedup_scatter_
    dropped_dups ~= dedup_scatter_zipf — so winning requires REDUCING the
    lane count against the big tables, not masking lanes).

    With host-sorted ids and a static per-field unique-capacity ``cap``:
      forward:  urows = t[useg]         (cap sorted lanes vs B from 68MB)
                rows  = urows[inv]      (B lanes from a [cap,w] buffer)
      backward: sdelta = delta[order]   (B lanes, [B,w] buffer)
                csum   = cumsum(sdelta) (one streaming pass, no scatter)
                segsum = csum[seg_end] - csum[seg_end - run_len]
                t.at[useg].add(segsum, unique + sorted, cap lanes)

    vs the shipped chain: t[ids] gather (B lanes, 68MB table) +
    t.at[ids].add (B lanes). Run with --n-idx 131072.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width, b = args.tables, args.rows, args.width + 1, args.n_idx
    cap = args.cap
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    ids_np = (rng.zipf(1.3, size=(b, F)) % rows).astype(np.int32)
    nu = max(np.unique(ids_np[:, f]).size for f in range(F))
    if nu > cap:
        raise SystemExit(f"cap {cap} < max unique {nu}; raise --cap")

    # Host aux from the SHIPPED builder (one implementation of the
    # useg/segstart/segend/order/inv contract — the probe must measure
    # the same layout the step consumes).
    from fm_spark_tpu.ops.scatter import compact_aux

    useg_np, segstart_np, segend_np, order_np, inv_np = compact_aux(
        ids_np, cap
    )
    order = jnp.asarray(order_np.T)   # probe uses [B, F]-major layouts
    useg = jnp.asarray(useg_np)
    segend = jnp.asarray(segend_np)
    segstart = jnp.asarray(segstart_np)
    inv = jnp.asarray(inv_np.T)
    ids = jnp.asarray(ids_np)
    tables = [jnp.zeros((rows, width), dtype) for _ in range(F)]
    delta = jnp.full((b, width), 1e-3, jnp.float32)

    timed = _make_timed(
        "compact",
        {"fields": F, "rows": rows, "width": width, "batch": b,
         "cap": cap, "max_unique": int(nu), "dtype": args.dtype},
        "ms/step-equivalent",
    )

    def baseline_chain(ts, idx):
        out = []
        for f, t in enumerate(ts):
            r = t[idx[:, f]].astype(jnp.float32)
            out.append(t.at[idx[:, f]].add(
                (r * 1e-4 + delta).astype(t.dtype), mode="drop"))
        return out

    timed("baseline_gather_scatter", baseline_chain, tables, ids)

    def compact_chain(ts, useg, inv, order, segend, segstart, skip=()):
        # ``skip`` disables pieces so their marginal cost can be
        # bracketed on chip: 'expand' (per-lane row expansion),
        # 'reorder' (the delta[order] gather), 'cumsum' (the segment
        # reduction).
        out = []
        for f, t in enumerate(ts):
            u = useg[f]
            urows = t[jnp.clip(u, 0, rows - 1)]        # cap sorted lanes
            if "expand" in skip:
                d = delta
            else:
                r = urows[inv[:, f]]                   # B lanes, tiny buf
                d = r.astype(jnp.float32) * 1e-4 + delta
            sdelta = d if "reorder" in skip else d[order[:, f]]
            if "cumsum" in skip:
                segsum = sdelta[segstart[f]]
            else:
                csum = jnp.cumsum(sdelta, axis=0)
                lo = csum[segstart[f]] - sdelta[segstart[f]]
                segsum = csum[segend[f]] - lo          # exact per-segment
            out.append(
                t.at[u].add(segsum.astype(t.dtype), mode="drop",
                            unique_indices=True, indices_are_sorted=True)
            )
        return out

    timed("chain", compact_chain, tables, useg, inv, order, segend,
          segstart)
    import functools

    for piece in ("expand", "reorder", "cumsum"):
        timed(
            f"chain_minus_{piece}",
            functools.partial(compact_chain, skip=(piece,)),
            tables, useg, inv, order, segend, segstart,
            extra={"skipped": piece},
        )

    def compact_scatter_only(ts, useg):
        return [
            t.at[useg[f]].add(jnp.ones((cap, width), t.dtype),
                              mode="drop", unique_indices=True,
                              indices_are_sorted=True)
            for f, t in enumerate(ts)
        ]

    timed("scatter_unique_sorted_only", compact_scatter_only, tables,
          useg)

    def compact_gather_only(ts, useg):
        return [jnp.sum(t[jnp.clip(useg[f], 0, rows - 1)]
                        .astype(jnp.float32))
                for f, t in enumerate(ts)]

    timed("gather_cap_only", compact_gather_only, tables, useg)


def bench_cumsum(args):
    """The compact chain's cumsum is its biggest removable piece (~46ms
    of the 127ms bf16 chain — `compact` probe, chain vs chain_minus_
    cumsum). This probe isolates how the prefix cost responds to width
    (TPU minor-dim lane padding: widths 1..128 should cost the SAME
    physical bandwidth), dtype, orientation, and the blocked two-level
    formulation, plus the totals-only lower bound (one read pass).
    Shapes: 39 x [131072, w] like the headline backward buffers.
    """
    import jax
    import jax.numpy as jnp

    F, b = args.tables, args.n_idx
    timed = _make_timed("cumsum", {"fields": F, "batch": b},
                        "ms/39-field")

    for w, dt_ in ((65, jnp.float32), (64, jnp.float32),
                   (128, jnp.float32), (33, jnp.float32),
                   (65, jnp.bfloat16)):
        xs = [jnp.full((b, w), 1e-3, dt_) for _ in range(F)]
        timed(
            f"w{w}_{dt_.__name__}",
            lambda ts: [jnp.cumsum(t, axis=0) for t in ts], xs,
            extra={"width": w, "dtype": dt_.__name__},
        )

    xs65 = [jnp.full((b, 65), 1e-3, jnp.float32) for _ in range(F)]
    # Totals-only lower bound: one read pass, [w] out per field.
    timed("sum_only_w65", lambda ts: [jnp.sum(t, axis=0) for t in ts],
          xs65, extra={"width": 65, "dtype": "float32"})

    # Blocked two-level prefix: per-block local cumsum -> tiny cumsum of
    # block totals -> add offsets. Same output as cumsum. (Round 3: this
    # formulation SHIPPED in ops/scatter.compact_apply and lifted the
    # headline 1.06M -> 1.18M; the block sweep picks _CSUM_BLOCK.)
    def blocked(ts, blk):
        out = []
        for t in ts:
            pad = (-b) % blk  # same padding as the shipped compact_apply
            if pad:
                t = jnp.pad(t, ((0, pad), (0, 0)))
            r = t.reshape(-1, blk, t.shape[-1])
            bl = jnp.cumsum(r, axis=1)
            off = jnp.cumsum(bl[:, -1, :], axis=0)
            off = jnp.concatenate(
                [jnp.zeros_like(off[:1]), off[:-1]], axis=0
            )
            out.append(
                (bl + off[:, None, :]).reshape(-1, t.shape[-1])[:b]
            )
        return out

    for blk in (256, 512, 1024):
        timed(f"blocked{blk}_w65",
              lambda ts, blk=blk: blocked(ts, blk), xs65,
              extra={"width": 65, "dtype": "float32"})

    # What compact_apply actually pays: it never materializes the full
    # prefix — it GATHERS bl/off at 2·cap boundary positions.
    cap = args.cap or 16384
    pos = jnp.sort(
        jax.random.randint(jax.random.key(0), (cap,), 0, b, jnp.int32)
    )

    def boundaries_only(ts, blk):
        out = []
        for t in ts:
            pad = (-b) % blk
            if pad:
                t = jnp.pad(t, ((0, pad), (0, 0)))
            r = t.reshape(-1, blk, t.shape[-1])
            bl = jnp.cumsum(r, axis=1)
            off = jnp.cumsum(bl[:, -1, :], axis=0)
            off = jnp.concatenate(
                [jnp.zeros_like(off[:1]), off[:-1]], axis=0
            )
            out.append(bl[pos // blk, pos % blk] + off[pos // blk])
        return out

    for blk in (256, 512, 1024):
        timed(f"boundaries{blk}_w65",
              lambda ts, blk=blk: boundaries_only(ts, blk), xs65,
              extra={"width": 65, "dtype": "float32", "cap": cap})

    # Transposed orientation: prefix along the LANE-major axis.
    xsT = [jnp.full((65, b), 1e-3, jnp.float32) for _ in range(F)]
    timed("transposed_w65",
          lambda ts: [jnp.cumsum(t, axis=1) for t in ts],
          xsT, extra={"width": 65, "dtype": "float32", "layout": "[w,B]"})


def bench_merge(args):
    """Is the compact chain's per-field gather/scatter cost a FIXED
    per-op overhead (x39 fields) rather than per-lane or per-byte? The
    `compact` probe measured ~1.7ms/table for a 16k-lane cap-gather —
    barely cheaper than 131k lanes — suggesting op-count or table-scan
    cost, not lane count, is what the cap path still pays. If per-op,
    ONE gather over a stacked monolith at cap*F lanes should crush 39
    per-field gathers even at the monolith's slow per-lane rate.
    Scatter is probed both ways too — the >128MB operand cliff (fact 3)
    predicts the merged scatter LOSES; per-field writes should stay.

    Index construction: the monolith has ``cap`` PADDING rows appended
    per field (shape [(rows+cap)*F, w]); field f's real ids live at
    ``f*(rows+cap) + id`` and its sentinel lanes map to the padding
    rows ``f*(rows+cap) + rows + s`` — so the flattened index vector is
    genuinely ascending AND unique (both XLA promises hold; padding
    rows absorb the sentinel writes, which is timing-equivalent to
    dropping them).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width = args.tables, args.rows, args.width + 1
    cap = args.cap
    b = args.n_idx
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    ids_np = (rng.zipf(1.3, size=(b, F)) % rows).astype(np.int32)
    from fm_spark_tpu.ops.scatter import compact_aux

    useg_np = compact_aux(ids_np, cap)[0]             # [F, cap]
    useg = jnp.asarray(useg_np)
    stride = rows + cap
    sent = useg_np >= rows                             # sentinel lanes
    within = np.where(
        sent,
        rows + (useg_np.argsort(axis=1).argsort(axis=1)),  # stable slots
        useg_np,
    )
    # Per-field ascending (real ids ascend below rows; sentinel slots
    # ascend from rows), plus field-major strides => globally ascending
    # and unique.
    gids = jnp.asarray(
        (within + (np.arange(F)[:, None] * stride)).astype(np.int32)
        .reshape(-1)
    )
    tables = [jnp.zeros((rows, width), dtype) for _ in range(F)]
    mono = jnp.zeros((F * stride, width), dtype)
    upd = jnp.full((F * cap, width), 1e-3, jnp.float32)

    timed = _make_timed(
        "merge",
        {"fields": F, "rows": rows, "width": width, "cap": cap,
         "dtype": args.dtype},
        "ms",
    )

    timed("gather_per_field",
          lambda ts, u: [t[jnp.clip(u[f], 0, rows - 1)]
                         for f, t in enumerate(ts)],
          tables, useg)
    timed("gather_monolith",
          lambda m, g: m.at[g].get(mode="clip", indices_are_sorted=True,
                                   unique_indices=True),
          mono, gids)
    timed("scatter_per_field",
          lambda ts, u: [t.at[u[f]].add(
              upd[f * cap:(f + 1) * cap].astype(t.dtype), mode="drop",
              unique_indices=True, indices_are_sorted=True)
              for f, t in enumerate(ts)],
          tables, useg)
    timed("scatter_monolith",
          lambda m, g: m.at[g].add(upd.astype(m.dtype), mode="drop",
                                   unique_indices=True,
                                   indices_are_sorted=True),
          mono, gids)


def bench_stackfuse(args):
    """Does issuing the chain's buffer work as 39 per-field ops cost
    more than ONE op over the stacked [39, B, w] array? (It did not on
    this chip — sum/cumsum/boundary came out equal, refuting the
    per-fusion-overhead hypothesis; the cost is per-work. Kept so the
    conclusion stays reproducible.)
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, width, b = args.tables, args.width + 1, args.n_idx
    cap = args.cap
    rng = np.random.default_rng(0)
    xs = [jnp.full((b, width), 1e-3, jnp.float32) for _ in range(F)]
    xstk = jnp.stack(xs)                              # [F, B, w]
    small = [jnp.full((cap, width), 1e-3, jnp.float32) for _ in range(F)]
    smallstk = jnp.stack(small)                       # [F, cap, w]
    inv = jnp.asarray(rng.integers(0, cap, size=(F, b)), jnp.int32)
    bnd = jnp.asarray(rng.integers(0, b, size=(F, cap)), jnp.int32)

    timed = _make_timed(
        "stackfuse",
        {"fields": F, "batch": b, "width": width, "cap": cap},
        "ms",
    )

    timed("sum_per_field",
          lambda ts: [jnp.sum(t, axis=0) for t in ts], xs)
    timed("sum_stacked", lambda t: jnp.sum(t, axis=1), xstk)
    timed("cumsum_per_field",
          lambda ts: [jnp.cumsum(t, axis=0) for t in ts], xs)
    timed("cumsum_stacked", lambda t: jnp.cumsum(t, axis=1), xstk)
    timed("expand_per_field",
          lambda ss, iv: [s[iv[f]] for f, s in enumerate(ss)],
          small, inv)
    timed("expand_stacked",
          lambda s, iv: jnp.take_along_axis(s, iv[:, :, None], axis=1),
          smallstk, inv)
    timed("boundary_per_field",
          lambda ts, bd: [t[bd[f]] for f, t in enumerate(ts)], xs, bnd)
    timed("boundary_stacked",
          lambda t, bd: jnp.take_along_axis(t, bd[:, :, None], axis=1),
          xstk, bnd)


def bench_scanmodel(args):
    """Pins the round-2 cost model: big-table ops cost ~= stream(operand
    bytes)/BW + lanes * ~20ns, i.e. gather SCANS the table no matter how
    few lanes it fetches. Probes (39 fields, headline rows/width):

    - cap-gather at cap in {1024, 16384, B}: flat => scan confirmed;
    - gather at fp8 / bf16 / fp32 tables: scan cost should track BYTES;
    - sorted segment_sum into cap segments (tiny [cap, w] operand) vs
      the cumsum+boundary formulation the chain ships;
    - cumsum with bf16 INPUT, fp32 accumulation (halves the read side).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width, b = args.tables, args.rows, args.width + 1, args.n_idx
    rng = np.random.default_rng(0)
    timed = _make_timed(
        "scanmodel", {"fields": F, "rows": rows, "width": width}, "ms",
    )

    for cap_try in (1024, 16384, min(b, rows)):
        ids = jnp.asarray(
            np.sort(rng.choice(rows, size=(F, cap_try))).astype(np.int32),
            jnp.int32,
        )
        tables = [jnp.zeros((rows, width), jnp.bfloat16)
                  for _ in range(F)]
        timed(f"gather_cap{cap_try}_bf16",
              lambda ts, u: [jnp.sum(t[u[f]].astype(jnp.float32))
                             for f, t in enumerate(ts)],
              tables, ids, extra={"cap": cap_try, "table_dtype": "bf16"})

    for dt_name in ("float8_e4m3fn", "bfloat16", "float32"):
        dt_ = getattr(jnp, dt_name)
        ids = jnp.asarray(
            np.sort(rng.choice(rows, size=(F, 16384))).astype(np.int32),
            jnp.int32,
        )
        tables = [jnp.zeros((rows, width), dt_) for _ in range(F)]
        timed(f"gather_cap16384_{dt_name}",
              lambda ts, u: [jnp.sum(t[u[f]].astype(jnp.float32))
                             for f, t in enumerate(ts)],
              tables, ids, extra={"cap": 16384, "table_dtype": dt_name})

    # Segment reduction alternatives at the chain's shapes.
    cap = args.cap
    seg = jnp.asarray(
        np.sort(rng.integers(0, cap, size=(F, b)), axis=1).astype(np.int32)
    )
    sdelta = [jnp.full((b, width), 1e-3, jnp.float32) for _ in range(F)]

    timed("segsum_sorted_capsegs",
          lambda ds, sg: [
              jax.ops.segment_sum(d, sg[f], num_segments=cap,
                                  indices_are_sorted=True)
              for f, d in enumerate(ds)
          ],
          sdelta, seg, extra={"cap": cap})

    bnd = jnp.asarray(rng.integers(0, b, size=(F, cap)), jnp.int32)
    timed("cumsum_boundary_fp32",
          lambda ds, bd: [
              jnp.cumsum(d, axis=0)[bd[f]] for f, d in enumerate(ds)
          ],
          sdelta, bnd, extra={"cap": cap})
    sdelta_bf = [d.astype(jnp.bfloat16) for d in sdelta]
    timed("cumsum_boundary_bf16in",
          lambda ds, bd: [
              jnp.cumsum(d, axis=0, dtype=jnp.float32)[bd[f]]
              for f, d in enumerate(ds)
          ],
          sdelta_bf, bnd, extra={"cap": cap})


def bench_transpose(args):
    """Table-layout probe: [rows, 65] pads the minor dim to 128 lanes
    (physical bytes ~2x nominal), and the scan model says big-table ops
    track OPERAND bytes. A transposed [65, rows] table has no lane
    padding (rows % 128 == 0) — if the scan really tracks physical
    bytes, column-gather/scatter on the transposed layout should cost
    about half. Also probes width 256 on the row layout (2 lane-tiles)
    to confirm the padding model itself.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, rows, width = args.tables, args.rows, args.width + 1
    cap = args.cap
    dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    from fm_spark_tpu.ops.scatter import compact_aux

    ids_np = (rng.zipf(1.3, size=(args.n_idx, F)) % rows).astype(np.int32)
    useg = jnp.asarray(compact_aux(ids_np, cap)[0])
    upd_row = jnp.full((cap, width), 1e-3, jnp.float32)
    upd_col = jnp.full((width, cap), 1e-3, jnp.float32)

    timed = _make_timed(
        "transpose",
        {"fields": F, "rows": rows, "width": width, "cap": cap,
         "dtype": args.dtype},
        "ms",
    )

    tables = [jnp.zeros((rows, width), dtype) for _ in range(F)]
    timed("row_gather_cap",
          lambda ts, u: [jnp.sum(t[jnp.clip(u[f], 0, rows - 1)]
                                 .astype(jnp.float32))
                         for f, t in enumerate(ts)],
          tables, useg)
    timed("row_scatter_cap",
          lambda ts, u: [t.at[u[f]].add(upd_row.astype(t.dtype),
                                        mode="drop", unique_indices=True,
                                        indices_are_sorted=True)
                         for f, t in enumerate(ts)],
          tables, useg)
    del tables

    tablesT = [jnp.zeros((width, rows), dtype) for _ in range(F)]
    timed("col_gather_cap",
          lambda ts, u: [jnp.sum(t[:, jnp.clip(u[f], 0, rows - 1)]
                                 .astype(jnp.float32))
                         for f, t in enumerate(ts)],
          tablesT, useg)
    timed("col_scatter_cap",
          lambda ts, u: [t.at[:, u[f]].add(upd_col.astype(t.dtype),
                                           mode="drop",
                                           unique_indices=True,
                                           indices_are_sorted=True)
                         for f, t in enumerate(ts)],
          tablesT, useg)
    del tablesT

    tables256 = [jnp.zeros((rows, 256), dtype) for _ in range(F)]
    timed("row_gather_cap_w256",
          lambda ts, u: [jnp.sum(t[jnp.clip(u[f], 0, rows - 1)]
                                 .astype(jnp.float32))
                         for f, t in enumerate(ts)],
          tables256, useg, extra={"width": 256})


def bench_gfull(args):
    """The g_full construction A/B (PERF.md round-4 lever): per-field
    ``concat([g_v, g_l])`` vs the fused ``ds·x·(s1 − mask·xv_full)``
    form (one s1 concat total). Both arms start from (rows, vals, ds, s)
    — including the xv recompute each form implies — and are timed two
    ways: bare construction (sum consumer) and with the compact chain's
    first consumer, a per-field reorder gather, so fusion INTO the
    gather is captured. If XLA already fuses the concats away, the arms
    tie and the lever is refuted.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    F, k, b = args.tables, args.width, args.n_idx
    w = k + 1
    rng = np.random.default_rng(0)
    rows = [jnp.asarray(rng.normal(size=(b, w)), jnp.float32)
            for _ in range(F)]
    vals = jnp.asarray(rng.uniform(0.5, 1.5, size=(b, F)), jnp.float32)
    ds = jnp.asarray(rng.normal(size=(b,)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(b, k)), jnp.float32)
    order = jnp.asarray(
        np.stack([rng.permutation(b) for _ in range(F)]), jnp.int32)

    timed = _make_timed(
        "gfull", {"fields": F, "batch": b, "width": w}, "ms",
    )

    def g_concat(rows, vals, ds, s):
        out = []
        for f in range(F):
            xv = rows[f][:, :k] * vals[:, f : f + 1]
            g_v = ds[:, None] * vals[:, f : f + 1] * (s - xv)
            g_l = ds * vals[:, f]
            out.append(jnp.concatenate([g_v, g_l[:, None]], axis=1))
        return out

    def g_fused(rows, vals, ds, s):
        s1 = jnp.concatenate(
            [s, jnp.ones((ds.shape[0], 1), jnp.float32)], axis=1)
        colmask = jnp.arange(w) < k
        out = []
        for f in range(F):
            xvf = rows[f] * vals[:, f : f + 1]
            out.append(ds[:, None] * vals[:, f : f + 1] * (
                s1 - jnp.where(colmask, xvf, jnp.zeros((), jnp.float32))))
        return out

    timed("concat_sum",
          lambda *xs: [jnp.sum(g) for g in g_concat(*xs)],
          rows, vals, ds, s)
    timed("fused_sum",
          lambda *xs: [jnp.sum(g) for g in g_fused(*xs)],
          rows, vals, ds, s)
    timed("concat_reorder",
          lambda o, *xs: [jnp.sum(g[o[f]])
                          for f, g in enumerate(g_concat(*xs))],
          order, rows, vals, ds, s)
    timed("fused_reorder",
          lambda o, *xs: [jnp.sum(g[o[f]])
                          for f, g in enumerate(g_fused(*xs))],
          order, rows, vals, ds, s)


BENCHES = {
    "dispatch": bench_dispatch,
    "gather": bench_gather,
    "scatter": bench_scatter,
    "matmul": bench_matmul,
    "cast": bench_cast,
    "dedup": bench_dedup,
    "split": bench_split,
    "compact": bench_compact,
    "cumsum": bench_cumsum,
    "merge": bench_merge,
    "stackfuse": bench_stackfuse,
    "scanmodel": bench_scanmodel,
    "transpose": bench_transpose,
    "gfull": bench_gfull,
}


def main():
    # Honor an explicit JAX_PLATFORMS=cpu smoke request even when the
    # attachment is dead (the plugin factory would hang init otherwise).
    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()
    ap = argparse.ArgumentParser()
    ap.add_argument("bench", choices=[*BENCHES, "all"])
    ap.add_argument("--calls", type=int, default=30)
    ap.add_argument("--n-idx", type=int, default=None,
                    help="index count. Default depends on the probe: the "
                    "single-table probes (gather/scatter) use B*F = "
                    "5242880 (the headline step's total index count); "
                    "the per-field batch probes (dedup/split/compact/"
                    "cumsum/merge/stackfuse/scanmodel/transpose/gfull) use "
                    "B = 131072 (the headline batch) — passing the B*F "
                    "default to those would build a 204M-id host aux")
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--tables", type=int, default=39)
    ap.add_argument("--size", type=int, default=8192)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="compact/merge probes: table storage dtype")
    ap.add_argument("--cap", type=int, default=16384,
                    help="compact probe: static per-field unique-id "
                    "capacity")
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    _log(f"device: {jax.devices()[0].device_kind}")
    import copy

    for name in (BENCHES if args.bench == "all" else [args.bench]):
        a = copy.copy(args)
        if a.n_idx is None:
            a.n_idx = 5_242_880 if name in ("gather", "scatter") else 1 << 17
        _log(f"running {name}...")
        BENCHES[name](a)


if __name__ == "__main__":
    main()
