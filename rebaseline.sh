#!/bin/bash
# Re-baseline on a HEALTHY attachment (VERDICT r4 #2b / PERF.md "Next
# levers": every ranking in PERF.md was measured on an attachment
# streaming at 5-10% of nominal HBM, and standalone-op probes there
# repeatedly over-predicted full-step effects — on a full-bandwidth
# chip the scan terms shrink ~10x and the bottleneck ranking likely
# reorders). Run this ONCE on real hardware before optimizing further:
#
#   bash rebaseline.sh [outdir]
#
# Captures, in order of value-per-minute (so a flaky window still
# yields the important rows first):
#   1. bench.py full default sweep  -> the headline + all staged A/Bs
#      (gfull slot 2, segtotal slot 3, colT, devaux) + MEASURED.json
#   2. bench_micro.py all           -> the op-level probe rows PERF.md's
#      cost model is built from (re-rank the levers against these)
#   3. bench_input.py               -> host pipeline rates (packed feed,
#      hashing, aux build) to re-check the host is still not the
#      bottleneck at the new device rate
# Everything lands in a dated dir with logs; compare against PERF.md's
# committed numbers and update the lever ranking there.
set -u
cd "$(dirname "$0")"
OUT=${1:-rebaseline_$(date -u +%Y%m%d_%H%M%S)}
mkdir -p "$OUT"
echo "rebaseline: start $(date -u) -> $OUT" | tee "$OUT/log"

run() {
  name=$1; shift
  echo "rebaseline: $name: $*" | tee -a "$OUT/log"
  timeout "$TIMEOUT" "$@" > "$OUT/$name.out" 2> "$OUT/$name.err"
  echo "rebaseline: $name rc=$? $(date -u +%H:%M:%S)" | tee -a "$OUT/log"
}

TIMEOUT=2000 run bench_sweep python bench.py --total-deadline 1800
TIMEOUT=2400 run micro_all   python bench_micro.py all
TIMEOUT=900  run input       python bench_input.py
cp MEASURED.json "$OUT/MEASURED.json" 2>/dev/null
echo "rebaseline: done $(date -u); headline line:" | tee -a "$OUT/log"
tail -1 "$OUT/bench_sweep.out" | tee -a "$OUT/log"
