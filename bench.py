"""Headline benchmark: Criteo-shaped FM training throughput on TPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}
or, if the TPU backend cannot be brought up after bounded retries, ONE
JSON line with an "error" key so the driver records a diagnosable
artifact instead of a bare traceback.

Config mirrors the north-star setting (BASELINE.json:5,9): FM rank 64,
39 fields (13 int + 26 categorical), 10.2M hashed features (39 x 262144
per-field buckets). Baseline = the driver target of 10M samples/sec on a
v5e-8 -> 1.25M samples/sec/chip; ``vs_baseline`` = measured-per-chip /
target-per-chip, so >= 1.0 beats the 8-chip target at equal per-chip rate.

What is measured: the full fused sparse-SGD train step (forward, analytic
backward -- the reference's computeGradient rule -- and in-place scatter
update) on the field-partitioned table layout (models/field_fm.py explains
the measured XLA gather/scatter cliffs that motivate it). Many steps are
rolled into one compiled ``fori_loop`` program so per-dispatch host/tunnel
overhead (~66ms on this setup) is amortized, matching production use where
the host only feeds data. Data is device-resident; the host input pipeline
is benchmarked separately by ``bench_input.py``.

Reliability design (round-2, reworked round-4): the TPU attachment on
this setup is flaky -- backend init can fail ("Unable to initialize
backend") or hang indefinitely, and a failed init poisons the process. So
the measurement runs in a CHILD process with a hard wall-clock timeout;
the parent retries with backoff on failure/hang. The child prints stage
heartbeats to stderr so a slow first compile (~20-60s) is distinguishable
from a hang.

Round-4 hardening (VERDICT r3 #1 -- round 3 ended rc=124 with NO
parseable line because the 4 x 600s retry budget exceeded the driver's
~30min kill window):
  * ``--total-deadline`` (default 1500s) bounds the WHOLE parent run,
    comfortably under the observed outer window; attempt timeouts are
    clamped to the remaining budget.
  * The child runs an init watchdog: a backend init that has not finished
    within ``--init-timeout`` (default 240s) never finishes on this
    attachment, so the child prints a provisional error JSON and exits
    early instead of burning the full attempt timeout.
  * A provisional error JSON is printed after EVERY failed attempt, so
    the final stdout line is parseable no matter where an outer kill
    lands.
  * SIGTERM/SIGINT in the parent (what ``timeout(1)`` sends) emits the
    best-so-far result line -- or the error JSON -- before exiting; the
    parent streams the child's stdout live so a mid-sweep cumulative-best
    line is salvageable at any instant.

Round-6 warm-start (ISSUE 1 — BENCH_r03..r05 all returned null because
backend init + first XLA compile outlasted the attachment's healthy
windows):
  * ``--compile-cache [DIR]`` enables jax's persistent compilation
    cache (utils/compile_cache) so a SECOND bench process deserializes
    every compiled step instead of recompiling — time-to-first-result
    drops from minutes to seconds on a warm cache.
  * ``--fast-first`` runs a TIERED sweep: leg 1 is the recorded winner
    variant (MEASURED.json), AOT-precompiled against abstract shapes
    before the tables are even initialized, and its non-provisional
    result JSON is emitted before any remaining leg starts.
  * Every completed leg streams to ``--artifacts-dir`` as it lands
    (``sweep_<model>.jsonl`` + atomically-replaced
    ``keepbest_<model>.json``), so a run killed mid-window leaves the
    best-so-far metric instead of null; a SIGTERM'd parent that
    salvaged any result line now exits 0.

Timing note: on this TPU attachment, ``block_until_ready`` returns before
execution completes; a device->host transfer of the loss is the reliable
fence, and is what we use.
"""

import argparse
import json
import os
import re
import subprocess
import sys
import threading
import time

# Per-model metric + per-chip target (--model). The tracked headline is
# the FM row (BASELINE.json:2); the FFM row exists so a chip window can
# REFRESH MEASURED.json's config-4 rate (carried from round 3 otherwise)
# with one command: `python bench.py --model ffm`.
METRICS = {
    "fm": ("criteo_fm_rank64_10Mfeat_samples_per_sec_per_chip",
           10_000_000 / 8),
    "ffm": ("avazu_ffm_rank16_samples_per_sec_per_chip", None),
    "deepfm": ("criteo_deepfm_rank16_samples_per_sec_per_chip", None),
    # Config 2 (BASELINE.json:8): FM rank-32, Criteo-Kaggle, 39x32768
    # ~= 1.28M hashed features. Its own metric so its rate can never
    # conflate with the rank-64/10M headline.
    "fm_kaggle": ("kaggle_fm_rank32_1Mfeat_samples_per_sec_per_chip",
                  None),
}
# Per-model DEFAULT rank: an explicit --rank override changes the
# program being measured, so it is stamped into the variant label
# (same provenance rule as a non-default --batch).
DEFAULT_RANK = {"fm": 64, "ffm": 16, "deepfm": 16, "fm_kaggle": 32}
# metric name -> MEASURED.json entry rewritten on a successful sweep
METRIC_ENTRY = {
    METRICS["fm"][0]: "headline",
    METRICS["ffm"][0]: "ffm_avazu",
    METRICS["deepfm"][0]: "deepfm_criteo",
    METRICS["fm_kaggle"][0]: "fm_kaggle",
}
METRIC, TARGET_PER_CHIP = METRICS["fm"]
UNIT = "samples/sec/chip"

# The run id shared by the parent and every child attempt (ISSUE 7):
# all of a run's telemetry — trace/metrics/flight streams AND the
# health_<model>.jsonl journal — lands under ONE per-run directory,
# <artifacts>/obs/<run_id>/, and the id is echoed in the result JSON
# (error lines included) so consumers can find the evidence.
_RUN_ID = None


def _gen_run_id():
    """Parent-side run-id mint (no fm_spark_tpu import: the parent must
    stay light — the package pulls jax)."""
    return time.strftime("%Y%m%d-%H%M%S", time.gmtime()) + f"-p{os.getpid()}"


def _obs_run_dir(art_dir, run_id):
    return os.path.join(art_dir, "obs", run_id)


def _renormalize_results(results, prev_chips, n_chips):
    """Re-normalize banked per-chip rates onto the surviving-chip
    denominator after an elastic shrink, so ``max()`` ranks every leg
    on comparable figures (a post-shrink leg must not win on a smaller
    divisor). Entries are ``(rate, label, dt, loss)``."""
    if prev_chips == n_chips:
        return list(results)
    return [(r * prev_chips / n_chips, label, dt, loss)
            for r, label, dt, loss in results]


def default_variants(model, batch):
    """The default sweep's staged A/B grid: ``(head, tail)`` lists of
    ``(label, (param_dtype, compute_dtype, table_layout), TrainConfig)``.

    ``head`` goes BEFORE the fp32/scatter_add reference variant, ordered
    by salvage value (a flaky attachment dying mid-sweep keeps the
    prefix): the MEASURED-BEST composed variant first (1,422,411 on
    2026-07-31 — floor-cap + gfull + segtotal, PERF.md round-5 table),
    the cap-ladder legs as the ongoing A/B, the two single-lever
    legs, the round-3 winner closing the 2x2 grid, and the secondary
    probes (devaux = the multi-chip-composable denominator; colT =
    thrice-neutral, kept for drift detection). ``tail`` goes after it
    (the dtype ladder).

    Module-level (not inlined in inner_main) so tests can pin the
    label<->TrainConfig consistency that the measurement's provenance
    depends on; imports TrainConfig lazily so the PARENT bench process
    never pulls in jax.
    """
    from fm_spark_tpu.train import TrainConfig

    # Compact capacity must bound the bench batch's max per-field unique
    # count (Zipf 1.3, seed 0: 11,990 at B=131072; 20,109 at B=262144 —
    # both under batch/10, rounded up to segtotal's 512 tile). The
    # historical 16384 stays the default-batch cap; larger batches scale
    # it, or the compact variants would die on compact_overflow='error'.
    bound = max(512, ((batch // 10) + 511) // 512 * 512)
    cap = min(max(16384, bound), batch)
    if model == "deepfm":
        # Config 5's optimizer (dense Adam head) with the measured-best
        # FM table levers (criteo-sized tables sit ABOVE the gather
        # cliffs, same as the FM headline), plus the composed-kernel
        # A/B at config 5's own shape (measured a LOSER there — narrow
        # rank-16 rows, PERF.md — kept as the drift sentinel).
        base = dict(learning_rate=1e-3, lr_schedule="constant",
                    optimizer="adam", sparse_update="dedup_sr",
                    host_dedup=True, compact_cap=cap)
        return [], [
            (f"bfloat16/dedup_sr/compact{cap}/cd-bf16",
             ("bfloat16", "bfloat16", None), TrainConfig(**base)),
            (f"bfloat16/dedup_sr/compact{cap}/cd-bf16/gfull/segtotal",
             ("bfloat16", "bfloat16", None),
             TrainConfig(**base, gfull_fused=True, segtotal_pallas=True)),
        ]
    if model == "ffm":
        # Measured winner first (816,553 on 2026-07-31): fp32 storage +
        # bf16 COMPUTE buffers + plain scatter_add — the cd-bf16 lever
        # halves the [B, F, F, k] sel-buffer traffic (FFM's dominant
        # term) while the fp32 tables keep scatter_add exact, so no
        # SR/dedup machinery is needed. NO compact variants: the
        # compact lever measured a LOSER on avazu's 24MB tables
        # (PERF.md: the tables sit under every gather cliff, so
        # cap-lane compaction only adds passes); bf16 STORAGE +
        # dedup_sr measured a 2x loser for the same reason (kept as
        # the drift sentinel).
        ffm_base = dict(learning_rate=0.05, lr_schedule="constant",
                        optimizer="sgd")
        return [
            ("float32/scatter_add/cd-bf16", ("float32", "bfloat16", None),
             TrainConfig(**ffm_base, sparse_update="scatter_add")),
            # Round-5 staged A/B (unpriced — needs a chip window): the
            # sel-blocked body never materializes the [B, F, F, k]
            # sel/dsel/dv tensors, the step's dominant HBM traffic
            # (the cd-bf16 lever, which halves exactly those bytes,
            # measured +23% — so the expected effect is of that order
            # if the step is still sel-bandwidth-bound).
            ("float32/scatter_add/cd-bf16/selblk",
             ("float32", "bfloat16", None),
             TrainConfig(**ffm_base, sparse_update="scatter_add",
                         sel_blocked=True)),
            # ISSUE 8: the sel-blocked body as Pallas kernels — the
            # [T, F, k] sel/dsel pair GUARANTEED tile-resident instead
            # of fusion-dependent (ops/pallas_fused.ffm_sel_*; bit-
            # exact fp32 vs the XLA selblk body). 'require' so a
            # no-Pallas attachment skips rather than silently pricing
            # the XLA body under this label.
            ("float32/scatter_add/cd-bf16/selblk-pallas",
             ("float32", "bfloat16", None),
             TrainConfig(**ffm_base, sparse_update="scatter_add",
                         sel_blocked=True, fused_embed="require")),
        ], [
            ("bfloat16/dedup_sr", ("bfloat16", "bfloat16", None),
             TrainConfig(**ffm_base, sparse_update="dedup_sr")),
        ]
    if model == "fm_kaggle":
        # Config 2: small tables — candidates from BOTH measured
        # regimes: the avazu winner form (bf16 compute over exact fp32
        # storage, no dedup machinery) and the criteo winner form
        # (bf16 storage + SR + compact; cap 16384 bounds the measured
        # 10,711 max per-field unique at B=131072). The on-chip sweep
        # decides; fp32/scatter_add is the reference variant between
        # head and tail.
        kbase = dict(learning_rate=0.05, lr_schedule="constant",
                     optimizer="sgd")
        return [
            ("float32/scatter_add/cd-bf16", ("float32", "bfloat16", None),
             TrainConfig(**kbase, sparse_update="scatter_add")),
            (f"bfloat16/dedup_sr/compact{cap}/cd-bf16",
             ("bfloat16", "bfloat16", None),
             TrainConfig(**kbase, sparse_update="dedup_sr",
                         host_dedup=True, compact_cap=cap)),
        ], [
            ("bfloat16/dedup_sr", ("bfloat16", "bfloat16", None),
             TrainConfig(**kbase, sparse_update="dedup_sr")),
        ]
    # FM headline (PERF.md "the compact lever": scatter cost is
    # per-lane even for dropped lanes, so cap-lane compaction wins; cap
    # 16384 bounds the measured max per-field unique count (~12k) on
    # the bench's Zipf batch).
    base = dict(learning_rate=0.05, lr_schedule="constant",
                optimizer="sgd", sparse_update="dedup_sr",
                host_dedup=True, compact_cap=cap)
    # Tight-cap measured a WINNER (2026-07-31 on-chip A/B: 1,398,617 at
    # cap 13312 vs 1,383,925 at 16384, +1.1% — the ~19% cap-lane
    # shrinkage priced across the gather/expand/scatter/segtotal
    # passes), so the tight composed variant now runs FIRST (salvage
    # order = measured best first) with the historical cap as the
    # ongoing A/B leg. The bound is MEASURED only at 131072 and 262144;
    # at other batches a too-tight cap makes the aux build raise
    # CompactCapOverflow, which the sweep's per-variant guard turns
    # into a logged skip (not a sweep abort).
    tight = min(bound, cap)
    # MEASURED WINNER (1,422,411 = 1.138x, 2026-07-31): cap 12288 = the
    # bench batch's measured max per-field unique (11,990 at Zipf 1.3,
    # seed 0) rounded to segtotal's 512 tile — the FLOOR of the cap
    # lever. The one-window cap ladder: 16384 -> 1.387M (+1.5%) ->
    # 13312 -> 1.407M (+1.1%) -> 12288 -> 1.422M. The floor is only
    # KNOWN at the measured batch; anywhere else floor_cap falls back
    # to the formula cap (otherwise an overflowing cap would just
    # waste the slot: the host-aux probe raises CompactCapOverflow at
    # build, and a compact-device leg poisons its loss to -inf — both
    # now skipped, never priced). One definition so the probe and
    # devaux legs can never measure different caps.
    floor_cap = 12288 if batch == 1 << 17 else cap
    ranked = []
    if floor_cap < tight:
        ranked.append(
            (f"bfloat16/dedup_sr/compact{floor_cap}/cd-bf16/gfull"
             "/segtotal",
             dict(compact_cap=floor_cap, gfull_fused=True,
                  segtotal_pallas=True), None))
    if tight < cap:
        ranked.append(
            (f"bfloat16/dedup_sr/compact{tight}/cd-bf16/gfull/segtotal",
             dict(compact_cap=tight, gfull_fused=True,
                  segtotal_pallas=True), None))
    ranked += [
        (f"bfloat16/dedup_sr/compact{cap}/cd-bf16/gfull/segtotal",
         dict(gfull_fused=True, segtotal_pallas=True), None),
    ]
    # Fused Pallas backward (ISSUE 8, ROADMAP item 4): the challenger
    # for the sel/dsel/dv HBM traffic the round-5 cd-bf16 probe priced
    # at +23% — g_full rebuilt on-chip from the sorted scalar streams +
    # the VMEM-resident urows block and segment-summed in the SAME
    # kernel, subsuming gfull+segtotal for the update stage. Staged
    # right after the composed winners (the round-5 selblk pattern):
    # a dying window prices the incumbent first, the challenger next.
    # fused_embed='require' so an attachment that cannot serve the
    # kernel SKIPS the leg (construction raises PallasUnavailable, the
    # per-variant guard logs it) instead of silently measuring the XLA
    # path under a fused label — the fallback-never-keep-bests rule.
    ranked.insert(1, (
        f"bfloat16/dedup_sr/compact{floor_cap}/cd-bf16/fusedbwd",
        dict(compact_cap=floor_cap, fused_embed="require"), None))
    ranked += [
        (f"bfloat16/dedup_sr/compact{cap}/cd-bf16/gfull",
         dict(gfull_fused=True), None),
        (f"bfloat16/dedup_sr/compact{cap}/cd-bf16/segtotal",
         dict(segtotal_pallas=True), None),
        (f"bfloat16/dedup_sr/compact{cap}/cd-bf16", {}, None),
        # devaux = the multi-chip-composable denominator (in-step aux
        # build; the only compact form that composes with scale-out —
        # PERF.md round 3). Measured at the floor cap WITH the composed
        # kernels so the multi-chip projection's discount is priced
        # against the same lever stack as the headline, not the bare
        # cd-bf16 base.
        (f"bfloat16/dedup_sr/compact{floor_cap}/devaux/cd-bf16"
         "/gfull/segtotal",
         dict(host_dedup=False, compact_device=True,
              compact_cap=floor_cap,
              gfull_fused=True, segtotal_pallas=True), None),
        (f"bfloat16/dedup_sr/compact{cap}/cd-bf16/colT", {}, "col"),
    ]
    head = [
        (label, ("bfloat16", "bfloat16", layout),
         TrainConfig(**{**base, **extra}))
        for label, extra, layout in ranked
    ]
    tail = [
        (f"{dt}/{su}/compact{cap}", (dt, None, None),
         TrainConfig(learning_rate=0.05, lr_schedule="constant",
                     optimizer="sgd", sparse_update=su,
                     host_dedup=True, compact_cap=cap))
        for su, dt in (("dedup", "float32"), ("dedup_sr", "bfloat16"))
    ]
    return head, tail


def _set_model(model: str) -> None:
    global METRIC, TARGET_PER_CHIP
    METRIC, TARGET_PER_CHIP = METRICS[model]


def _artifacts_dir(args) -> str:
    """Where the incremental sweep artifacts land (``--artifacts-dir``,
    default ``artifacts/`` next to this script)."""
    d = args.artifacts_dir or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "artifacts"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _persist_incremental(dirpath, model, best_payload, leg_record):
    """Persist the sweep's state AS IT LANDS (warm-start tiering, ISSUE
    1): append this leg's measurement to ``sweep_<model>.jsonl`` and
    atomically replace ``keepbest_<model>.json`` with the cumulative
    best — so a bench killed mid-window (flaky attachment, outer
    timeout) leaves the best-so-far metric on disk instead of nothing.
    Best-effort by contract: persistence must never kill the sweep."""
    try:
        with open(os.path.join(dirpath, f"sweep_{model}.jsonl"), "a") as f:
            f.write(json.dumps(leg_record) + "\n")
        tmp = os.path.join(dirpath, f".keepbest_{model}.tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps(best_payload) + "\n")
        os.replace(tmp, os.path.join(dirpath, f"keepbest_{model}.json"))
    except OSError as e:
        _log(f"[inner] incremental artifact write failed: {e!r}")


def _completed_legs(art_dir, model, labels, device_kind,
                    since: float = 0.0):
    """``--resume-sweep`` support: variant-label → last completed leg
    record from this model's ``sweep_<model>.jsonl``. Filtered to (a)
    labels in THIS sweep's grid — a changed grid or shape stamp
    re-measures, it never resumes a stale label; (b) records measured
    on THIS device kind — a CPU smoke sweep's rates must never ride a
    resume into an on-chip payload (where the keep-best path could
    stamp them TPU); (c) records stamped at/after ``since`` — the
    parent's own-start filter for auto-resume on retry, without which a
    retry would "resume" legs measured in a prior round's window.
    Best-effort: an unreadable artifact just means a full re-measure."""
    path = os.path.join(art_dir, f"sweep_{model}.jsonl")
    out = {}
    try:
        with open(path) as f:
            for line in f:
                # Per-record guard: one malformed record (ts: null, a
                # bool value, a non-dict line) skips that record, never
                # the whole resume — degraded artifacts are exactly
                # this path's operating condition.
                try:
                    rec = json.loads(line)
                    v = rec.get("value")
                    if (isinstance(v, bool)
                            or not (isinstance(v, (int, float)) and v > 0)):
                        continue
                    if rec.get("variant") not in labels:
                        continue
                    if rec.get("device") != device_kind:
                        continue
                    if float(rec.get("ts") or 0.0) < since:
                        continue
                    if rec.get("degraded"):
                        # A shrunk-denominator salvage rate must not
                        # ride a resume into an undegraded payload —
                        # the restarted process may have full capacity
                        # back, so the leg is simply re-measured.
                        continue
                    out[rec["variant"]] = rec
                except (AttributeError, TypeError, ValueError):
                    continue
    except OSError:
        pass
    return out


def _recorded_winner(metric: str):
    """The measured-best variant label recorded for this metric in
    MEASURED.json, or None — the fast-first tier measures it FIRST so
    the highest-value leg is in the can before the sweep's A/B legs
    start."""
    try:
        from fm_spark_tpu.measured import load_measured

        entry = METRIC_ENTRY.get(metric)
        return load_measured()[entry]["variant"] if entry else None
    except Exception:
        return None


def _log(msg):
    print(f"bench: {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Child: the actual measurement. Runs in its own process so a hung/poisoned
# backend init can be killed and retried by the parent.
# --------------------------------------------------------------------------

def _last_measured_block():
    """The best PREVIOUSLY recorded on-chip rate for the current metric
    (MEASURED.json), provenance-stamped and marked stale — attached to
    every error JSON so even a dead-attachment round transports the
    best-known headline machine-readably (VERDICT r5 next-round #1)
    instead of a bare null. None when no record exists; best-effort by
    the final-line contract (an unreadable MEASURED.json must not break
    error emission)."""
    try:
        from fm_spark_tpu.measured import load_measured

        entry = METRIC_ENTRY.get(METRIC)
        if entry is None:
            return None
        rec = load_measured().get(entry)
        if rec is None:
            return None
        return {
            "value": rec["rate_samples_per_sec_per_chip"],
            "unit": UNIT,
            "vs_baseline": rec.get("vs_baseline"),
            "variant": rec.get("variant"),
            "attachment": rec.get("attachment"),
            "date": rec.get("date"),
            "source": rec.get("source"),
            "stale": True,
            "provenance": "MEASURED.json keep-best record — NOT this "
                          "round's measurement",
        }
    except Exception:
        return None


def _error_line(msg, permanent=None):
    payload = {
        "metric": METRIC, "value": None, "unit": UNIT,
        "vs_baseline": None, "error": msg,
    }
    if _RUN_ID:
        payload["run_id"] = _RUN_ID
    if permanent:
        # The parent's fault classifier concluded the attachment is
        # DEAD (N identical consecutive failures), not flapping —
        # downstream consumers should reschedule, not retry.
        payload["permanent"] = True
    last = _last_measured_block()
    if last is not None:
        payload["last_measured"] = last
    return json.dumps(payload)


def _classify_diags(diags, threshold=3):
    """Transient-vs-permanent verdict over the parent's child-failure
    diagnostics (resilience/elastic.py's classifier; lazy import so the
    happy path never pays it, best-effort so classification can never
    break the final-line contract)."""
    try:
        from fm_spark_tpu.resilience.elastic import classify_failures

        return classify_failures(diags, threshold)
    except Exception:
        return "transient"


def _dirty_input_leg(art_dir, model, log):
    """Hardened-ingest leg (ISSUE 5, ``--dirty-input``): stream a
    synthetic 3-shard Criteo-shaped dataset with deterministically
    corrupted mid-shard lines through the quarantine policy and measure
    the host-side ingest rate. Host-only (no device involvement) and
    cheap, so it runs before the sweep and its stats land in the result
    JSON even when the attachment later dies: ``bad_records`` is the
    dead-lettered count and ``quarantine_exact`` asserts it equals the
    injected corruption — the bench-level witness that dirty input
    degrades to quarantine accounting instead of a crash or silent
    noise."""
    import shutil
    import tempfile

    import numpy as np

    from fm_spark_tpu.data import criteo
    from fm_spark_tpu.data.stream import RecordGuard, ShardReader

    tmp = tempfile.mkdtemp(prefix="fm_dirty_")
    try:
        rng = np.random.default_rng(0)
        paths = []
        n_per, n_shards, injected = 2000, 3, 0
        for s in range(n_shards):
            p = os.path.join(tmp, f"shard{s}.tsv")
            criteo.synthesize_tsv(p, n_per, seed=s)
            with open(p, "rb") as f:
                lines = f.read().splitlines(keepends=True)
            # Flip bytes mid-shard: ~1% of lines, deterministic.
            for k in rng.choice(np.arange(10, n_per - 10),
                                size=n_per // 100, replace=False):
                lines[int(k)] = b"\x00corrupt\t" + lines[int(k)][:9] + b"\n"
                injected += 1
            with open(p, "wb") as f:
                f.write(b"".join(lines))
            paths.append(p)
        bucket = 1 << 14
        total = n_shards * n_per

        def _run(native_ingest, qdir):
            """One full pass under quarantine; returns (guard, dt)."""
            from fm_spark_tpu.data.native_stream import make_stream_batches

            shutil.rmtree(qdir, ignore_errors=True)
            guard = RecordGuard("quarantine", quarantine_dir=qdir,
                                max_bad_frac=0.5)
            batches = make_stream_batches(
                ShardReader(paths), "criteo", 512, criteo.NUM_FIELDS,
                guard=guard, num_features=criteo.NUM_FIELDS * bucket,
                bucket=bucket,
                native_ingest=native_ingest,
            )
            t0 = time.perf_counter()
            while guard.n_ok + guard.n_bad < total:
                batches.next_batch()
            return guard, time.perf_counter() - t0

        # Priced BOTH ways (ISSUE 6): the per-line Python parser and the
        # native chunk parser run the same dirty pass with identical
        # quarantine semantics — the result JSON carries both rates so
        # the native win (and any accounting drift) stays attributable.
        guard, dt = _run(False, os.path.join(art_dir, f"quarantine_{model}"))
        stats = {
            "rows": total,
            "bad_records": guard.n_bad,
            "injected_bad": injected,
            "quarantine_exact": guard.n_bad == injected,
            "rows_per_sec": round(total / dt, 1),
            "policy": "quarantine",
        }
        log(f"[inner] [dirty-input] {total} rows in {dt:.2f}s "
            f"({stats['rows_per_sec']:,.0f} rows/sec, python parse); "
            f"{guard.n_bad}/{injected} corrupt lines quarantined")
        from fm_spark_tpu.data.native_stream import native_stream_supported

        if native_stream_supported("criteo", criteo.NUM_FIELDS, bucket):
            nguard, ndt = _run(
                "auto", os.path.join(art_dir, f"quarantine_{model}_native"))
            stats["rows_per_sec_native"] = round(total / ndt, 1)
            stats["native_quarantine_exact"] = nguard.n_bad == injected
            stats["native_counters_match"] = (
                nguard.counters() == guard.counters())
            log(f"[inner] [dirty-input] {total} rows in {ndt:.2f}s "
                f"({stats['rows_per_sec_native']:,.0f} rows/sec, native "
                f"chunk parse); {nguard.n_bad}/{injected} quarantined, "
                f"counters match: {stats['native_counters_match']}")
        return stats
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def inner_main(args):
    t_start = time.perf_counter()
    _log("[inner] importing jax + initializing backend "
         "(a hang here = flaky TPU attachment)...")

    # Resilience wiring (ISSUE 2): the health-event journal + the
    # supervisor/fault machinery arm BEFORE the backend touch, so
    # init-path failures are journaled and deterministically injectable
    # (the package import pulls fm_spark_tpu, and thus jax — which this
    # child is about to import anyway; backend INIT still happens only
    # at the jax.devices() below).
    from fm_spark_tpu.resilience import (
        BackoffPolicy,
        CircuitOpen,
        RetriesExhausted,
        Supervisor,
        faults,
        is_device_loss,
    )
    from fm_spark_tpu import obs
    from fm_spark_tpu.utils.logging import EventLog

    art_dir = _artifacts_dir(args)
    # Per-run telemetry directory (ISSUE 7): every stream this run
    # emits — spans, metrics snapshots, the flight-recorder window, and
    # the health journal — lives under <artifacts>/obs/<run_id>/. The
    # parent mints the run id and passes it down so retried attempts
    # append to the SAME run (journal included), and the id is echoed
    # in every result line.
    global _RUN_ID
    run_id = _RUN_ID = args.run_id or _gen_run_id()
    obs_dir = _obs_run_dir(art_dir, run_id)
    obs.configure(obs_dir, run_id=run_id, install_signals=True)
    # Live introspection (ISSUE 14): the capture engine arms over this
    # run dir — a sentinel `regressed` verdict on any leg below fires a
    # bounded capture bundle while the slow program is still resident —
    # and --metrics-port serves the live registry while the sweep runs.
    from fm_spark_tpu.obs import introspect

    introspect.configure(obs_dir, run_id=run_id)
    if args.metrics_port is not None:
        from fm_spark_tpu.obs import export as obs_export

        _msrv = obs_export.start_metrics_server(args.metrics_port)
        print(json.dumps({"metrics_port": _msrv.port,
                          "metrics_url": _msrv.url}), flush=True)
    journal = EventLog(os.path.join(obs_dir,
                                    f"health_{args.model}.jsonl"),
                       mirror_to_flight=True)
    journal.emit("backend_init_start", model=args.model)

    # Init watchdog: on this attachment an init that has not completed in
    # ~4 minutes never completes; exiting early lets the parent retry
    # within its total deadline instead of burning the full attempt
    # timeout on a known-dead hang.
    init_done = threading.Event()

    def _init_watchdog():
        if not init_done.wait(args.init_timeout):
            journal.emit("backend_init_timeout",
                         timeout_s=args.init_timeout)
            print(_error_line(
                f"backend init exceeded {args.init_timeout:.0f}s "
                "(init watchdog; flaky TPU attachment)"), flush=True)
            _log(f"[inner] init watchdog fired at {args.init_timeout:.0f}s"
                 " -- exiting for parent retry")
            os._exit(3)

    threading.Thread(target=_init_watchdog, daemon=True).start()
    # The injected init faults (hang / exit:3) fire HERE — after the
    # watchdog arms, before the real backend touch — reproducing the
    # observed attachment failure modes on any backend (faults.py).
    faults.inject("backend_init")
    import jax

    # Honor an explicit cpu request (CI / smoke tests): config pin + axon
    # factory drop, same guard as cli.main and __graft_entry__.
    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()

    # Warm-start (ISSUE 1): the persistent compile cache turns the
    # second process's minutes of XLA compilation into a disk read —
    # enable BEFORE the first compile. --compile-cache DIR / bare flag
    # for the repo-local default; FM_SPARK_COMPILE_CACHE without the
    # flag.
    from fm_spark_tpu.utils import compile_cache

    if args.compile_cache is not None:
        cache_dir = compile_cache.enable(args.compile_cache or None)
        _log(f"[inner] persistent compile cache at {cache_dir}")
    elif compile_cache.enable_from_env():
        _log("[inner] persistent compile cache from env: "
             f"{compile_cache.cache_stats()['dir']}")

    import jax.numpy as jnp
    from jax import lax

    devs = jax.devices()  # forces backend init
    init_done.set()
    journal.emit("backend_init_up",
                 seconds=round(time.perf_counter() - t_start, 1),
                 devices=len(devs), kind=devs[0].device_kind)
    _log(f"[inner] backend up in {time.perf_counter() - t_start:.1f}s: "
         f"{len(devs)} x {devs[0].device_kind}")

    # Perf provenance (ISSUE 9): every completed leg is appended to the
    # cross-run ledger (artifacts/obs/ledger.jsonl) with a measurement
    # fingerprint — lever-config hash, chip kind + count, jax/libtpu
    # versions, degraded/fused_fallback stamps, and the supervisor-
    # journal attachment-health verdict — and judged by the noise-aware
    # sentinel against its (leg, fingerprint) cohort history BEFORE the
    # record lands. The verdict rides the leg record, the result JSON,
    # and (via the parent's keep-best gate) the MEASURED.json decision.
    from fm_spark_tpu.obs.ledger import runtime_versions

    ledger = obs.PerfLedger(obs.default_ledger_path(art_dir))
    sentinel = obs.Sentinel(ledger)
    _versions = runtime_versions()

    from fm_spark_tpu import models
    from fm_spark_tpu.sparse import (
        make_field_deepfm_sparse_body,
        make_field_ffm_sparse_sgd_body,
        make_field_sparse_sgd_body,
    )
    from fm_spark_tpu.train import TrainConfig

    import numpy as np

    _set_model(args.model)
    if args.model == "ffm":
        # Config 4's shape (configs.avazu_ffm_r16): 23 fields, 16384
        # per-field buckets, rank 16.
        num_fields, bucket = 23, 1 << 14
        rank = args.rank or DEFAULT_RANK["ffm"]
        if args.table_layout != "row":
            raise SystemExit("--table-layout col is a FieldFM lever")
    elif args.model == "deepfm":
        # Config 5's shape (configs.criteo1tb_deepfm): 39 fields,
        # 262144 buckets, rank 16, 3x400 MLP head on dense Adam.
        num_fields, bucket = 39, 1 << 18
        rank = args.rank or DEFAULT_RANK["deepfm"]
        if args.table_layout != "row":
            raise SystemExit("--table-layout col is a FieldFM lever")
    elif args.model == "fm_kaggle":
        # Config 2's shape (configs.criteo_kaggle_fm_r32): 39 fields,
        # 32768 per-field buckets, rank 32 — per-field tables are SMALL
        # (2.1MB bf16), so the avazu small-table lesson applies and the
        # grid stages the cd-bf16-over-fp32 candidate first.
        num_fields, bucket = 39, 1 << 15
        rank = args.rank or DEFAULT_RANK["fm_kaggle"]
    else:
        num_fields, bucket = 39, 262_144
        rank = args.rank or DEFAULT_RANK["fm"]
    batch = args.batch
    steps_warmup = 3
    steps_timed = args.steps

    def make_spec(param_dtype, compute_dtype=None, table_layout=None):
        if args.model == "ffm":
            return models.FieldFFMSpec(
                num_features=num_fields * bucket, rank=rank,
                num_fields=num_fields, bucket=bucket, init_std=0.01,
                param_dtype=param_dtype,
                compute_dtype=compute_dtype or args.compute_dtype,
            )
        if args.model == "deepfm":
            return models.FieldDeepFMSpec(
                num_features=num_fields * bucket, rank=rank,
                num_fields=num_fields, bucket=bucket, init_std=0.01,
                mlp_dims=(400, 400, 400),
                param_dtype=param_dtype,
                compute_dtype=compute_dtype or args.compute_dtype,
            )
        return models.FieldFMSpec(
            num_features=num_fields * bucket, rank=rank,
            num_fields=num_fields, bucket=bucket, init_std=0.01,
            param_dtype=param_dtype,
            compute_dtype=compute_dtype or args.compute_dtype,
            table_layout=table_layout or args.table_layout,
        )

    rng = np.random.default_rng(0)
    # Criteo-like Zipf skew within each field's bucket.
    ids_np = (rng.zipf(1.3, size=(batch, num_fields)) % bucket).astype(np.int32)
    ids = jnp.asarray(ids_np)
    vals = jnp.ones((batch, num_fields), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, batch), jnp.float32)
    weights = jnp.ones((batch,), jnp.float32)

    # Variant sweep: with explicit knobs, measure exactly what was asked;
    # with pure defaults, ALSO measure the host-dedup candidate (PERF.md
    # round-3 lever) and report the fastest — the headline is "the
    # framework's best configuration", decided by measurement, not by a
    # default frozen before the chip could confirm it.
    lever_explicit = (args.sparse_update != "scatter_add"
                      or args.use_pallas
                      or args.host_dedup or args.param_dtype != "float32"
                      or args.compute_dtype != "float32"
                      or args.table_layout != "row"
                      or args.compact_cap
                      or args.compact_device or args.gfull_fused
                      or args.segtotal_pallas
                      or args.fused_embed != "off"
                      or args.embed_tier != "off")
    shape_explicit = (args.rank is not None or args.batch != 1 << 17
                      or args.steps != 20)
    # --fast-first keeps the tiered variant sweep even at a non-default
    # SHAPE (batch/steps/rank only change what one leg measures — the
    # stamp below keeps the provenance honest); explicit LEVER knobs
    # still mean "measure exactly this one program".
    explicit = lever_explicit or (shape_explicit and not args.fast_first)
    variants = [(
        f"{args.param_dtype}/{args.sparse_update}"
        + ("/pallas" if args.use_pallas else "")
        + (f"/compact{args.compact_cap}" if args.compact_cap
           else "/hostdedup" if args.host_dedup else "")
        + ("/devaux" if args.compact_device else "")
        + ("/cd-bf16" if args.compute_dtype == "bfloat16" else "")
        + ("/colT" if args.table_layout == "col" else "")
        + ("/gfull" if args.gfull_fused else "")
        + ("/segtotal" if args.segtotal_pallas else "")
        + (f"/fused-{args.fused_embed}" if args.fused_embed != "off"
           else "")
        + (f"/tier-{args.embed_tier}" if args.embed_tier != "off"
           else ""),
        (args.param_dtype, None, None),
        TrainConfig(learning_rate=0.05, lr_schedule="constant",
                    optimizer="sgd", sparse_update=args.sparse_update,
                    use_pallas=args.use_pallas, host_dedup=args.host_dedup,
                    compact_cap=args.compact_cap,
                    compact_device=args.compact_device,
                    gfull_fused=args.gfull_fused,
                    segtotal_pallas=args.segtotal_pallas,
                    fused_embed=args.fused_embed,
                    embed_tier=args.embed_tier, hot_rows=args.hot_rows,
                    embed_bucket_rows=args.embed_bucket_rows),
    )]
    if not explicit:
        head, tail = default_variants(args.model, batch)
        variants[0:0] = head
        variants.extend(tail)
        if args.fast_first:
            # Tier 1 = the RECORDED winner (MEASURED.json), measured
            # before any A/B leg: with a warm compile cache its result
            # JSON lands in seconds, so even a window that dies right
            # after still beats a null artifact. The head is already
            # ranked best-first, so this only reorders when the record
            # disagrees with the static ranking.
            rec = _recorded_winner(METRIC)
            idx = next((i for i, (l, _, _) in enumerate(variants)
                        if l == rec), None)
            if idx:
                variants.insert(0, variants.pop(idx))
            _log(f"[inner] fast-first: leg 1 = "
                 f"{variants[0][0]!r}"
                 + (f" (recorded winner)" if idx is not None else
                    " (ranked head; no recorded winner in sweep)"))

    # Batch and rank are part of a rate's provenance (a doubled batch
    # amortizes fixed per-step work; a different rank is a different
    # program entirely), so non-default values are stamped into every
    # label and such rates can never keep-best into MEASURED.json
    # (comparable_variant below).
    stamp = ""
    if args.batch != 1 << 17:
        stamp += f"/b{args.batch}"
    if args.rank is not None and args.rank != DEFAULT_RANK[args.model]:
        stamp += f"/r{args.rank}"
    if stamp:
        variants = [(f"{label}{stamp}", dtypes, config)
                    for label, dtypes, config in variants]

    import functools

    aux_cache = {}

    def build_variant(dtypes, config):
        spec = make_spec(*dtypes)
        init_opt = None
        if args.model == "ffm":
            body = make_field_ffm_sparse_sgd_body(spec, config)
        elif args.model == "deepfm":
            body, init_opt = make_field_deepfm_sparse_body(spec, config)
        else:
            body = make_field_sparse_sgd_body(spec, config)
        aux = None
        if config.host_dedup:
            # Aux for the (fixed) bench batch is computed once here; in
            # production it rides the prefetch thread (DedupAuxBatches) —
            # bench_input.py --host-dedup measures that host-side rate.
            akey = config.compact_cap  # 0 = full-B dedup aux
            if akey not in aux_cache:
                from fm_spark_tpu.ops.scatter import compact_aux, dedup_aux

                aux_cache[akey] = jax.device_put(
                    compact_aux(ids_np, akey) if akey else dedup_aux(ids_np)
                )
            aux = aux_cache[akey]
        return spec, init_opt, body, aux

    # Per-leg supervision (ISSUE 2): a transient device loss mid-leg is
    # retried with bounded backoff instead of forfeiting the leg; the
    # circuit breaker abandons the REMAINING legs when the attachment
    # keeps dying (salvaging completed measurements beats burning the
    # deadline re-crashing), and every transition lands in the health
    # journal next to the sweep artifacts.
    sup = Supervisor(
        policy=BackoffPolicy(initial=2.0, multiplier=2.0, max_delay=30.0,
                             max_attempts=3),
        journal=journal, breaker_threshold=3,
    )
    # Elastic degraded mode (ISSUE 4): when a leg's retries exhaust on a
    # PERMANENT fault (identical consecutive device losses — dead
    # capacity, not a flap), shed chips instead of abandoning the sweep:
    # the controller halves the device set, the breaker re-arms, the leg
    # re-runs, and every subsequent rate is normalized per SURVIVING
    # chip with the payload stamped degraded — a measured result on a
    # shrunk mesh instead of an error-only artifact.
    elastic = None
    if args.elastic:
        from fm_spark_tpu.resilience import ElasticController

        elastic = ElasticController(devices=devs,
                                    max_shrinks=args.max_shrinks,
                                    journal=journal)
    n_chips = len(devs)

    dirty_stats = None
    if args.dirty_input:
        # Best-effort: a broken dirty leg must not forfeit the device
        # sweep (the mirror of the per-variant guards below).
        try:
            dirty_stats = _dirty_input_leg(art_dir, args.model, _log)
            journal.emit("dirty_input_leg", **dirty_stats)
        except Exception as e:  # noqa: BLE001 — diagnosable, not fatal
            _log(f"[inner] [dirty-input] FAILED ({type(e).__name__}): "
                 f"{(str(e).splitlines() or [''])[0][:200]}")

    t_first_result = None  # wall-clock to the FIRST emitted result
    results = []
    # Per-label sentinel verdict blocks (resumed legs reload theirs
    # from the sweep artifact) — what emit_best stamps into the
    # payload's sentinel/all_verdicts fields.
    leg_verdicts = {}
    # Labels whose fused_embed='auto' resolved to the XLA path (ISSUE
    # 8): the rate is a valid XLA measurement, but its provenance says
    # "fused requested, not served" — stamped into the leg record and
    # the payload so the parent's keep-best gate can refuse it.
    fused_fallback_legs = set()
    resumed = {}
    if args.resume_sweep:
        resumed = _completed_legs(
            art_dir, args.model, {l for l, _, _ in variants},
            device_kind=devs[0].device_kind, since=args.resume_since,
        )

    def emit_best():
        """Print the cumulative-best result line (the parent's salvage
        scan takes the LAST one) and return the payload."""
        nonlocal t_first_result
        if t_first_result is None:
            t_first_result = round(time.perf_counter() - t_start, 1)
        best_rate, best_label, _, _ = max(results)
        payload = {
            "metric": METRIC,
            "value": round(best_rate, 1),
            "unit": UNIT,
            "vs_baseline": (round(best_rate / TARGET_PER_CHIP, 4)
                            if TARGET_PER_CHIP else None),
            "variant": best_label,
            "device": devs[0].device_kind,
            "all_variants": {l: round(r, 1) for r, l, _, _ in results},
            "legs_completed": len(results),
            "t_first_result_s": t_first_result,
            "run_id": run_id,
            # Step-time percentiles (per-leg mean step times), ingest
            # rate/accounting, fault timeline — the substrate ROADMAP
            # items 1/3/5 read their numbers from (ISSUE 7).
            "telemetry": obs.telemetry_block(),
        }
        # Sentinel stamps (ISSUE 9): the promoted leg's full verdict
        # block — the parent's keep-best gate refuses anything but
        # improved/flat — plus the per-leg verdict map.
        if best_label in leg_verdicts:
            payload["sentinel"] = leg_verdicts[best_label]
        payload["all_verdicts"] = {
            label: (block or {}).get("verdict")
            for label, block in leg_verdicts.items()
        }
        if resumed:
            payload["resumed_legs"] = len(resumed)
        if dirty_stats is not None:
            # The dirty-input leg's quarantine accounting rides the
            # result JSON (ISSUE 5): bad_records = dead-lettered count.
            payload["dirty_input"] = dirty_stats
            payload["bad_records"] = dirty_stats["bad_records"]
        if elastic is not None and elastic.degraded:
            # A shrunk-mesh rate must never masquerade as a full-mesh
            # one: stamp the degraded provenance (chips = the surviving
            # count the per-chip rate is normalized to).
            payload.update(elastic.summary())
        if best_label in fused_fallback_legs:
            # A fused-requested leg that ran the XLA path must never
            # become the recorded keep-best under its fused label
            # (ISSUE 8); the parent's _emit_final gate refuses this
            # stamp exactly like a degraded one.
            payload["fused_fallback"] = True
        if args.chaos:
            # A chaos-drill rate measured a run under injected faults
            # (ISSUE 10) — its own cohort, never the recorded
            # capability; the parent's _emit_final gate refuses it.
            payload["chaos"] = True
        print(json.dumps(payload), flush=True)
        return payload

    if resumed:
        # --resume-sweep: completed legs from the persisted sweep
        # artifact seed the results, and the best-so-far line is emitted
        # BEFORE any remaining leg runs — a restart after a mid-window
        # kill re-enters through the warm compile cache and is
        # salvageable from its first second, without re-measuring what
        # already landed.
        for label, rec in resumed.items():
            dt_banked = float(rec.get("dt_s", 0.0))
            results.append((float(rec["value"]), label,
                            dt_banked, float(rec.get("loss", 0.0))))
            if rec.get("fused_fallback"):
                fused_fallback_legs.add(label)
            if rec.get("sentinel"):
                # The banked leg was already judged (and ledgered) by
                # the attempt that measured it — re-observing would
                # double-count it in its own cohort history.
                leg_verdicts[label] = dict(rec["sentinel"],
                                           resumed=True)
            # Banked legs still belong in the telemetry percentiles:
            # obs.configure reset the registry for this attempt, so
            # without replaying the banked per-leg mean the final
            # telemetry block would cover only re-measured legs.
            if dt_banked > 0:
                obs.histogram("step_time_ms").observe(
                    dt_banked / steps_timed * 1e3)
        remaining = sum(1 for l, _, _ in variants if l not in resumed)
        _log(f"[inner] --resume-sweep: {len(resumed)} completed leg(s) "
             f"loaded from the sweep artifact; {remaining} remaining")
        journal.emit("resume_sweep", resumed_legs=len(resumed),
                     remaining_legs=remaining)
        emit_best()

    for label, dtypes, config in variants:
        if label in resumed:
            _log(f"[inner] [{label}] resumed from sweep artifact "
                 f"({resumed[label]['value']:,.1f} {UNIT}) -- skipping")
            continue
        # Everything variant-specific — INCLUDING the host aux build,
        # whose CompactCapOverflow is exactly the failure a staged
        # tight-cap variant can hit at an unmeasured batch — sits inside
        # one guard so a broken variant is skipped, not sweep-fatal.
        try:
            spec, init_opt, body, aux = build_variant(dtypes, config)
        except Exception as e:  # noqa: BLE001 — same rationale as the
            # warmup/timing guard below
            _log(f"[inner] [{label}] construction FAILED "
                 f"({type(e).__name__}): "
                 f"{(str(e).splitlines() or [''])[0][:200]}"
                 " -- skipping variant")
            continue
        if config.fused_embed == "auto":
            # The 'auto' lever's fallback is queryable, never silent
            # (ISSUE 8): resolve the plan ONCE here and stamp the leg
            # when the XLA path is what actually runs.
            from fm_spark_tpu.sparse import fused_embed_plan

            fam, fb_reason = fused_embed_plan(spec, config)
            if fam is None:
                fused_fallback_legs.add(label)
                _log(f"[inner] [{label}] fused-embed XLA fallback "
                     f"({fb_reason}) -- leg will never keep-best")
        # n_steps is a DYNAMIC argument so the warmup call compiles the
        # exact program the timed call runs (a static count would
        # recompile inside the timed region). DeepFM threads its dense
        # optax state through the carry (same shape as the multistep
        # roll); the other models carry (params, loss) only.
        if init_opt is not None:
            # (params, opt, loss) carry; params + opt donated.
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def run_df(params, opt, ids, vals, labels, weights, aux,
                       n_steps, body=body):
                def fbody(i, carry):
                    p, o, _ = carry
                    return body(p, o, i, ids, vals, labels, weights, aux)

                return lax.fori_loop(0, n_steps, fbody,
                                     (params, opt, jnp.float32(0)))

            jit_fn = run_df

            def run(carry, *a):
                return run_df(carry[0], carry[1], *a)
        else:
            # (params, loss) carry; params donated.
            @functools.partial(jax.jit, donate_argnums=(0,))
            def run_pl(params, ids, vals, labels, weights, aux, n_steps,
                       body=body):
                def fbody(i, carry):
                    p, _ = carry
                    return body(p, i, ids, vals, labels, weights, aux)

                return lax.fori_loop(0, n_steps, fbody,
                                     (params, jnp.float32(0)))

            jit_fn = run_pl

            def run(carry, *a):
                return run_pl(carry[0], *a)

        if args.fast_first and not results and compile_cache.is_enabled():
            # AOT warm-start: lower + compile leg 1's program against
            # ABSTRACT shapes before the multi-GB tables are even
            # initialized — on a warm cache this is a deserialize (the
            # whole point: the healthy window starts MEASURING in
            # seconds); on a cold one it populates the cache for every
            # later process. The later run() call re-traces but its XLA
            # compile hits the same cache entry. Skipped when the cache
            # is off (the work would be thrown away) and best-effort:
            # an AOT failure must not cost the leg.
            try:
                from fm_spark_tpu.sparse import abstract_field_batch

                t_aot = time.perf_counter()
                sds = jax.ShapeDtypeStruct
                params_abs = jax.eval_shape(spec.init, jax.random.key(0))
                batch_abs = abstract_field_batch(spec, batch)
                aux_abs = (None if aux is None else jax.tree_util.tree_map(
                    lambda a: sds(a.shape, a.dtype), aux))
                n_abs = sds((), jnp.int32)
                if init_opt is not None:
                    opt_abs = jax.eval_shape(init_opt, params_abs)
                    jit_fn.lower(params_abs, opt_abs, *batch_abs,
                                 aux_abs, n_abs).compile()
                else:
                    jit_fn.lower(params_abs, *batch_abs,
                                 aux_abs, n_abs).compile()
                cs = compile_cache.cache_stats()
                _log(f"[inner] [{label}] AOT precompile in "
                     f"{time.perf_counter() - t_aot:.1f}s (cache: "
                     f"{cs['hits']} hits / {cs['misses']} misses, "
                     f"{cs['entries']} entries)")
            except Exception as e:  # noqa: BLE001 — best-effort
                _log(f"[inner] [{label}] AOT precompile failed "
                     f"({type(e).__name__}): "
                     f"{(str(e).splitlines() or [''])[0][:200]}")

        def measure(label=label, spec=spec, init_opt=init_opt, run=run,
                    aux=aux):
            """One supervised measurement attempt. The ``sweep_leg``
            fault point fires first (the deterministic mid-sweep device
            loss), then FRESH tables — params are donated into the step,
            so every retry must rebuild them; the local scope also
            guarantees the tables are dropped before the next variant's
            init (two resident sets would double peak HBM)."""
            faults.inject("sweep_leg")
            params = spec.init(jax.random.key(0))
            carry = (
                (params, init_opt(params), jnp.float32(0))
                if init_opt is not None else (params, jnp.float32(0))
            )
            _log(f"[inner] [{label}] compiling + warmup (first TPU "
                 "compile is slow, ~20-60s)...")
            t0 = time.perf_counter()
            carry = run(carry, ids, vals, labels, weights, aux,
                        jnp.int32(steps_warmup))
            float(carry[-1])  # d2h fence
            _log(f"[inner] [{label}] warmup done in "
                 f"{time.perf_counter() - t0:.1f}s; timing {steps_timed} "
                 f"steps x batch {batch}...")
            t0 = time.perf_counter()
            carry = run(carry, ids, vals, labels, weights, aux,
                        jnp.int32(steps_timed))
            final_loss = float(carry[-1])  # d2h fence
            return time.perf_counter() - t0, final_loss

        # Supervision scope: the per-leg retry recovers TRANSIENT
        # losses (a raise that leaves the process healthy — the
        # injectable kind, and brief flaps surfaced as step errors). A
        # WEDGED backend is beyond in-process repair — the retry reuses
        # this leg's jitted executable and device-resident aux, and on
        # this attachment a dead backend hangs rather than raises — so
        # that mode stays the parent watchdog's job: attempt timeout →
        # kill → respawn → auto --resume-sweep of the banked legs.
        outcome = None
        t_leg_wall, t_leg0 = time.time(), time.perf_counter()
        # Failure delta over THIS leg: the fingerprint's attachment-
        # health verdict is per-measurement weather, not run-lifetime
        # state (one early flap must not stamp every later leg flaky).
        leg_fail0 = sup.total_failures
        while outcome is None:
            try:
                dt, final_loss = sup.run(measure, op=f"leg:{label}",
                                         retryable=is_device_loss)
                outcome = "ok"
            except (CircuitOpen, RetriesExhausted) as e:
                if (elastic is not None and sup.permanent()
                        and elastic.can_shrink()):
                    # Permanent fault + capacity to shed: degrade
                    # instead of abandoning. The shrink is journaled,
                    # the breaker re-arms, and the SAME leg re-runs.
                    # What the shrink changes here is the ACCOUNTING,
                    # not the placement: the leg is a single-process
                    # measurement whose per-chip rate divides by the
                    # fleet the result claims to represent, so the
                    # denominator drops to the surviving count and the
                    # payload is stamped degraded (and never keep-bests
                    # into MEASURED.json). A fresh retry window is the
                    # other half of the value — bounded by max_shrinks,
                    # so a default device that is truly dead still
                    # abandons after the ladder is spent.
                    prev_chips = n_chips
                    n_chips = len(elastic.shrink(f"leg:{label}"))
                    # Keep every banked rate on ONE denominator: legs
                    # measured before the shrink re-normalize to the
                    # surviving count, so max() ranks variants on
                    # comparable per-chip figures instead of letting a
                    # post-shrink leg win on a 2x smaller divisor.
                    results[:] = _renormalize_results(results, prev_chips,
                                                      n_chips)
                    sup.reset(f"leg:{label}")
                    _log(f"[inner] [{label}] permanent device fault -- "
                         f"degraded mode: retrying on {n_chips} chip(s) "
                         f"(shrink {elastic.shrinks}/{elastic.max_shrinks})")
                    continue
                if isinstance(e, CircuitOpen):
                    _log(f"[inner] circuit open ({e}) -- abandoning the "
                         "remaining legs; completed measurements still "
                         "count")
                    outcome = "abandon"
                else:
                    # A device loss that exhausted its retries (mixed
                    # failure modes, or no elastic capacity left); its
                    # history is in the health journal.
                    _log(f"[inner] [{label}] FAILED "
                         f"({type(e).__name__}): "
                         f"{(str(e).splitlines() or [''])[0][:200]}"
                         " -- skipping variant")
                    outcome = "skip"
            except Exception as e:  # noqa: BLE001 — one broken variant
                # (e.g. a Mosaic lowering reject, round 5's segtotal
                # block-spec ValueError) must not kill the remaining
                # A/Bs; the parent's retry would re-crash on the same
                # variant and the sweep would never price the rest.
                # Hangs are the watchdog's job.
                _log(f"[inner] [{label}] FAILED ({type(e).__name__}): "
                     f"{(str(e).splitlines() or [''])[0][:200]}"
                     " -- skipping variant")
                outcome = "skip"
        # Retroactive per-leg span (compile+warmup+timed window+any
        # retries): the report's phase breakdown attributes the sweep's
        # wall-clock leg by leg without fencing inside the measurement.
        obs.emit_span("bench/leg", t_leg_wall,
                      time.perf_counter() - t_leg0,
                      label=label, outcome=outcome)
        if outcome == "abandon":
            break
        if outcome == "skip":
            continue
        if not np.isfinite(final_loss):
            # compact_device signals cap overflow by POISONING the loss
            # (-inf; sparse.py _fold_overflow) instead of raising like
            # the host aux build — a poisoned run's rate is a
            # measurement of a corrupted program and must not enter
            # results (it could win max() and reach MEASURED.json).
            _log(f"[inner] [{label}] non-finite final loss "
                 f"({final_loss}) — overflow/divergence poison; "
                 "skipping variant")
            continue
        rate = steps_timed * batch / dt / n_chips
        results.append((rate, label, dt, final_loss))
        # One step-time sample per leg (the timed window's mean step —
        # the fori_loop rolls the steps into one program, so per-step
        # fencing would change the measurement): percentiles across
        # legs land in the telemetry block.
        obs.histogram("step_time_ms").observe(dt / steps_timed * 1e3)
        # Device-memory watermark right after the leg, while its tables
        # are still resident: HBM peak rides the leg record next to the
        # rate (the registry gauges feed the telemetry block too).
        mem = obs.device_memory_snapshot(devs) or {}
        # Fingerprint + sentinel verdict (ISSUE 9): judge this rate
        # against the cohort history, then append it — best-effort by
        # the telemetry contract (a broken ledger must not cost the
        # leg), but a verdict failure is logged, never silent.
        degraded_now = elastic is not None and elastic.degraded
        leg_health = ("degraded" if degraded_now else
                      "flaky" if (sup.total_failures - leg_fail0) > 0
                      else sup.health_verdict())
        fingerprint = obs.measurement_fingerprint(
            variant=label, model=args.model, batch=batch,
            steps=steps_timed, rank=rank,
            device_kind=devs[0].device_kind, n_chips=n_chips,
            jax_version=_versions["jax_version"],
            libtpu_version=_versions["libtpu_version"],
            degraded=degraded_now,
            fused_fallback=label in fused_fallback_legs,
            chaos=args.chaos,
            attachment_health=leg_health,
        )
        reused_ledger_record = False
        try:
            # Crash window on a RETRIED attempt only (the lookup costs
            # a ledger scan, so the common fresh path skips it): the
            # aborted attempt appended this leg's ledger record but
            # died before _persist_incremental banked it, so the
            # resume scan re-measured the leg.
            prior = [r for r in ledger.records(kind="bench_leg",
                                               leg=METRIC,
                                               run_id=run_id)
                     if r.get("variant") == label
                     ] if args.resume_sweep else []
            if prior and prior[-1].get("sentinel"):
                reused_ledger_record = True
                # Judge the RE-MEASURED rate against the recorded
                # history (which already contains the aborted
                # attempt's row) WITHOUT appending a duplicate
                # (run_id, leg, variant) record — the verdict stays
                # truthful about this value, the history stays
                # duplicate-free.
                leg_verdicts[label] = dict(
                    sentinel.judge(METRIC, round(rate, 1), fingerprint),
                    reused_ledger_record=True)
            else:
                leg_verdicts[label] = sentinel.observe({
                    "kind": "bench_leg", "leg": METRIC,
                    "run_id": run_id,
                    "variant": label, "value": round(rate, 1),
                    "unit": UNIT, "dt_s": round(dt, 3),
                    "loss": round(final_loss, 6),
                    # PJRT's peak_bytes_in_use is the PROCESS-
                    # cumulative high-water mark at leg end (no reset
                    # API exists): legs after the sweep's largest
                    # inherit its peak.
                    "hbm_peak_bytes": mem.get("peak_bytes_in_use"),
                    "fingerprint": fingerprint,
                })
            _log(f"[inner] [{label}] sentinel: "
                 f"{leg_verdicts[label]['verdict']} "
                 f"({leg_verdicts[label]['reason']})")
        except Exception as e:  # noqa: BLE001 — ledger is best-effort
            _log(f"[inner] [{label}] ledger/sentinel failed "
                 f"({type(e).__name__}): "
                 f"{(str(e).splitlines() or [''])[0][:200]}")
        # Per-leg cost attribution (ISSUE 14): pair the measured step
        # time with the leg's bytes-moved model (the same traffic-term
        # families bench_kernels.py prices per kernel) into a
        # `cost_attribution` ledger record — the autotuner's evidence
        # base (ROADMAP item 4) grows on every sweep, not only at
        # pricing time. value = model-implied GB/s. A resumed leg whose
        # aborted attempt already ledgered is SKIPPED, same dedup as
        # the bench_leg record above (the two appends travel together,
        # so the crash window leaves both or neither) — one record per
        # (run_id, variant).
        if not reused_ledger_record:
            try:
                pb = 2 if dtypes[0] == "bfloat16" else 4
                cb = 2 if dtypes[1] == "bfloat16" else 4
                cost = introspect.step_cost_model(
                    args.model, batch, rank, cap=config.compact_cap,
                    param_bytes=pb, compute_bytes=cb)
                step_s = dt / steps_timed
                ledger.append({
                    "kind": "cost_attribution",
                    "leg": f"cost/{METRIC}",
                    "run_id": run_id, "variant": label,
                    "value": round(cost["bytes_total"] / step_s / 1e9,
                                   3),
                    "unit": "GB/s(model)",
                    "step_ms": round(step_s * 1e3, 3),
                    "bytes_per_step": cost["bytes_total"],
                    "families": cost["families"],
                    "assumptions": cost["assumptions"],
                    "fingerprint": fingerprint,
                })
            except Exception as e:  # noqa: BLE001 — best-effort rule
                _log(f"[inner] [{label}] cost-attribution append "
                     f"failed ({type(e).__name__}): "
                     f"{(str(e).splitlines() or [''])[0][:200]}")
        _log(f"[inner] [{label}] {rate:,.0f} samples/sec/chip "
             f"(dt={dt:.3f}s loss={final_loss:.4f})")
        # Emit the best-so-far line after EVERY variant: if a later
        # variant hangs/crashes (flaky attachment), the parent's salvage
        # scan still finds a valid completed measurement (it takes the
        # LAST matching line). In --fast-first terms this IS the tier
        # boundary: the first line (leg 1 = the recorded winner) is a
        # full non-provisional result, emitted before any remaining
        # sweep leg starts.
        payload = emit_best()
        # Keep-best incrementally persisted: an interrupted run never
        # reports null when any leg completed. ``ts`` stamps the record
        # so --resume-since can tell THIS run's legs from a prior
        # round's.
        leg_record = {
            "variant": label, "value": round(rate, 1), "unit": UNIT,
            "dt_s": round(dt, 3), "loss": round(final_loss, 6),
            "device": devs[0].device_kind,
            "ts": round(time.time(), 3),
            "t_since_start_s": round(time.perf_counter() - t_start, 1),
            # Provenance fields (ISSUE 9): run_id + fingerprint are
            # REQUIRED on every leg record (tools/resilience_lint.py
            # pins these keys), so a sweep artifact line can always be
            # traced to its run and comparability cohort.
            "run_id": run_id,
            "fingerprint": fingerprint,
            "hbm_peak_bytes": mem.get("peak_bytes_in_use"),
        }
        if label in leg_verdicts:
            leg_record["sentinel"] = leg_verdicts[label]
            leg_record["verdict"] = leg_verdicts[label]["verdict"]
        if elastic is not None and elastic.degraded:
            leg_record["chips"] = n_chips
            leg_record["degraded"] = True
        if label in fused_fallback_legs:
            leg_record["fused_fallback"] = True
        if args.chaos:
            leg_record["chaos"] = True
        _persist_incremental(art_dir, args.model, payload, leg_record)
        # Metrics snapshot after every leg: a later kill still leaves
        # the run's numeric record in <obs_dir>/metrics.jsonl.
        obs.export_snapshot()

    if not results:
        _log("[inner] every variant failed; no measurement")
        obs.shutdown()
        return 1
    rate, label, dt, final_loss = max(results)
    _log(f"[inner] device={devs[0].device_kind} "
         f"chips={n_chips} best={label} batch={batch} "
         f"steps={steps_timed} dt={dt:.3f}s loss={final_loss:.4f}"
         + (f" DEGRADED (shrinks={elastic.shrinks})"
            if elastic is not None and elastic.degraded else ""))
    obs.shutdown()
    return 0


# --------------------------------------------------------------------------
# Parent: spawn the child with a hard timeout, retry with backoff under a
# TOTAL wall-clock deadline, emit a provisional error JSON after every
# failed attempt, and salvage the best-so-far line even on SIGTERM.
# --------------------------------------------------------------------------

# Shared with the signal handler: the last valid cumulative-best result
# line streamed from any child, the failure log so far, and the live
# child process (so the handler can kill it before exiting — an orphaned
# child would keep holding the exclusive TPU attachment). RLock: the
# handler runs on the main thread, which may already hold the lock when
# the signal lands.
_SALVAGE = {"line": None, "failures": [], "emitted": False, "proc": None,
            "permanent": False}
_SALVAGE_LOCK = threading.RLock()

# Parent-side ledger target (set by main): the error path appends a
# NULL record — a dead round is a first-class attachment_transient
# data point in the history, not a gap (the BENCH_r03–r05 lesson).
_LEDGER_PATH = None
_MODEL_NAME = "fm"


def _load_obs_file(name):
    """Load fm_spark_tpu/obs/<name>.py standalone (ledger/sentinel are
    deliberately stdlib-only): the light parent gets provenance and the
    keep-best gate without importing the jax-pulling package."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fm_spark_tpu", "obs", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_bench_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    # Register before exec: dataclass processing looks the module up
    # in sys.modules.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _ledger_error_record():
    """Append the dead-round null record (best-effort by the final-line
    contract)."""
    if _LEDGER_PATH is None:
        return
    try:
        lg = _load_obs_file("ledger")
        st = _load_obs_file("sentinel")
        ledger = lg.PerfLedger(_LEDGER_PATH)
        st.Sentinel(ledger).observe({
            "kind": "bench_leg", "leg": METRIC,
            "run_id": _RUN_ID or "unknown",
            "variant": None, "value": None, "unit": UNIT,
            "error": "; ".join(_SALVAGE["failures"])[:500]
            or "no attempt completed",
            "fingerprint": lg.measurement_fingerprint(
                variant="(error)", model=_MODEL_NAME,
                attachment_health="down"),
        })
    except Exception as e:
        _log(f"[parent] error-record ledger append failed: {e!r}")


def comparable_variant(variant) -> bool:
    """True iff a sweep result's variant label carries no non-default
    shape stamp — ``/b<digits>`` (non-default ``--batch``) or
    ``/r<digits>`` (non-default ``--rank``), added by inner_main. Only
    such results are comparable with the recorded MEASURED.json rates:
    every recorded rate is at its model's default batch and rank, a
    doubled batch amortizes fixed per-step work into an incomparable
    samples/sec, and a different rank is a different program."""
    return not re.search(r"/[br]\d", str(variant or ""))


def _emit_final():
    """Print the authoritative last line exactly once (result or error),
    and on a real measurement rewrite MEASURED.json so every downstream
    projection (dryrun_multichip, PERF analyses) picks up the new rate
    with its provenance — the single-source-of-truth contract of
    fm_spark_tpu/measured.py (VERDICT r4 Weak #1)."""
    with _SALVAGE_LOCK:
        if _SALVAGE["emitted"]:
            return
        _SALVAGE["emitted"] = True
        if _SALVAGE["line"] is not None:
            print(_SALVAGE["line"], flush=True)
            try:
                parsed = json.loads(_SALVAGE["line"])
                # Only a real TPU measurement may become the recorded
                # rate — a CPU smoke run must not clobber provenance.
                if "tpu" not in str(parsed.get("device", "")).lower():
                    raise RuntimeError(
                        f"not a TPU measurement: {parsed.get('device')!r}")
                # A non-default-shape A/B (the /b262144 or /r32 labels)
                # stays in its sweep artifact; promoting it is a
                # deliberate re-baseline, not a keep-best side effect.
                if not comparable_variant(parsed.get("variant")):
                    raise RuntimeError(
                        f"non-default-shape variant "
                        f"{parsed.get('variant')!r}; not comparable with "
                        "the recorded default-shape rate")
                # A degraded (shrunk-mesh) rate is a salvage artifact,
                # not the attachment's measured capability — it must
                # never become the recorded keep-best.
                if parsed.get("degraded"):
                    raise RuntimeError(
                        f"degraded measurement on {parsed.get('chips')} "
                        "chip(s) after an elastic shrink; keeping the "
                        "recorded full-mesh rate")
                # A fused-embed leg that fell back to XLA measured the
                # wrong program for its label — never the keep-best
                # (ISSUE 8; same contract as the degraded stamp).
                if parsed.get("fused_fallback"):
                    raise RuntimeError(
                        "fused-embed run fell back to the XLA path; "
                        "not a fused-kernel measurement — keeping the "
                        "recorded rate")
                # A chaos-drill rate ran under an injected fault
                # schedule (ISSUE 10): a different program in
                # everything but name — never the keep-best.
                if parsed.get("chaos"):
                    raise RuntimeError(
                        "chaos-drill measurement (run under an active "
                        "fault schedule); drill legs have their own "
                        "ledger cohort — keeping the recorded rate")
                # Sentinel gate (ISSUE 9): only an improved/flat
                # verdict against the ledger's cohort history may
                # promote — a statistically-regressed rate, or one
                # measured under adverse attachment weather, never
                # overwrites the recorded capability no matter how the
                # numeric comparison lands.
                sb = parsed.get("sentinel")
                if not _load_obs_file("sentinel").keepbest_allowed(sb):
                    raise RuntimeError(
                        f"sentinel verdict {(sb or {}).get('verdict')!r}"
                        f" ({(sb or {}).get('reason')}); only improved/"
                        "flat measurements may promote — keeping the "
                        "recorded rate")
                # Keep-best: MEASURED.json records the best measured
                # on-chip capability. A later throttled window (this
                # attachment streams at 5-10% of nominal HBM on bad
                # days) or a SIGTERM-salvaged partial sweep must not
                # clobber a healthier earlier measurement — same rule
                # as tpu_watch.sh's best-sweep selection.
                from fm_spark_tpu.measured import (
                    load_measured,
                    update_entry,
                )
                entry = METRIC_ENTRY[parsed["metric"]]
                try:
                    prev = load_measured()[entry][
                        "rate_samples_per_sec_per_chip"]
                except (OSError, ValueError, KeyError):
                    prev = 0.0
                if parsed["value"] <= prev:
                    raise RuntimeError(
                        f"measured {parsed['value']:.0f} <= recorded "
                        f"best {prev:.0f}; keeping the recorded rate")
                update_entry(
                    entry,
                    rate=parsed["value"],
                    vs_baseline=parsed.get("vs_baseline"),
                    variant=parsed.get("variant", "?"),
                    source=f"bench.py --model sweep (round 5+), metric "
                           f"{parsed['metric']}",
                    attachment=parsed.get("device", "unknown device"),
                    date=time.strftime("%Y-%m-%d", time.gmtime()),
                )
                _log(f"[parent] MEASURED.json {entry} updated from "
                     "this sweep")
            except Exception as e:  # never break the final-line contract
                _log(f"[parent] MEASURED.json update failed: {e!r}")
        else:
            _ledger_error_record()
            print(_error_line("; ".join(_SALVAGE["failures"])
                              or "no attempt completed",
                              permanent=_SALVAGE["permanent"]),
                  flush=True)


def _parse_result_line(line):
    line = line.strip()
    if not line.startswith("{"):
        return None
    try:
        parsed = json.loads(line)
    except json.JSONDecodeError:
        return None
    if parsed.get("metric") == METRIC and parsed.get("value") is not None:
        return line
    return None


def _run_attempt(argv, timeout_s):
    """One child run. Returns (json_line_or_None, diagnostic_str).

    The child's stdout is STREAMED (not buffered in communicate()): each
    cumulative-best line is recorded into _SALVAGE the moment it appears,
    so an outer SIGTERM landing mid-sweep still finds the newest
    completed measurement.
    """
    cmd = [sys.executable, os.path.abspath(__file__), "--inner"] + argv
    # stderr inherited -> child heartbeats stream live.
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
    with _SALVAGE_LOCK:
        _SALVAGE["proc"] = proc

    found_holder = {"line": None}

    def reader():
        for line in proc.stdout:
            got = _parse_result_line(line)
            if got is not None:
                # LAST matching line wins: the child prints a
                # cumulative-best line after each variant.
                found_holder["line"] = got
                with _SALVAGE_LOCK:
                    _SALVAGE["line"] = got
        proc.stdout.close()

    rd = threading.Thread(target=reader, daemon=True)
    rd.start()

    hb_stop = threading.Event()

    def heartbeat():
        t0 = time.perf_counter()
        while not hb_stop.wait(30):
            # One-decimal durations everywhere a duration is
            # interpolated: BENCH_r05's tail printed the raw float
            # ("timeout 125.98949042700042s").
            _log(f"[parent] attempt alive, {time.perf_counter() - t0:.1f}s "
                 f"elapsed (timeout {timeout_s:.1f}s)")

    hb = threading.Thread(target=heartbeat, daemon=True)
    hb.start()
    timed_out = False
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        proc.kill()
        proc.wait()
    finally:
        hb_stop.set()
        rd.join(timeout=10)
        with _SALVAGE_LOCK:
            _SALVAGE["proc"] = None

    found = found_holder["line"]
    if found is not None:
        return found, ""
    if timed_out:
        return None, f"child hung: no result within {timeout_s:.0f}s (killed)"
    return None, f"child exited rc={proc.returncode} without a result line"


def main():
    ap = argparse.ArgumentParser(
        description="FM training throughput bench (variant knobs for "
        "perf sweeps; defaults = the headline configuration)"
    )
    ap.add_argument("--inner", action="store_true",
                    help="internal: run the measurement in-process")
    ap.add_argument("--param-dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--compute-dtype", default="float32",
                    dest="compute_dtype",
                    choices=["float32", "bfloat16"],
                    help="forward/backward buffer dtype (the [B, w] "
                         "passes; storage stays --param-dtype)")
    ap.add_argument("--table-layout", default="row", dest="table_layout",
                    choices=["row", "col"],
                    help="physical table orientation; col = transposed "
                         "[width, bucket] (no minor-dim lane padding -> "
                         "~2x fewer physical table bytes; needs the "
                         "compact path)")
    ap.add_argument("--sparse-update", default="scatter_add",
                    choices=["scatter_add", "dedup", "dedup_sr"])
    ap.add_argument("--use-pallas", action="store_true", dest="use_pallas",
                    help="route row gather/update through the Pallas "
                         "pipelined-DMA kernels (PERF.md 'Pallas' lever)")
    ap.add_argument("--host-dedup", action="store_true", dest="host_dedup",
                    help="host-precomputed dedup aux: device writes each "
                         "unique id once (PERF.md round-3 lever; pair "
                         "with --sparse-update dedup or dedup_sr)")
    ap.add_argument("--compact-cap", type=int, default=0, dest="compact_cap",
                    help="COMPACT host-dedup: static per-field unique-id "
                         "capacity; device touches the big tables with "
                         "cap lanes instead of B (requires --host-dedup "
                         "or --compact-device, and a dedup "
                         "--sparse-update)")
    ap.add_argument("--compact-device", action="store_true",
                    dest="compact_device",
                    help="build the compact aux on device inside the "
                         "step (the scale-out form of --compact-cap; "
                         "exclusive with --host-dedup)")
    ap.add_argument("--gfull-fused", action="store_true",
                    dest="gfull_fused",
                    help="fused g_full construction (no per-field "
                         "concat([g_v, g_l]); PERF.md round-4 lever)")
    ap.add_argument("--segtotal-pallas", action="store_true",
                    dest="segtotal_pallas",
                    help="Pallas sorted-run segment totals in the "
                         "compact update (no blocked-prefix "
                         "materialization; round-5 lever)")
    ap.add_argument("--fused-embed", default="off",
                    choices=["off", "auto", "require"],
                    dest="fused_embed",
                    help="fused Pallas embedding path (ISSUE 8): "
                         "'require' measures exactly the fused kernel "
                         "family (fails if unservable); 'auto' falls "
                         "back to XLA — the leg is then stamped "
                         "fused_fallback and never keep-bests into "
                         "MEASURED.json")
    ap.add_argument("--embed-tier", default="off",
                    choices=["off", "auto", "require"],
                    dest="embed_tier",
                    help="tiered embedding store lever (ISSUE 16) for "
                         "the measured config: the in-HBM sweep legs "
                         "reject 'require' loudly (the tiered path is "
                         "priced by its OWN ladder, bench_embed.py, "
                         "into the embed_bench ledger kind — never "
                         "compared against in-HBM legs)")
    ap.add_argument("--hot-rows", type=int, default=0, dest="hot_rows",
                    help="HBM hot-tier rows for --embed-tier (see "
                         "bench_embed.py for the tiered ladder itself)")
    ap.add_argument("--embed-bucket-rows", type=int, default=512,
                    dest="embed_bucket_rows",
                    help="rows per hot-tier bucket for --embed-tier")
    ap.add_argument("--fast-first", action="store_true",
                    dest="fast_first",
                    help="tiered sweep (warm-start): measure the "
                         "recorded winner variant FIRST (AOT-"
                         "precompiled when the compile cache is on) "
                         "and emit its non-provisional result JSON "
                         "before the remaining legs start; every leg "
                         "streams to --artifacts-dir as it lands")
    ap.add_argument("--compile-cache", nargs="?", const="", default=None,
                    metavar="DIR", dest="compile_cache",
                    help="enable jax's persistent XLA compilation "
                         "cache at DIR (bare flag = the repo-local "
                         "default): a second bench process reuses "
                         "every compiled step — time-to-first-result "
                         "drops from minutes to seconds. "
                         "FM_SPARK_COMPILE_CACHE=<dir|1> without the "
                         "flag")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic degraded mode: a sweep leg whose "
                         "retries exhaust on a PERMANENT fault (N "
                         "identical consecutive device losses) sheds "
                         "chips (8>4>2>1) and re-runs instead of "
                         "abandoning the sweep; the result JSON is "
                         "stamped degraded with per-surviving-chip "
                         "normalization, and never keep-bests into "
                         "MEASURED.json")
    ap.add_argument("--max-shrinks", type=int, default=3,
                    dest="max_shrinks",
                    help="with --elastic: how many times the device "
                         "set may halve before the fault propagates")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos-drill stamping (ISSUE 10): this run is "
                         "executing under an active fault schedule "
                         "(FM_SPARK_FAULTS), so every leg's measurement "
                         "fingerprint carries chaos=true — drill legs "
                         "form their own ledger cohort and can never "
                         "join a real perf cohort or pass the keep-best "
                         "gate into MEASURED.json")
    ap.add_argument("--dirty-input", action="store_true",
                    dest="dirty_input",
                    help="run the hardened-ingest leg before the sweep "
                         "(ISSUE 5): stream a synthetic 3-shard dataset "
                         "with deterministically corrupted lines through "
                         "the quarantine policy and stamp its "
                         "bad_records / rows_per_sec accounting into "
                         "the result JSON (host-only, ~seconds)")
    ap.add_argument("--resume-sweep", action="store_true",
                    dest="resume_sweep",
                    help="skip sweep legs already completed in "
                         "--artifacts-dir's sweep_<model>.jsonl and "
                         "measure only the remaining ones (the restart "
                         "path after a mid-window kill; composes with "
                         "--fast-first and the warm compile cache — "
                         "the best-so-far line is emitted before any "
                         "remaining leg runs)")
    ap.add_argument("--resume-since", type=float, default=0.0,
                    dest="resume_since", metavar="EPOCH",
                    help="with --resume-sweep: only resume legs whose "
                         "sweep record is stamped at/after this unix "
                         "time (the parent passes its own start time "
                         "when auto-resuming a retried attempt; 0 = "
                         "any prior record)")
    ap.add_argument("--artifacts-dir", default=None, dest="artifacts_dir",
                    help="where sweep_<model>.jsonl / "
                         "keepbest_<model>.json land (default: "
                         "artifacts/ next to this script)")
    ap.add_argument("--model", default="fm", choices=sorted(METRICS),
                    help="which fused step to measure: fm = the tracked "
                         "Criteo headline; ffm = config 4's avazu shape "
                         "(refreshes MEASURED.json's ffm_avazu entry)")
    ap.add_argument("--rank", type=int, default=None,
                    help="factor rank (default: 64 for fm, 16 for ffm)")
    ap.add_argument("--batch", type=int, default=1 << 17)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--attempts", type=int, default=6,
                    help="max child attempts before emitting the error JSON "
                         "(the total deadline usually binds first)")
    ap.add_argument("--attempt-timeout", type=float, default=900.0,
                    help="hard wall-clock limit per attempt (seconds); "
                         "sized for the 7-variant default sweep (round 2 "
                         "ran 5 variants inside 600s) — a hung INIT "
                         "still exits at --init-timeout, and the "
                         "cumulative-best lines salvage a sweep the "
                         "limit cuts short")
    ap.add_argument("--total-deadline", type=float, default=1500.0,
                    dest="total_deadline",
                    help="hard wall-clock limit for the WHOLE run incl. "
                         "retries; kept under the driver's ~30min outer "
                         "kill window so the final JSON line always lands")
    ap.add_argument("--init-timeout", type=float, default=240.0,
                    dest="init_timeout",
                    help="child-side backend init watchdog: an init that "
                         "has not finished by then never finishes here; "
                         "the child exits early for a cheap retry")
    ap.add_argument("--metrics-port", type=int, default=None,
                    dest="metrics_port", metavar="PORT",
                    help="serve the live metrics registry from the "
                         "measuring child over stdlib HTTP on "
                         "127.0.0.1:PORT (0 = OS-assigned, echoed as a "
                         "JSON line): /metrics Prometheus text + "
                         "/healthz JSON — watch a sweep without "
                         "touching the process (ISSUE 14)")
    ap.add_argument("--run-id", default=None, dest="run_id",
                    help="telemetry run id (ISSUE 7): every stream this "
                         "run emits lands under <artifacts>/obs/"
                         "<run_id>/ and the id is echoed in the result "
                         "JSON. Default: minted fresh — the parent "
                         "passes its mint to every child attempt so "
                         "retries append to the SAME run")
    args = ap.parse_args()

    if (args.host_dedup or args.compact_device) and (
        args.sparse_update not in ("dedup", "dedup_sr")
    ):
        ap.error("--host-dedup/--compact-device require --sparse-update "
                 "dedup or dedup_sr")
    if (args.host_dedup or args.compact_device) and args.use_pallas:
        ap.error("--host-dedup/--compact-device and --use-pallas are "
                 "exclusive")
    if args.compact_cap and not (args.host_dedup or args.compact_device):
        ap.error("--compact-cap requires --host-dedup or --compact-device")
    if args.compact_device and args.host_dedup:
        ap.error("--compact-device and --host-dedup are exclusive")
    if args.compact_device and not args.compact_cap:
        ap.error("--compact-device requires --compact-cap")

    if args.inner:
        sys.exit(inner_main(args))

    # Re-build the child argv from the variant knobs only.
    _set_model(args.model)
    # Mint the run id HERE so every retried child appends to the same
    # per-run telemetry directory and the parent's own error JSON
    # carries the id of the evidence it left behind.
    global _RUN_ID, _LEDGER_PATH, _MODEL_NAME
    _RUN_ID = args.run_id or _gen_run_id()
    _MODEL_NAME = args.model
    _LEDGER_PATH = os.path.join(_artifacts_dir(args), "obs",
                                "ledger.jsonl")
    # Config errors must fail HERE, not in the child: the parent treats
    # a child death as a retryable attachment flake and would burn the
    # whole --total-deadline re-spawning a guaranteed failure.
    if args.model == "ffm" and args.table_layout != "row":
        raise SystemExit("--table-layout col is a FieldFM lever")
    argv = [
        "--model", args.model,
        "--param-dtype", args.param_dtype,
        "--compute-dtype", args.compute_dtype,
        "--table-layout", args.table_layout,
        "--sparse-update", args.sparse_update,
        "--batch", str(args.batch),
        "--steps", str(args.steps),
        "--init-timeout", str(args.init_timeout),
        "--run-id", _RUN_ID,
    ]
    if args.rank is not None:
        argv += ["--rank", str(args.rank)]
    if args.use_pallas:
        argv.append("--use-pallas")
    if args.host_dedup:
        argv.append("--host-dedup")
    if args.compact_cap:
        argv += ["--compact-cap", str(args.compact_cap)]
    if args.compact_device:
        argv.append("--compact-device")
    if args.gfull_fused:
        argv.append("--gfull-fused")
    if args.segtotal_pallas:
        argv.append("--segtotal-pallas")
    if args.fused_embed != "off":
        argv += ["--fused-embed", args.fused_embed]
    if args.embed_tier != "off":
        argv += ["--embed-tier", args.embed_tier,
                 "--hot-rows", str(args.hot_rows),
                 "--embed-bucket-rows", str(args.embed_bucket_rows)]
    if args.fast_first:
        argv.append("--fast-first")
    if args.dirty_input:
        argv.append("--dirty-input")
    if args.chaos:
        argv.append("--chaos")
    if args.elastic:
        argv += ["--elastic", "--max-shrinks", str(args.max_shrinks)]
    if args.compile_cache is not None:
        argv.append("--compile-cache")
        if args.compile_cache:
            argv.append(args.compile_cache)
    if args.artifacts_dir:
        argv += ["--artifacts-dir", args.artifacts_dir]
    if args.metrics_port is not None:
        argv += ["--metrics-port", str(args.metrics_port)]
    # An outer kill (timeout(1) sends SIGTERM) must still leave a
    # parseable final line: best-so-far result if any child printed one,
    # otherwise the error JSON with the failure log.
    import signal

    def _on_signal(signum, frame):
        with _SALVAGE_LOCK:
            _SALVAGE["failures"].append(
                f"parent received signal {signum} before completion")
            proc = _SALVAGE["proc"]
            salvaged = _SALVAGE["line"] is not None
        if proc is not None:
            try:
                proc.kill()
            except OSError:
                pass
        _emit_final()
        # A salvaged sweep IS a successful measurement (fast-first
        # contract: any completed leg beats a null artifact) — exit 0
        # so callers chained on success (tpu_watch's one-time queue)
        # still advance.
        os._exit(0 if salvaged else 1)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)

    deadline = time.perf_counter() + args.total_deadline
    t_epoch = time.time()  # auto-resume cutoff: only THIS run's legs
    raw_diags = []  # un-prefixed child failure diags for classification
    for attempt in range(1, args.attempts + 1):
        remaining = deadline - time.perf_counter()
        if remaining < 90:
            with _SALVAGE_LOCK:
                _SALVAGE["failures"].append(
                    f"total deadline {args.total_deadline:.0f}s reached "
                    f"after {attempt - 1} attempts")
            break
        # Reserve 15s so the final emit always beats the deadline.
        timeout_s = min(args.attempt_timeout, remaining - 15)
        child_argv = list(argv)
        if args.resume_sweep:
            child_argv.append("--resume-sweep")
            if args.resume_since:
                child_argv += ["--resume-since", str(args.resume_since)]
        elif attempt > 1:
            # A retried attempt auto-resumes: legs the previous child
            # completed before it died are loaded from the incremental
            # sweep artifact instead of re-measured — the remaining
            # deadline goes to legs that still NEED a window. Scoped to
            # records stamped after this parent started, so a prior
            # round's artifact can never masquerade as today's data.
            child_argv += ["--resume-sweep",
                           "--resume-since", f"{t_epoch:.3f}"]
        _log(f"[parent] attempt {attempt}/{args.attempts} "
             f"(timeout {timeout_s:.0f}s, {remaining:.0f}s of total "
             "budget left)")
        line, diag = _run_attempt(child_argv, timeout_s)
        if line is not None:
            with _SALVAGE_LOCK:
                _SALVAGE["line"] = line
            _emit_final()
            return 0
        raw_diags.append(diag)
        with _SALVAGE_LOCK:
            _SALVAGE["failures"].append(f"attempt {attempt}: {diag}")
        _log(f"[parent] {diag}")
        # Transient-vs-permanent classification (ISSUE 4 satellite — the
        # BENCH_r05 failure mode: six supervised attempts burned against
        # a permanently dead attachment): N identical consecutive child
        # failures mean the attachment is DEAD, so re-spawning and
        # re-sleeping the remaining attempts only burns the deadline.
        if _classify_diags(raw_diags, threshold=3) == "permanent":
            with _SALVAGE_LOCK:
                _SALVAGE["permanent"] = True
                _SALVAGE["failures"].append(
                    f"classified permanent after {len(raw_diags)} "
                    "identical consecutive failures -- abandoning the "
                    f"{args.attempts - attempt} remaining attempt(s)")
            _log(f"[parent] permanent fault: {len(raw_diags)} identical "
                 "consecutive failures -- stopping retries")
            break
        # Provisional artifact NOW: if the outer window kills us later,
        # the last stdout line is already parseable.
        with _SALVAGE_LOCK:
            print(_error_line(
                "provisional after failed attempt "
                f"{attempt}: " + "; ".join(_SALVAGE["failures"])),
                flush=True)
        if attempt < args.attempts:
            if _classify_diags(raw_diags, threshold=2) == "permanent":
                # Two identical failures already: suspected permanent.
                # The next attempt is the cheap confirmation probe —
                # spend the budget on it, not on a backoff sleep.
                _log("[parent] identical consecutive failures -- "
                     "skipping backoff (suspected permanent fault)")
                continue
            from fm_spark_tpu.utils.sleeps import scaled as _sleep_scaled

            # Designed sleep (FM_SPARK_TEST_SLEEP_SCALE shrinks it in
            # the fault suite); the deadline guard is NOT scaled.
            backoff = min(_sleep_scaled(10 * attempt),
                          max(0, deadline - time.perf_counter() - 90))
            if backoff > 0:
                _log(f"[parent] backing off {backoff:.1f}s before retry "
                     "(flaky TPU attachment)")
                time.sleep(backoff)

    _emit_final()
    return 1


if __name__ == "__main__":
    sys.exit(main())
