"""Headline benchmark: Criteo-shaped FM training throughput on TPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Config mirrors the north-star setting (BASELINE.json:5,9): FM rank 64,
39 fields (13 int + 26 categorical), 10.2M hashed features (39 × 262144
per-field buckets). Baseline = the driver target of 10M samples/sec on a
v5e-8 → 1.25M samples/sec/chip; ``vs_baseline`` = measured-per-chip /
target-per-chip, so ≥ 1.0 beats the 8-chip target at equal per-chip rate.

What is measured: the full fused sparse-SGD train step (forward, analytic
backward — the reference's computeGradient rule — and in-place scatter
update) on the field-partitioned table layout (models/field_fm.py explains
the measured XLA gather/scatter cliffs that motivate it). Many steps are
rolled into one compiled ``fori_loop`` program so per-dispatch host/tunnel
overhead (~66ms on this setup) is amortized, matching production use where
the host only feeds data. Data is device-resident; the host input pipeline
is exercised by the data-layer tests/benches instead.

Timing note: on this TPU attachment, ``block_until_ready`` returns before
execution completes; a device→host transfer of the loss is the reliable
fence, and is what we use.
"""

import argparse
import json
import sys
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser(
        description="FM training throughput bench (variant knobs for "
        "perf sweeps; defaults = the headline configuration)"
    )
    ap.add_argument("--param-dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--sparse-update", default="scatter_add",
                    choices=["scatter_add", "dedup", "dedup_sr"])
    ap.add_argument("--rank", type=int, default=64)
    ap.add_argument("--batch", type=int, default=1 << 17)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax import lax

    from fm_spark_tpu import models
    from fm_spark_tpu.sparse import make_field_sparse_sgd_body
    from fm_spark_tpu.train import TrainConfig

    num_fields = 39
    bucket = 262_144
    rank = args.rank
    batch = args.batch
    steps_warmup = 3
    steps_timed = args.steps

    spec = models.FieldFMSpec(
        num_features=num_fields * bucket, rank=rank,
        num_fields=num_fields, bucket=bucket, init_std=0.01,
        param_dtype=args.param_dtype,
    )
    config = TrainConfig(learning_rate=0.05, lr_schedule="constant",
                         optimizer="sgd", sparse_update=args.sparse_update)
    body = make_field_sparse_sgd_body(spec, config)

    params = spec.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    # Criteo-like Zipf skew within each field's bucket.
    ids = jnp.asarray(rng.zipf(1.3, size=(batch, num_fields)) % bucket, jnp.int32)
    vals = jnp.ones((batch, num_fields), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, batch), jnp.float32)
    weights = jnp.ones((batch,), jnp.float32)

    import functools

    # n_steps is a DYNAMIC argument so the warmup call compiles the exact
    # program the timed call runs (a static count would recompile inside
    # the timed region).
    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(params, ids, vals, labels, weights, n_steps):
        def fbody(i, carry):
            p, _ = carry
            return body(p, i, ids, vals, labels, weights)

        return lax.fori_loop(0, n_steps, fbody, (params, jnp.float32(0)))

    # Warmup: compile and touch all buffers.
    params, loss = run(params, ids, vals, labels, weights, jnp.int32(steps_warmup))
    float(loss)  # d2h fence

    t0 = time.perf_counter()
    params, loss = run(params, ids, vals, labels, weights, jnp.int32(steps_timed))
    final_loss = float(loss)  # d2h fence
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    samples_per_sec = steps_timed * batch / dt
    per_chip = samples_per_sec / n_chips
    target_per_chip = 10_000_000 / 8
    print(json.dumps({
        "metric": "criteo_fm_rank64_10Mfeat_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(per_chip / target_per_chip, 4),
    }))
    print(
        f"# device={jax.devices()[0].device_kind} chips={n_chips} "
        f"batch={batch} steps={steps_timed} dt={dt:.3f}s "
        f"loss={final_loss:.4f}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
