#!/usr/bin/env python
"""Supervised TPU-attachment watcher: the round-7 replacement for
tpu_watch.sh's bash poll loop (ISSUE 2).

Same job as rounds 5-6 — poll the flaky attachment, and whenever it
comes up run the pending on-chip measurements (gfull micro-probe, the
warm-start headline sweep, then the one-time ffm → deepfm → kaggle →
b262 queue), keeping the BEST sweep by parsed headline value — but the
probe/backoff/journal machinery is now the tested
:mod:`fm_spark_tpu.resilience` subsystem instead of inlined bash:

- the attachment probe is :class:`Supervisor`'s (device enumeration in
  a CHILD process — a dead attachment hangs/poisons whatever process
  INITIALIZES a backend, so the watcher itself never does; importing
  the resilience package does pull in jax, but import alone never
  touches the attachment — only ``jax.devices()`` does, and that runs
  in the probe child);
- down-time polling backs off by :class:`BackoffPolicy` (bounded
  exponential, deterministic jitter) instead of a fixed ``sleep 45``,
  resetting when the attachment answers;
- every transition (probe result, backoff, sweep outcome, queue
  advance) lands in ``<out>/health.jsonl``
  (:class:`~fm_spark_tpu.utils.logging.EventLog`) next to the raw
  captures, so a round's watch history is machine-readable.

The file layout and one-time markers (``ffm_done``/``deepfm_done``/
``kaggle_done``/``b262_done``, ``bench_sweep.out`` = best sweep) are
unchanged from the shell version, so existing round tooling keeps
working. Killed by the builder before round end, same as always.

Usage::

    python tools/tpu_watch.py [deadline_seconds]    # default 36000 (10h)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fm_spark_tpu.obs.ledger import (  # noqa: E402
    PerfLedger,
    default_ledger_path,
    measurement_fingerprint,
)
from fm_spark_tpu.resilience import BackoffPolicy, Supervisor  # noqa: E402
from fm_spark_tpu.utils.logging import EventLog  # noqa: E402

#: Warm-start flags every bench run gets (round-6: the first healthy
#: window pays XLA once; every later window deserializes and measures
#: the recorded winner first).
BENCH_WARM = ["--fast-first", "--compile-cache"]

#: The one-time measurement queue: (marker_file, bench argv tail,
#: timeout_s). Each entry runs once the headline has landed, in order,
#: and is retried in later windows until its output parses a value > 0.
QUEUE = [
    ("ffm_done",
     BENCH_WARM + ["--model", "ffm", "--total-deadline", "900"], 1100),
    ("deepfm_done",
     BENCH_WARM + ["--model", "deepfm", "--total-deadline", "900"], 1100),
    ("kaggle_done",
     BENCH_WARM + ["--model", "fm_kaggle", "--total-deadline", "900"],
     1100),
    # The doubled-batch A/B of the composed winner (provenance-stamped
    # /b262144 label — by design never updates MEASURED.json).
    ("b262_done",
     ["--compile-cache", "--batch", "262144", "--compact-cap", "26624",
      "--param-dtype", "bfloat16", "--compute-dtype", "bfloat16",
      "--sparse-update", "dedup_sr", "--host-dedup",
      "--gfull-fused", "--segtotal-pallas", "--total-deadline", "900"],
     1100),
]


def best_value(path: str) -> float:
    """Best parsed ``value`` from a bench output file (-1.0 if none) —
    the queue gate is a PARSED result, never the exit code (the outer
    timeout wrapper reports 124 on its own kill no matter what bench
    salvaged)."""
    best = -1.0
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                v = d.get("value")
                if isinstance(v, (int, float)) and v > best:
                    best = float(v)
    except OSError:
        pass
    return best


class TpuWatch:
    """The watch loop, with every external effect injectable so the
    policy logic unit-tests without a device, a bench run, or
    wall-clock (tests/test_tpu_watch.py)."""

    def __init__(self, out_dir: str, deadline_s: float,
                 runner=None, probe=None, sleep=time.sleep,
                 clock=time.monotonic, journal=None,
                 policy: BackoffPolicy | None = None,
                 obs_dir: str | None = None,
                 ledger: PerfLedger | None = None,
                 run_id: str = "tpuwatch"):
        self.out = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.deadline = clock() + deadline_s
        self.sleep = sleep
        self.clock = clock
        if journal is None:
            # ISSUE 7 consolidation: with an obs dir the watch journal
            # joins the per-run telemetry convention
            # (artifacts/obs/<run_id>/health.jsonl) instead of living
            # only beside the raw captures; raw captures stay in
            # out_dir either way.
            jdir = obs_dir or out_dir
            os.makedirs(jdir, exist_ok=True)
            journal = EventLog(os.path.join(jdir, "health.jsonl"))
        self.journal = journal
        # Down-time poll cadence: starts near the shell loop's 45s and
        # backs off toward 3 min — a long outage stops burning CPU on
        # this single-core VM, while the jitter keeps restarts from
        # synchronizing; resets the moment the attachment answers.
        self.policy = policy or BackoffPolicy(
            initial=45.0, multiplier=1.5, max_delay=180.0, jitter=0.1)
        self.sup = Supervisor(policy=self.policy, journal=self.journal,
                              probe=probe or self._probe_attachment,
                              sleep=sleep)
        self.runner = runner or self._run_cmd
        self.best_val = -1.0
        self.down_streak = 0
        # Attachment weather into the perf ledger (ISSUE 9 satellite):
        # every probe outcome becomes a first-class
        # ``attachment_probe`` record in the fingerprint stream, so
        # "the attachment was flaky that day" is a queryable series
        # instead of PERF.md prose. Default: the cross-run ledger
        # beside the obs run dirs.
        self.run_id = run_id
        self.ledger = ledger if ledger is not None else PerfLedger(
            default_ledger_path())

    def _ledger_probe(self, healthy: bool) -> None:
        """Best-effort probe record (the watch must outlive a broken
        ledger)."""
        try:
            self.ledger.append({
                "kind": "attachment_probe", "leg": "attachment",
                "run_id": self.run_id,
                "value": 1.0 if healthy else 0.0,
                "unit": "healthy",
                "streak": 0 if healthy else self.down_streak,
                "fingerprint": measurement_fingerprint(
                    variant="attachment_probe",
                    attachment_health="healthy" if healthy else "down"),
            })
        except Exception:
            pass

    # ---------------------------------------------------- external effects

    def _probe_attachment(self) -> bool:
        """Cheap probe in a CHILD process: device enumeration returns in
        seconds when healthy; 75 s is generous for a cold backend init,
        and a hang (the observed dead-attachment mode) only costs the
        child."""
        try:
            rc = subprocess.run(
                [sys.executable, "-c", "import jax; assert jax.devices()"],
                timeout=75, cwd=_REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ).returncode
            return rc == 0
        except subprocess.TimeoutExpired:
            return False

    def _run_cmd(self, argv: list, timeout_s: int, out_path: str,
                 err_path: str) -> int:
        """Run one measurement command, stdout/stderr to files (the
        audit trail the shell version kept); a timeout is rc 124 like
        timeout(1).

        Timeout delivery matters: like timeout(1) — and unlike
        ``subprocess.run(timeout=)``, whose expiry SIGKILLs — the first
        signal is SIGTERM, because bench.py's handler needs to run: it
        kills bench's own inner measurement child (an orphan would keep
        holding the exclusive TPU attachment and poison every later
        window) and emits the salvaged best-so-far line. SIGKILL only
        after a grace period."""
        with open(out_path, "w") as out, open(err_path, "w") as err:
            proc = subprocess.Popen(
                [sys.executable] + argv, cwd=_REPO,
                stdout=out, stderr=err,
            )
            try:
                return proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                return 124

    # -------------------------------------------------------- window work

    def _bench(self, name: str, argv_tail: list, timeout_s: int) -> float:
        out = os.path.join(self.out, f"{name}.out")
        err = os.path.join(self.out, f"{name}.err")
        rc = self.runner(["bench.py"] + argv_tail, timeout_s, out, err)
        val = best_value(out)
        self.journal.emit("bench_done", name=name, rc=rc, value=val)
        return val

    def measure_window(self) -> None:
        """One healthy-window pass: gfull micro-probe once, headline
        sweep keep-best, then the one-time queue in order."""
        ts = time.strftime("%H%M%S", time.gmtime())
        gfull = os.path.join(self.out, "gfull_probe.jsonl")
        if not (os.path.exists(gfull) and os.path.getsize(gfull)):
            rc = self.runner(
                ["bench_micro.py", "gfull"], 900, gfull,
                os.path.join(self.out, "gfull_probe.err"))
            self.journal.emit("gfull_probe", rc=rc)

        val = self._bench(
            f"sweep_{ts}",
            BENCH_WARM + ["--total-deadline", "1500"], 1700)
        headline_ok = val > 0
        if val > self.best_val:
            # Keep the BEST sweep across windows: a later, healthier
            # window replaces an early throttled one.
            self.best_val = val
            for ext in (".out", ".err"):
                src = os.path.join(self.out, f"sweep_{ts}{ext}")
                dst = os.path.join(self.out, f"bench_sweep{ext}")
                try:
                    with open(src, "rb") as s, open(dst, "wb") as d:
                        d.write(s.read())
                except OSError:
                    pass
            self.journal.emit("new_best_sweep", value=val)

        if not headline_ok:
            return
        for marker, argv_tail, timeout_s in QUEUE:
            mpath = os.path.join(self.out, marker)
            if os.path.exists(mpath):
                continue
            qval = self._bench(marker.removesuffix("_done") + "_sweep",
                               argv_tail, timeout_s)
            if qval > 0:
                with open(mpath, "w"):
                    pass
                self.journal.emit("queue_advanced", marker=marker,
                                  value=qval)
            # One queue entry per window beyond the first failure: a
            # value<=0 means the window flapped mid-queue — stop and
            # let the next healthy window retry this entry.
            if qval <= 0:
                return

    def queue_drained(self) -> bool:
        return os.path.exists(os.path.join(self.out, QUEUE[-1][0]))

    # --------------------------------------------------------------- loop

    def watch(self) -> float:
        self.journal.emit("watch_start",
                          deadline_s=round(self.deadline - self.clock()))
        while self.clock() < self.deadline:
            if self.sup.probe():
                self.down_streak = 0
                self._ledger_probe(True)
                self.sup.note_success("attachment")
                self.measure_window()
                # Queue drained → keep-best re-sweeps only: back WAY
                # off so the watcher stops contending with the
                # builder's CPU work; while draining, re-probe quickly.
                self.sleep(1500 if self.queue_drained() else 120)
            else:
                self.down_streak += 1
                self._ledger_probe(False)
                delay = self.policy.delay(self.down_streak,
                                          self.sup._rng)
                self.journal.emit("down", streak=self.down_streak,
                                  next_probe_s=round(delay, 1))
                self.sleep(delay)
        self.journal.emit("watch_end", best=self.best_val)
        return self.best_val


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    deadline = float(args[0]) if args else 36000.0
    from fm_spark_tpu import obs

    run_id = obs.new_run_id() + "-tpuwatch"
    watch = TpuWatch(
        os.path.join(_REPO, "tpu_watch_out"), deadline,
        obs_dir=os.path.join(_REPO, "artifacts", "obs", run_id),
        run_id=run_id)
    watch.watch()
    return 0


if __name__ == "__main__":
    sys.exit(main())
