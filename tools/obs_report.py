#!/usr/bin/env python
"""Render one human-readable run report from a telemetry directory.

The consumer end of the ISSUE 7 telemetry plane: given a per-run obs
directory (``artifacts/obs/<run_id>/`` — span trace, metrics snapshots,
flight spool/dump, health journals, dead-letter stream), print a single
report answering "where did this run spend its time, what faulted, and
what did ingest/step-rate look like":

- **Phase breakdown** — spans aggregated by name (count, total time,
  mean, max, share of the run's observed wall-clock);
- **Percentile tables** — the last metrics snapshot's histograms
  (count/mean/p50/p95/p99) plus counters and gauges;
- **Fault / retry timeline** — fault-kind events from the flight
  window and every health journal, time-ordered with offsets relative
  to the first observed event;
- **Quarantine** — dead-letter reason counts, when ingest quarantined.

Back-compat (ISSUE 7 satellite): pointed at a PRE-obs artifacts
directory (flat ``health_<model>.jsonl`` / ``deadletter.jsonl``, no
``trace.jsonl``), the report still renders the fault timeline and
quarantine sections from the old flat layout.

Usage::

    python tools/obs_report.py artifacts/obs/<run_id>/
    python tools/obs_report.py --latest            # newest run under
                                                   # artifacts/obs/
    python tools/obs_report.py --run-id <id>       # explicit run-id
                                                   # selector (ISSUE 14:
                                                   # mtime-based --latest
                                                   # is wrong while a
                                                   # serve daemon keeps
                                                   # its run dir hot)
"""

from __future__ import annotations

import json
import os
import sys
from collections import Counter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from fm_spark_tpu.obs import FAULT_KINDS, TRACE_FILE  # noqa: E402
from fm_spark_tpu.obs.introspect import list_captures  # noqa: E402


def _read_jsonl(path: str) -> list[dict]:
    """Best-effort JSONL parse: unparseable lines (the torn tail a kill
    can leave) are skipped, a missing file is an empty stream."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def load_run(obs_dir: str) -> dict:
    """Parse every stream in ``obs_dir`` into one report-ready dict.
    Works on both the per-run layout and the old flat artifacts layout
    (where only health/dead-letter journals exist)."""
    spans = [r for r in _read_jsonl(os.path.join(obs_dir, TRACE_FILE))
             if r.get("event") == "span"]
    snapshots = _read_jsonl(os.path.join(obs_dir, "metrics.jsonl"))

    flight_events = _read_jsonl(os.path.join(obs_dir, "flight.jsonl"))
    dump = None
    try:
        with open(os.path.join(obs_dir, "flight_dump.json")) as f:
            dump = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass

    health = []
    for fname in sorted(os.listdir(obs_dir)) if os.path.isdir(obs_dir) \
            else []:
        if fname.startswith("health") and fname.endswith(".jsonl"):
            health.extend(_read_jsonl(os.path.join(obs_dir, fname)))

    dead = _read_jsonl(os.path.join(obs_dir, "deadletter.jsonl"))

    # Kernel-pricing report (ISSUE 9 satellite): bench_kernels.py
    # writes kernel_pricing.json into the run dir — surface it instead
    # of ignoring it.
    pricing = None
    try:
        with open(os.path.join(obs_dir, "kernel_pricing.json")) as f:
            pricing = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass

    # Fault timeline: flight window + health journals, de-duplicated —
    # the health journal is MIRRORED into the flight ring, so the same
    # transition usually exists in both streams. The key is the FULL
    # payload (minus the ring's own seq/kind bookkeeping), not just
    # (ts, kind): a quarantine burst can emit many distinct bad_record
    # events inside one rounded millisecond, and each must keep its
    # own timeline row.
    seen, timeline = set(), []
    for rec in flight_events + health:
        kind = rec.get("kind") or rec.get("event")
        if kind not in FAULT_KINDS:
            continue
        key = (kind, json.dumps(
            {k: v for k, v in rec.items() if k not in ("seq", "kind",
                                                       "event")},
            sort_keys=True, default=str))
        if key in seen:
            continue
        seen.add(key)
        timeline.append(dict(rec, kind=kind))
    timeline.sort(key=lambda r: r.get("ts") or 0.0)

    return {
        "dir": os.path.abspath(obs_dir),
        "run_id": (dump or {}).get("run_id") or next(
            (e.get("run_id") for e in flight_events
             if e.get("kind") == "run_start" and e.get("run_id")),
            os.path.basename(os.path.normpath(obs_dir))),
        "spans": spans,
        "snapshot": snapshots[-1] if snapshots else
        (dump or {}).get("metrics"),
        "dump": dump,
        "timeline": timeline,
        "flight_events": flight_events,
        "dead": dead,
        "kernel_pricing": pricing,
        # Deep-capture bundles (ISSUE 14): every valid manifest under
        # <run>/captures/ — trigger, context, profiler status.
        "captures": list_captures(obs_dir),
    }


def serve_timeline(flight_events: list[dict]) -> list[dict]:
    """The serving reload/swap timeline from a flight window (ISSUE
    12): ``serve_*``/``reload_*`` events, payload-deduped — a
    journaled event and its flight-ring mirror are the same
    transition. Shared by this report and ``tools/run_doctor.py``."""
    return _dedup_timeline(flight_events, ("serve_", "reload_"))


def online_timeline(flight_events: list[dict]) -> list[dict]:
    """The continuous-learning timeline (ISSUE 13): eval verdicts,
    drift alarms, demotions, rollbacks, pointer republishes — same
    dedup contract as :func:`serve_timeline`."""
    return _dedup_timeline(
        flight_events,
        ("quality_eval", "online_", "divergence_",
         "generation_demoted", "last_good_republished"))


def render_captures(captures: list[dict]) -> list[str]:
    """The 'Deep captures' section body (ISSUE 14) — trigger, profiler
    status, context, bundle path per valid manifest. Shared by this
    report and ``tools/run_doctor.py`` (same sharing contract as
    :func:`serve_timeline`), so the format can never drift between the
    two tools."""
    out = [f"## Deep captures ({len(captures)} bundle(s))"]
    for m in captures:
        ctx = " ".join(f"{k}={v}" for k, v in sorted(
            (m.get("context") or {}).items()))
        prof = (m.get("profiler") or {}).get("status", "?")
        out.append(f"  {m.get('trigger', '?'):22} "
                   f"#{m.get('seq', '?')}  profiler={prof}  "
                   f"{ctx}"[:200])
        out.append(f"    -> {m.get('dir')}")
    out.append("")
    return out


def _dedup_timeline(flight_events: list[dict], prefixes) -> list[dict]:
    seen, out = set(), []
    for e in flight_events:
        if not str(e.get("kind", "")).startswith(tuple(prefixes)):
            continue
        key = json.dumps({k: v for k, v in e.items()
                          if k not in ("seq", "ts")},
                         sort_keys=True, default=str)
        if key in seen:
            continue
        seen.add(key)
        out.append(e)
    return out


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v:,.2f}"


def _phase_rows(spans: list[dict]) -> list[tuple]:
    agg: dict[str, list[float]] = {}
    for s in spans:
        agg.setdefault(s.get("name", "?"), []).append(
            float(s.get("dur_ms") or 0.0))
    rows = []
    for name, durs in agg.items():
        rows.append((sum(durs), name, len(durs),
                     sum(durs) / len(durs), max(durs)))
    rows.sort(reverse=True)
    return rows


def render(run: dict) -> str:
    """The report text (also what ``main`` prints)."""
    out = [f"# fm_spark_tpu run report — {run['run_id']}",
           f"obs dir: {run['dir']}", ""]

    spans = run["spans"]
    out.append(f"## Phase breakdown ({len(spans)} spans)")
    if spans:
        starts = [s.get("t_start") for s in spans
                  if s.get("t_start") is not None]
        ends = [s["t_start"] + s.get("dur_ms", 0.0) / 1e3 for s in spans
                if s.get("t_start") is not None]
        wall_s = (max(ends) - min(starts)) if starts else 0.0
        out.append(f"observed wall-clock: {wall_s:,.3f} s")
        out.append(f"{'name':32} {'count':>6} {'total_s':>10} "
                   f"{'mean_ms':>10} {'max_ms':>10} {'share':>7}")
        for total_ms, name, n, mean_ms, max_ms in _phase_rows(spans):
            share = (total_ms / 1e3 / wall_s) if wall_s > 0 else 0.0
            out.append(f"{name:32} {n:>6} {total_ms / 1e3:>10,.3f} "
                       f"{mean_ms:>10,.2f} {max_ms:>10,.2f} "
                       f"{share:>6.1%}")
    else:
        out.append("(no span trace — pre-obs layout or tracing disabled)")
    traced = {str(s.get("trace")) for s in spans if s.get("trace")}
    if traced:
        # Request-scoped spans (ISSUE 18): this run dir holds one
        # process's slice — cross-process stitching lives elsewhere.
        out.append(
            f"{len(traced)} distinct request trace id(s) in this "
            "process's spans — merge the fleet's view with "
            "tools/trace_report.py <obs root>")
    out.append("")

    snap = run["snapshot"]
    out.append("## Metrics")
    if snap:
        hists = snap.get("histograms") or {}
        if hists:
            out.append(f"{'histogram':32} {'count':>8} {'mean':>10} "
                       f"{'p50':>10} {'p95':>10} {'p99':>10}")
            for name in sorted(hists):
                s = hists[name]
                out.append(
                    f"{name:32} {s.get('count', 0):>8} "
                    f"{_fmt_ms(s.get('mean')):>10} "
                    f"{_fmt_ms(s.get('p50')):>10} "
                    f"{_fmt_ms(s.get('p95')):>10} "
                    f"{_fmt_ms(s.get('p99')):>10}")
        for kind in ("counters", "gauges"):
            vals = {k: v for k, v in (snap.get(kind) or {}).items()
                    if v is not None}
            if vals:
                out.append(f"{kind}:")
                for name in sorted(vals):
                    out.append(f"  {name:40} {vals[name]:,.6g}")
    else:
        out.append("(no metrics snapshot)")
    out.append("")

    timeline = run["timeline"]
    out.append(f"## Fault / retry timeline ({len(timeline)} events)")
    if timeline:
        t0 = timeline[0].get("ts") or 0.0
        for rec in timeline:
            extras = {k: v for k, v in rec.items()
                      if k not in ("ts", "kind", "event", "seq")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(
                extras.items()))
            out.append(f"  +{(rec.get('ts') or t0) - t0:>9.3f}s "
                       f"{rec['kind']:28} {detail}"[:200])
    else:
        out.append("(clean run: no fault events)")
    out.append("")

    # Serving reload timeline (ISSUE 12): swaps, reload failures, and
    # warmup events from the flight window — the hot-reload story the
    # fault timeline's FAULT_KINDS filter only partially covers.
    serve_events = serve_timeline(run.get("flight_events", []))
    if serve_events:
        out.append(f"## Serving reload timeline "
                   f"({len(serve_events)} events)")
        t0 = serve_events[0].get("ts") or 0.0
        for rec in serve_events:
            extras = {k: v for k, v in rec.items()
                      if k not in ("ts", "kind", "seq")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(
                extras.items()))
            out.append(f"  +{(rec.get('ts') or t0) - t0:>9.3f}s "
                       f"{rec['kind']:24} {detail}"[:200])
        out.append("")

    # Continuous-learning timeline (ISSUE 13): the drift story — eval
    # verdicts, alarms, demotions, rollbacks — in stream order.
    drift_events = online_timeline(run.get("flight_events", []))
    if drift_events:
        out.append(f"## Continuous-learning timeline "
                   f"({len(drift_events)} events)")
        t0 = drift_events[0].get("ts") or 0.0
        for rec in drift_events:
            extras = {k: v for k, v in rec.items()
                      if k not in ("ts", "kind", "seq")}
            detail = " ".join(f"{k}={v}" for k, v in sorted(
                extras.items()))
            out.append(f"  +{(rec.get('ts') or t0) - t0:>9.3f}s "
                       f"{rec['kind']:24} {detail}"[:200])
        out.append("")

    dead = run["dead"]
    if dead:
        out.append(f"## Quarantine ({len(dead)} dead-letter records)")
        reasons = Counter(r.get("reason", "?") for r in dead
                          if r.get("event") == "bad_record")
        for reason, n in reasons.most_common():
            out.append(f"  {n:>6}  {reason}")
        out.append("")

    pricing = run.get("kernel_pricing")
    if pricing:
        kernels = pricing.get("kernels") or []
        out.append(f"## Kernel pricing ({len(kernels)} row(s), "
                   f"backend={pricing.get('backend')}"
                   + (", INTERPRET — timings are emulation overhead"
                      if pricing.get("interpret") else "") + ")")
        out.append(f"{'kernel':28} {'family':10} {'ms':>10} "
                   f"{'model GB/s':>11}  note")
        for row in kernels:
            if row.get("skipped"):
                out.append(f"{row.get('kernel', '?'):28} "
                           f"{row.get('family', '?'):10} "
                           f"{'-':>10} {'-':>11}  "
                           f"skipped: {row['skipped']}"[:120])
                continue
            out.append(
                f"{row.get('kernel', '?'):28} "
                f"{row.get('family', '?'):10} "
                f"{_fmt_ms(row.get('ms')):>10} "
                f"{_fmt_ms(row.get('model_gbps')):>11}  "
                f"{row.get('note', '')}"[:120])
        out.append("")

    captures = run.get("captures") or []
    if captures:
        out.extend(render_captures(captures))

    dump = run["dump"]
    if dump:
        out.append(f"last flight dump: reason={dump.get('reason')!r} "
                   f"events={len(dump.get('events') or [])}")
    return "\n".join(out) + "\n"


def _latest_run_dir(root: str) -> str | None:
    try:
        runs = [os.path.join(root, d) for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d))]
    except OSError:
        return None
    return max(runs, key=os.path.getmtime) if runs else None


def _run_dir_by_id(root: str, run_id: str) -> str | None:
    """Explicit run-id selection (ISSUE 14 satellite): `--latest`'s
    mtime pick is wrong when a serve daemon keeps its run dir hot —
    the run you want to inspect is named, not newest."""
    path = os.path.join(root, run_id)
    return path if os.path.isdir(path) else None


def select_run_dir(args: list, default_root: str) -> "str | int":
    """Shared --latest / --run-id / positional-dir selection for this
    report and tools/run_doctor.py. Returns the run dir, or an int
    exit code: not-found complaints are printed here (tool-agnostic),
    but a USAGE error (2) returns silently — each caller prints its
    OWN usage doc, never this module's."""
    if args and args[0] == "--run-id":
        if len(args) < 2:
            return 2
        root = args[2] if len(args) > 2 else default_root
        obs_dir = _run_dir_by_id(root, args[1])
        if obs_dir is None:
            print(f"no run directory {args[1]!r} under {root}",
                  file=sys.stderr)
            return 1
        return obs_dir
    if args and args[0] == "--latest":
        root = args[1] if len(args) > 1 else default_root
        obs_dir = _latest_run_dir(root)
        if obs_dir is None:
            print(f"no run directories under {root}", file=sys.stderr)
            return 1
        return obs_dir
    if len(args) == 1:
        return args[0]
    return 2


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    obs_dir = select_run_dir(args, os.path.join(_REPO, "artifacts",
                                                "obs"))
    if isinstance(obs_dir, int):
        if obs_dir == 2:
            print(__doc__, file=sys.stderr)
        return obs_dir
    if not os.path.isdir(obs_dir):
        print(f"not a directory: {obs_dir}", file=sys.stderr)
        return 1
    sys.stdout.write(render(load_run(obs_dir)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
