"""Assert README.md's machine-owned numbers match reality (VERDICT r4
Weak #4 / next-round #6: the test count drifted by hand two rounds
running — stop typing it, assert it).

Usage (end-of-round doc pass, and any time the suite changes):

    python tools/readme_check.py          # check, exit 1 on drift
    python tools/readme_check.py --fix    # rewrite README's numbers

Two machine-editable sentences are owned here:

- ``NNN tests (NNN fast + NN slow)`` — the collected pytest counts;
- ``NN fmlint rules`` (ISSUE 15) — the registered static-analysis
  rule count, read from the fmlint registry so README's rule glossary
  header can never drift from the code.
"""

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(REPO, "README.md")
PATTERN = re.compile(r"(\d+) tests\s*\((\d+) fast \+ (\d+) slow\)")
RULES_PATTERN = re.compile(r"(\d+) fmlint rules")


def registered_rule_count() -> int:
    """The fmlint registry's rule count, loaded by path (no jax)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fmlint import load_analysis

    return len(load_analysis(REPO).all_rules())


def collected_counts() -> tuple[int, int]:
    """(total, slow) from pytest --collect-only."""

    def count(extra):
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/", "-q",
             "--collect-only", *extra],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout
        # With -m filtering pytest prints "41/373 tests collected
        # (332 deselected)" — the selected count is BEFORE the slash,
        # so try that form first (a bare search for 'N tests collected'
        # would match the total after the slash).
        m = re.search(r"(\d+)/\d+ tests collected", out)
        if not m:
            m = re.search(r"(\d+) tests collected", out)
        if not m:
            raise SystemExit(
                f"could not parse pytest --collect-only output:\n{out[-500:]}")
        return int(m.group(1))

    total = count([])
    slow = count(["-m", "slow"])
    return total, slow


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fix", action="store_true",
                    help="rewrite README.md's counts instead of failing")
    args = ap.parse_args()

    total, slow = collected_counts()
    fast = total - slow
    want = f"{total} tests ({fast} fast + {slow} slow)"
    want_rules = f"{registered_rule_count()} fmlint rules"

    text = open(README).read()
    m = PATTERN.search(text)
    if not m:
        raise SystemExit(
            "README.md does not contain the machine-editable counts "
            "sentence 'NNN tests (NNN fast + NN slow)'")
    mr = RULES_PATTERN.search(text)
    if not mr:
        raise SystemExit(
            "README.md does not contain the machine-editable rule "
            "count sentence 'NN fmlint rules' (ISSUE 15)")
    have, have_rules = m.group(0), mr.group(0)
    if have == want and have_rules == want_rules:
        print(f"README counts OK: {want}; {want_rules}")
        return 0
    if args.fix:
        text = PATTERN.sub(want, text, count=1)
        text = RULES_PATTERN.sub(want_rules, text, count=1)
        open(README, "w").write(text)
        print(f"README updated: {have!r} -> {want!r}; "
              f"{have_rules!r} -> {want_rules!r}")
        return 0
    print(f"README count DRIFT: README says {have!r} / {have_rules!r}, "
          f"want {want!r} / {want_rules!r}; run tools/readme_check.py "
          "--fix")
    return 1


if __name__ == "__main__":
    sys.exit(main())
