"""Assert README.md's test numbers match the collected suite (VERDICT r4
Weak #4 / next-round #6: the count drifted by hand two rounds running —
stop typing it, assert it).

Usage (end-of-round doc pass, and any time the suite changes):

    python tools/readme_check.py          # check, exit 1 on drift
    python tools/readme_check.py --fix    # rewrite README's numbers

The README must state the counts in the exact machine-editable form
``NNN tests (NNN fast + NN slow)`` — this tool owns that sentence.
"""

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(REPO, "README.md")
PATTERN = re.compile(r"(\d+) tests\s*\((\d+) fast \+ (\d+) slow\)")


def collected_counts() -> tuple[int, int]:
    """(total, slow) from pytest --collect-only."""

    def count(extra):
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "tests/", "-q",
             "--collect-only", *extra],
            cwd=REPO, capture_output=True, text=True, check=True,
        ).stdout
        # With -m filtering pytest prints "41/373 tests collected
        # (332 deselected)" — the selected count is BEFORE the slash,
        # so try that form first (a bare search for 'N tests collected'
        # would match the total after the slash).
        m = re.search(r"(\d+)/\d+ tests collected", out)
        if not m:
            m = re.search(r"(\d+) tests collected", out)
        if not m:
            raise SystemExit(
                f"could not parse pytest --collect-only output:\n{out[-500:]}")
        return int(m.group(1))

    total = count([])
    slow = count(["-m", "slow"])
    return total, slow


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fix", action="store_true",
                    help="rewrite README.md's counts instead of failing")
    args = ap.parse_args()

    total, slow = collected_counts()
    fast = total - slow
    want = f"{total} tests ({fast} fast + {slow} slow)"

    text = open(README).read()
    m = PATTERN.search(text)
    if not m:
        raise SystemExit(
            "README.md does not contain the machine-editable counts "
            "sentence 'NNN tests (NNN fast + NN slow)'")
    have = m.group(0)
    if have == want:
        print(f"README test counts OK: {want}")
        return 0
    if args.fix:
        open(README, "w").write(PATTERN.sub(want, text, count=1))
        print(f"README updated: {have!r} -> {want!r}")
        return 0
    print(f"README test-count DRIFT: README says {have!r}, "
          f"collected {want!r}; run tools/readme_check.py --fix")
    return 1


if __name__ == "__main__":
    sys.exit(main())
