"""Reproducible build for the native kernels: fasthash.cpp → libfmfast.so.

The checked-in shared library would otherwise be an opaque binary with
no recorded recipe — this script IS the recipe (compiler flags pinned
below, the same line ``fm_spark_tpu/native/__init__.py`` uses for its
lazy on-import rebuild) plus a drift detector:

    python tools/build_native.py            # (re)build in place
    python tools/build_native.py --check    # build to a temp dir and
                                            # diff exported fm_* symbols
                                            # against EXPECTED_SYMBOLS
                                            # and the shipped .so
    python tools/build_native.py --print-symbols

``--check`` exits nonzero when the source exports a symbol set that
differs from :data:`EXPECTED_SYMBOLS` (someone added an entry point
without registering it here — the ctypes bindings guard symbols
individually, so a stale .so degrades silently instead of failing; this
check is what turns red) or when the SHIPPED .so is missing one (a
stale cached artifact). Tier-1 wiring: tests/test_native_stream.py runs
``--check`` and skips cleanly when no compiler is present.
"""

import argparse
import ctypes
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "fm_spark_tpu", "native", "fasthash.cpp")
SO = os.path.join(REPO, "fm_spark_tpu", "native", "libfmfast.so")

#: Pinned compiler + flags — keep in sync with native/__init__.py _build().
COMPILER = "g++"
FLAGS = ("-O3", "-shared", "-fPIC", "-std=c++17")

#: The extern "C" surface the ctypes bindings may bind. Adding an entry
#: point to fasthash.cpp without listing it here fails --check.
EXPECTED_SYMBOLS = (
    "fm_murmur3_32",
    "fm_hash_bytes_batch",
    "fm_hash_u64_batch",
    "fm_parse_criteo",
    "fm_parse_criteo_rows",
    "fm_parse_avazu_rows",
    "fm_parse_libsvm_rows",
    "fm_dedup_aux",
    "fm_compact_aux",
    "fm_gather_rows",
)


def compiler_available() -> bool:
    return shutil.which(COMPILER) is not None


def build(out_path: str) -> None:
    """Compile SRC → out_path with the pinned flags (raises on failure)."""
    cmd = [COMPILER, *FLAGS, SRC, "-o", out_path]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(
            f"{' '.join(cmd)} failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-2000:]}"
        )


def exported_symbols(so_path: str) -> list[str]:
    """fm_* symbols exported by a shared library. Prefers ``nm -D``
    (sees everything); falls back to ctypes lookups against
    EXPECTED_SYMBOLS when binutils is absent (extra symbols then go
    undetected, missing ones do not)."""
    nm = shutil.which("nm")
    if nm is not None:
        proc = subprocess.run([nm, "-D", "--defined-only", so_path],
                              capture_output=True, text=True)
        if proc.returncode == 0:
            return sorted(
                line.split()[-1] for line in proc.stdout.splitlines()
                if line.split() and line.split()[-1].startswith("fm_")
            )
    lib = ctypes.CDLL(so_path)
    return sorted(s for s in EXPECTED_SYMBOLS if hasattr(lib, s))


def check() -> int:
    """Build fresh, diff symbols vs EXPECTED_SYMBOLS and the shipped .so."""
    rc = 0
    with tempfile.TemporaryDirectory(prefix="fm_build_native_") as tmp:
        fresh = os.path.join(tmp, "libfmfast.so")
        build(fresh)
        got = set(exported_symbols(fresh))
        want = set(EXPECTED_SYMBOLS)
        if got != want:
            rc = 1
            for sym in sorted(want - got):
                print(f"MISSING from fresh build: {sym}", file=sys.stderr)
            for sym in sorted(got - want):
                print(f"UNREGISTERED export: {sym} (add it to "
                      "EXPECTED_SYMBOLS)", file=sys.stderr)
        if os.path.exists(SO):
            shipped = set(exported_symbols(SO))
            for sym in sorted(want - shipped):
                rc = 1
                print(f"shipped libfmfast.so is STALE: missing {sym} "
                      "(rerun tools/build_native.py)", file=sys.stderr)
        else:
            print("note: no shipped libfmfast.so (first use will build it)")
    if rc == 0:
        print(f"symbol check OK: {len(want)} exported fm_* symbols")
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="build to a temp dir and diff exported symbols "
                         "instead of overwriting the shipped .so")
    ap.add_argument("--print-symbols", action="store_true",
                    dest="print_symbols",
                    help="list the shipped library's fm_* exports")
    args = ap.parse_args()
    if args.print_symbols:
        if not os.path.exists(SO):
            print(f"error: {SO} does not exist (run tools/build_native.py "
                  "first)", file=sys.stderr)
            return 2
        for sym in exported_symbols(SO):
            print(sym)
        return 0
    if not compiler_available():
        print(f"error: {COMPILER} not found on PATH", file=sys.stderr)
        return 2
    if args.check:
        return check()
    build(SO)
    print(f"built {SO}")
    for sym in exported_symbols(SO):
        print(f"  {sym}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
