#!/usr/bin/env python
"""Hash-collision auditor for the bucket hash (ISSUE 16).

The bench_embed.py ladder's quality claim rests on the hashing trick:
at 10M/100M/1B buckets, how many distinct tokens silently share a row?
This tool MEASURES the collision rate of the production bucket fn —
``murmur3_u64(token) % m`` (data/hashing.py, the same x86_32 Murmur3
the text parsers and the C++ extension implement bit-for-bit) — per
feature-axis decade, and compares it against the analytic
uniform-hashing expectation

    E[colliding tokens] = n − m·(1 − (1 − 1/m)^n)   ≈ n²/(2m) for n ≪ m

(n tokens into m buckets; a "colliding token" is one that landed in a
bucket some earlier token already occupied). A hash materially WORSE
than uniform at any decade would mean the ladder's quality numbers
degrade faster than the axis grows — tests/test_hash_audit.py pins the
measured curve to the expectation so that claim is continuously
checked, not asserted once in a doc.

Usage::

    python tools/hash_audit.py                     # 1M tokens/decade
    python tools/hash_audit.py --tokens 200000 --decades 10000000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: The bench_embed ladder's decades — audit where the ladder measures.
DECADES = (10_000_000, 100_000_000, 1_000_000_000)


def expected_collision_fraction(n: int, m: int) -> float:
    """Uniform-hashing expectation of the colliding-token fraction:
    ``(n − m·(1 − (1 − 1/m)^n)) / n``, computed in log space (the
    direct ``(1−1/m)^n`` underflows no decade here, but log1p keeps
    the small-n/m ratio exact to fp64)."""
    occupied = m * -np.expm1(n * np.log1p(-1.0 / m))
    return float((n - occupied) / n)


def audit_decade(n_tokens: int, m: int, seed: int = 0) -> dict:
    """Hash ``n_tokens`` distinct uint64 tokens into ``m`` buckets with
    the production fn; return measured vs expected collision stats."""
    from fm_spark_tpu.data.hashing import murmur3_u64

    rng = np.random.default_rng(np.random.SeedSequence([seed, m]))
    # Distinct random uint64 tokens: collisions measured downstream of
    # the hash, never manufactured upstream of it.
    tokens = rng.choice(np.iinfo(np.int64).max, size=n_tokens,
                        replace=False).astype(np.uint64)
    buckets = murmur3_u64(tokens) % np.uint64(m)
    distinct = np.unique(buckets).size
    colliding = n_tokens - distinct
    expected = expected_collision_fraction(n_tokens, m)
    return {
        "buckets": m,
        "tokens": n_tokens,
        "colliding_tokens": int(colliding),
        "collision_rate": colliding / n_tokens,
        "expected_rate": expected,
        "ratio_vs_uniform": (colliding / n_tokens) / expected
        if expected > 0 else None,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="hash_audit")
    ap.add_argument("--tokens", type=int, default=1_000_000,
                    help="distinct tokens hashed per decade")
    ap.add_argument("--decades", default=None,
                    help="comma-separated bucket counts (default: "
                         "10M,100M,1B — the bench_embed ladder)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-ratio", type=float, default=1.25,
                    dest="max_ratio",
                    help="fail (exit 1) if measured/expected exceeds "
                         "this at any decade (Poisson noise at 1B "
                         "buckets is ~±5%% on 1M tokens; 1.25 flags a "
                         "broken hash, not weather)")
    args = ap.parse_args(argv)

    decades = (tuple(int(d) for d in args.decades.split(",") if d)
               if args.decades else DECADES)
    rows = [audit_decade(args.tokens, m, args.seed) for m in decades]
    worst = max((r["ratio_vs_uniform"] or 0.0) for r in rows)
    result = {"tool": "hash_audit", "tokens": args.tokens,
              "rows": rows, "worst_ratio_vs_uniform": worst,
              "ok": worst <= args.max_ratio}
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
