#!/usr/bin/env python
"""Automated run doctor: attribute where a run's time went and why.

The diagnostic end of the perf-provenance layer (ISSUE 9). Given a
per-run telemetry directory (``artifacts/obs/<run_id>/``), the doctor
turns the run's streams into ONE screen a human can act on:

- **Where the time went** — the compile-vs-execute split (bench legs:
  leg-span wall minus the ledger's timed window; train runs: the PR-7
  first-step fence's ``compile_split`` events), ingest busy time (from
  the rows/sec gauge + row counters), fault/backoff wall (the
  resilience spans), eval, and the unattributed remainder — each as a
  share of the observed wall-clock;
- **Per-leg verdicts** — every ``bench_leg`` ledger record for this
  run: variant, rate, the sentinel verdict, attachment health, HBM
  peak, and the degraded/fused_fallback stamps;
- **Fault timeline** — event-kind counts plus total backoff seconds;
- **Serving** (ISSUE 12) — request/batch latency percentiles, the
  ``serve_bench`` ledger rows with their sentinel verdicts, the
  reload/swap timeline, staleness + degraded-mode state, and the
  chaos auditor's serving-invariant verdict;
- **Continuous learning** (ISSUE 13) — the ``quality_eval`` AUC series
  with sentinel verdicts, the drift timeline (alarms, demotions,
  rollbacks, pointer republishes), and the rollback/quarantined-
  generation counters;
- **Static analysis** (ISSUE 15) — the run's ``fmlint.json`` report
  (written by ``tools/fmlint.py`` into the same run dir): per-rule
  finding counts, unbaselined (build-failing) findings, reasoned
  suppressions, and the baseline burn-down — analysis regressions
  render next to perf ones;
- **Request tracing** (ISSUE 18) — the top-k slowest distributed
  traces merged from every process's span file under the obs root,
  with each trace's dominant hop named and torn/incomplete traces
  flagged (the write side lives in ``fm_spark_tpu/obs/trace.py``;
  the merge logic in ``tools/trace_report.py``);
- **Storage health** (ISSUE 20) — the durable-write seam's failure
  counters by path class, the ``obs/io_degraded`` gauge + swallowed-
  failure window, the checkpoint tier's retry/backoff table and
  ENOSPC emergency-GC events, and the io-fault timeline; a
  ``DISK_DEGRADED`` finding lands in the diagnosis when the obs tier
  ran degraded (rendered only for runs that hit the fault surface);
- **Diagnosis** — the doctor's findings: cold-cache compile domination,
  attachment weather, ingest-bound execution, degraded/fallback legs,
  statistically-regressed legs, stale/degraded/regressed serving,
  drift rollbacks and quality regressions.

The ledger is found beside the run dir by default
(``<run_dir>/../ledger.jsonl`` — the cross-run convention) or via
``--ledger``.

Usage::

    python tools/run_doctor.py artifacts/obs/<run_id>/
    python tools/run_doctor.py --latest [obs_root]
    python tools/run_doctor.py --run-id <id> [obs_root]

``--run-id`` (ISSUE 14 satellite) selects a run by name — ``--latest``
picks by mtime, which is wrong while a serve daemon keeps its own run
directory hot. The doctor also renders the run's **deep-capture
bundles** (``captures/<trigger>_<seq>/`` — trigger-fired profiler
traces + metrics/flight snapshots) and its **cost-attribution table**
(``cost_attribution`` ledger rows: measured step time x bytes-moved
model = model-implied GB/s, the autotuner's lever-ranking evidence).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_file(path, modname):
    """Standalone by-path module load (register in sys.modules BEFORE
    exec — dataclass processing looks the module up there)."""
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_TOOL_CACHE: dict = {}


def _load_tool(name):
    if name not in _TOOL_CACHE:
        _TOOL_CACHE[name] = _load_file(
            os.path.join(_REPO, "tools", f"{name}.py"),
            f"_doctor_{name}")
    return _TOOL_CACHE[name]


def _span_totals(spans: list[dict]) -> dict:
    out: dict[str, float] = {}
    for s in spans:
        out[s.get("name", "?")] = (out.get(s.get("name", "?"), 0.0)
                                   + float(s.get("dur_ms") or 0.0) / 1e3)
    return out


def _leg_rows(ledger_path: str, run_id: str) -> list[dict]:
    """This run's bench_leg ledger records (jax-free ledger load)."""
    lg = _load_file(os.path.join(_REPO, "fm_spark_tpu", "obs",
                                 "ledger.py"), "_doctor_ledger")
    return lg.PerfLedger(ledger_path).records(kind="bench_leg",
                                              run_id=run_id)


def _serve_rows(ledger_path: str, run_id: str) -> list[dict]:
    """This run's serve_bench ledger records (ISSUE 12)."""
    lg = _load_file(os.path.join(_REPO, "fm_spark_tpu", "obs",
                                 "ledger.py"), "_doctor_ledger")
    return lg.PerfLedger(ledger_path).records(kind="serve_bench",
                                              run_id=run_id)


def _quality_rows(ledger_path: str, run_id: str) -> list[dict]:
    """This run's quality_eval ledger records (ISSUE 13): the online
    loop's day-over-day AUC series."""
    lg = _load_file(os.path.join(_REPO, "fm_spark_tpu", "obs",
                                 "ledger.py"), "_doctor_ledger")
    return lg.PerfLedger(ledger_path).records(kind="quality_eval",
                                              run_id=run_id)


def _embed_rows(ledger_path: str, run_id: str) -> list[dict]:
    """This run's embed_bench ledger records (ISSUE 16): the tiered
    embedding store's ladder rungs."""
    lg = _load_file(os.path.join(_REPO, "fm_spark_tpu", "obs",
                                 "ledger.py"), "_doctor_ledger")
    return lg.PerfLedger(ledger_path).records(kind="embed_bench",
                                              run_id=run_id)


def embed_diagnose(run: dict, embed_rows: list[dict]) -> dict | None:
    """The tiered-embedding view of a run (ISSUE 16): hot-tier hit
    rate / eviction / blocking-stall gauges plus this run's
    ``embed_bench`` ladder rungs. ``None`` when the run has no
    embedding-tier footprint (the gauges only exist once a
    TieredStore served a batch)."""
    snap = run.get("snapshot") or {}
    gauges = snap.get("gauges") or {}
    has_embed = bool(embed_rows or "embed/hit_rate" in gauges)
    if not has_embed:
        return None
    return {
        "hit_rate": gauges.get("embed/hit_rate"),
        "evictions": gauges.get("embed/evictions"),
        "stall_ms": gauges.get("embed/stall_ms"),
        "rows": embed_rows,
    }


def embed_findings(embed: dict | None) -> list[str]:
    if embed is None:
        return []
    out = []
    hr = embed.get("hit_rate")
    if hr is not None and hr < 0.5:
        out.append(
            f"embed-tier hit rate {hr:.3f} — the hot tier is thrashing "
            "(working set or drift outruns capacity); raise --hot-rows "
            "or shrink --embed-bucket-rows")
    stall = embed.get("stall_ms")
    if stall is not None and stall > 0:
        out.append(
            f"embed-tier blocking stalls {stall:.1f} ms — misses the "
            "prefetcher did not hide (counted, never hidden); deepen "
            "--prefetch or slow the working-set drift")
    for r in embed.get("rows") or []:
        if r.get("parity_ok") is False:
            out.append(
                f"embed_bench {r.get('leg')}: tiered/untiered parity "
                "FAILED — the merged view diverged from the in-HBM "
                "trajectory (file this; never bench over it)")
        v = (r.get("sentinel") or {}).get("verdict")
        if v == "regressed":
            out.append(
                f"embed_bench {r.get('leg')}: sentinel verdict "
                "regressed vs its own tiered cohort")
    return out


# The durable-seam event kinds (ISSUE 20): the obs-tier swallowed
# failure, the checkpoint tier's bounded retry / ENOSPC emergency GC /
# loud give-up.
_STORAGE_KINDS = ("io_write_failed", "ckpt_io_retry",
                  "ckpt_emergency_gc", "ckpt_emergency_gc_done",
                  "checkpoint_io_error")


def storage_diagnose(run: dict, flight_events: list[dict]) -> dict | None:
    """The storage-health view of a run (ISSUE 20): the durable-write
    seam's failure counters by path class, the ``obs/io_degraded``
    gauge, the checkpoint tier's retry/backoff and emergency-GC
    evidence, and the io-fault event timeline. ``None`` when the run
    never hit the fault surface (counters/gauge unset, no io events) —
    a healthy disk renders no section."""
    snap = run.get("snapshot") or {}
    gauges = snap.get("gauges") or {}
    counters = snap.get("counters") or {}
    events = [e for e in flight_events
              if str(e.get("kind", "")) in _STORAGE_KINDS]
    write_failed = counters.get("io.write_failed_total") or 0
    retries = counters.get("checkpoint.io_retries_total") or 0
    gcs = counters.get("checkpoint.emergency_gc_total") or 0
    degraded = gauges.get("obs/io_degraded")
    if not (events or write_failed or retries or gcs or degraded):
        return None
    prefix = "io.write_failed."
    by_class = {k[len(prefix):-len("_total")]: v
                for k, v in sorted(counters.items())
                if k.startswith(prefix) and k.endswith("_total")}
    # Degraded-obs window: the span of swallowed best-effort failures —
    # the stretch of this run whose telemetry has holes on disk.
    besteff = [e for e in events if e.get("kind") == "io_write_failed"
               and e.get("best_effort")]
    window = None
    if besteff:
        ts = [float(e.get("ts") or 0.0) for e in besteff]
        window = {"first_ts": min(ts), "last_ts": max(ts),
                  "n": len(besteff)}
    return {
        "degraded": degraded,
        "write_failed_total": write_failed,
        "by_class": by_class,
        "retries": retries,
        "retry_rows": [e for e in events
                       if e.get("kind") == "ckpt_io_retry"],
        "emergency_gcs": gcs,
        "gc_rows": [e for e in events
                    if e.get("kind") == "ckpt_emergency_gc"],
        "io_errors": [e for e in events
                      if e.get("kind") == "checkpoint_io_error"],
        "degraded_window": window,
        "events": events,
    }


def storage_findings(storage: dict | None) -> list[str]:
    """Storage-health one-liners for the diagnosis section."""
    if storage is None:
        return []
    out = []
    for e in storage["io_errors"]:
        out.append(
            f"CHECKPOINT IO ERROR: durable write of {e.get('path')} "
            f"failed loud (errno {e.get('errno')}) after bounded "
            "retries/emergency GC — the chain stopped advancing; fix "
            "the disk, then resume from last_good")
    if storage["degraded"] or storage["degraded_window"]:
        w = storage["degraded_window"] or {}
        out.append(
            f"DISK_DEGRADED: {w.get('n', '?')} obs-tier write "
            "failure(s) swallowed (obs/io_degraded gauge set) — the "
            "telemetry record on disk has holes; training/serving "
            "bytes are unaffected by design (best-effort tier)")
    if storage["emergency_gcs"]:
        steps = sorted({s for e in storage["gc_rows"]
                        for s in (e.get("steps") or [])})
        out.append(
            f"{storage['emergency_gcs']:.0f} ENOSPC emergency GC "
            f"pass(es) collected demoted generation(s) {steps} — "
            "journaled before deletion; last_good never a candidate")
    if storage["retries"] and not storage["io_errors"]:
        out.append(
            f"transient disk errors absorbed: "
            f"{storage['retries']:.0f} bounded checkpoint "
            "retry/backoff(s), chain committed")
    return out


def _cost_rows(ledger_path: str, run_id: str) -> list[dict]:
    """This run's cost_attribution ledger records (ISSUE 14): measured
    step time paired with the bytes-moved model per leg/kernel."""
    lg = _load_file(os.path.join(_REPO, "fm_spark_tpu", "obs",
                                 "ledger.py"), "_doctor_ledger")
    return lg.PerfLedger(ledger_path).records(kind="cost_attribution",
                                              run_id=run_id)


def online_diagnose(run: dict, timeline: list[dict],
                    quality_rows: list[dict]) -> dict | None:
    """The continuous-learning view of a run (ISSUE 13): the AUC/
    drift-score gauges, rollback/demotion counters, and the drift
    event timeline (pre-deduped by ``obs_report.online_timeline`` —
    a journaled event and its flight-ring mirror are the same
    transition). ``None`` when the run has no online footprint."""
    snap = run.get("snapshot") or {}
    gauges = snap.get("gauges") or {}
    counters = snap.get("counters") or {}
    events = timeline
    # A genuine ONLINE footprint is required — a plain offline run's
    # divergence_detected (loss-spike guard) rides the same timeline
    # helper but must not conjure a Continuous-learning section.
    has_online = bool(
        quality_rows or counters.get("online.days_total")
        or any(str(e.get("kind", "")).startswith(("online_",
                                                  "quality_eval"))
               for e in events))
    if not has_online:
        return None
    return {
        "auc": gauges.get("online/auc"),
        "drift_score": gauges.get("online/drift_score"),
        "quarantined": gauges.get(
            "checkpoint/quarantined_generations") or 0,
        "days": counters.get("online.days_total") or 0,
        "rollbacks": counters.get("online.rollbacks_total") or 0,
        "demotions": counters.get("checkpoint.demotions_total") or 0,
        "events": events,
        "quality_rows": quality_rows,
    }


def online_findings(online: dict | None) -> list[str]:
    """Continuous-learning one-liners for the diagnosis section."""
    if online is None:
        return []
    out = []
    if online["rollbacks"]:
        out.append(
            f"DRIFT ROLLBACK: {online['rollbacks']:.0f} coordinated "
            f"rollback(s), {online['demotions']:.0f} generation(s) "
            "demoted — the chain's tombstoned saves will never serve; "
            "check the eval-day AUC series for when the world moved")
    elif online["quarantined"]:
        out.append(
            f"{online['quarantined']:.0f} quarantined generation(s) "
            "in the chain (tombstoned by an earlier run)")
    regressed = [r for r in online["quality_rows"]
                 if (r.get("sentinel") or {}).get("verdict")
                 == "regressed"]
    if regressed:
        out.append(
            f"QUALITY REGRESSED: eval AUC {regressed[-1].get('value')}"
            f" on day {regressed[-1].get('day')} — "
            f"{(regressed[-1].get('sentinel') or {}).get('reason')}")
    if not out and online["days"]:
        out.append(
            f"online learning clean: {online['days']:.0f} day(s) "
            f"trained, AUC {online['auc']}, no drift verdicts")
    return out


def serve_diagnose(run: dict, timeline: list[dict],
                   serve_legs: list[dict]) -> dict | None:
    """The serving view of a run (ISSUE 12): latency percentiles from
    the serve histograms, the reload/swap timeline (pre-deduped by
    ``obs_report.serve_timeline``), staleness and degraded-mode state,
    and the chaos auditor's serving-invariant verdict over the
    observed event stream. ``None`` when the run has no serving
    footprint."""
    snap = run.get("snapshot") or {}
    hists = {k: v for k, v in (snap.get("histograms") or {}).items()
             if k.startswith("serve/")}
    gauges = snap.get("gauges") or {}
    counters = snap.get("counters") or {}
    if not (hists or timeline or serve_legs):
        return None
    # Standalone by-path load (fm_spark_tpu/resilience/chaos_audit.py
    # is import-free by design) — the doctor stays jax-light.
    audit = _load_file(
        os.path.join(_REPO, "fm_spark_tpu", "resilience",
                     "chaos_audit.py"), "_doctor_chaos_audit")

    staleness = gauges.get("serve/staleness_steps")
    # Staleness here is an OBSERVATION, not an invariant verdict: a
    # server that exits mid-stream is honestly behind the tip, and
    # only a drill (which knows recovery completed) may hold a bound
    # against it — so the doctor reports it as a finding below and
    # audits the event stream for torn swaps only.
    violations = audit.audit_serve_events(timeline)
    return {
        "histograms": hists,
        "timeline": timeline,
        "staleness_steps": staleness,
        "degraded": bool(gauges.get("serve/degraded") or 0),
        "swaps": counters.get("serve.swaps_total") or 0,
        "reload_failures": counters.get(
            "serve.reload_failures_total") or 0,
        "requests": counters.get("serve.requests_total") or 0,
        "batches": counters.get("serve.batches_total") or 0,
        "violations": violations,
    }


def serve_findings(serve: dict | None, serve_legs: list[dict]
                   ) -> list[str]:
    """Serving one-liners for the diagnosis section."""
    if serve is None:
        return []
    out = []
    for v in serve["violations"]:
        out.append(f"SERVE INVARIANT VIOLATED — {v['invariant']}: "
                   f"{v['detail']}")
    if serve["degraded"]:
        out.append(
            "serving DEGRADED: the last reload attempt failed "
            f"({serve['reload_failures']:.0f} failure(s)) — the old "
            "generation keeps serving; check the chain")
    elif serve["staleness_steps"]:
        out.append(
            f"serving stale: {serve['staleness_steps']:.0f} step(s) "
            "behind the published chain tip")
    for r in serve_legs:
        v = (r.get("sentinel") or {}).get("verdict")
        if v == "regressed":
            out.append(
                f"SERVING REGRESSED: {r.get('leg')} at "
                f"{r.get('value'):,.0f} rows/s — "
                f"{(r.get('sentinel') or {}).get('reason')}")
    if not out and (serve["requests"] or serve_legs):
        out.append(
            f"serving clean: {serve['requests']:.0f} request(s) in "
            f"{serve['batches']:.0f} micro-batch(es), "
            f"{serve['swaps']:.0f} hot swap(s), staleness "
            f"{serve['staleness_steps'] or 0:.0f}")
    return out


def fleet_diagnose(run: dict, fleet_events: list[dict]
                   ) -> dict | None:
    """The serving-fleet view of a run (ISSUE 17): per-replica
    lifecycle/generation state from the fleet health journal
    (``fleet_health.jsonl``), the front door's admission accounting
    (its ``frontdoor_summary`` journal event, falling back to the
    snapshot's ``frontdoor.*`` counters), and the replica-loss ->
    recovery timeline (each ``replica_down`` paired with that
    replica's next ``replica_ready``). ``None`` when the run has no
    fleet footprint.

    ISSUE 19 extensions: each replica loss is CLASSIFIED — a
    ``replica_drained`` healed by ``replica_ready`` with no
    ``replica_down`` between is a PARTITION (the link failed, the
    process lived; collected under ``partitions``), while a
    ``replica_down`` -> ``replica_ready`` pair is a crash+respawn
    (``recoveries``, as before) — and the autoscaler's journaled
    ``autoscale_decision`` events roll up under ``autoscale``
    (decision log, grow/shrink counts, direction changes)."""
    snap = run.get("snapshot") or {}
    snap_counters = snap.get("counters") or {}
    has_fd = any(k.startswith("frontdoor.")
                 for k in snap_counters)
    if not fleet_events and not has_fd:
        return None
    stats = None
    replicas: dict[int, dict] = {}
    recoveries: list[dict] = []
    partitions: list[dict] = []
    decisions: list[dict] = []
    for e in fleet_events:
        kind = e.get("event") or e.get("kind")
        rep = e.get("replica")
        r = None
        if rep is not None:
            r = replicas.setdefault(int(rep), {
                "replica": int(rep), "spawns": 0, "downs": 0,
                "drains": 0, "state": "?", "generation_step": None,
                "staleness_steps": None, "last_rc": None,
                "_down_ts": None, "_drain_ts": None})
        if kind == "replica_spawn" and r is not None:
            r["spawns"] += 1
            r["state"] = "starting"
        elif kind == "replica_ready" and r is not None:
            r["state"] = "ready"
            if r.get("generation_step") is None:
                r["generation_step"] = e.get("generation_step")
            if r["_down_ts"] is not None and e.get("ts") is not None:
                recoveries.append({
                    "replica": int(rep), "down_ts": r["_down_ts"],
                    "rc": r["last_rc"],
                    "recovery_s": round(e["ts"] - r["_down_ts"], 3)})
                r["_down_ts"] = None
            elif (r["_drain_ts"] is not None
                    and e.get("ts") is not None):
                # Drained then readmitted with NO death between: the
                # loss was a parent<->replica LINK failure, not a
                # crash (ISSUE 19 partition classification).
                partitions.append({
                    "replica": int(rep),
                    "drain_ts": r["_drain_ts"],
                    "heal_s": round(e["ts"] - r["_drain_ts"], 3)})
            r["_drain_ts"] = None
        elif kind == "replica_state" and r is not None:
            if e.get("generation_step") is not None:
                r["generation_step"] = e["generation_step"]
            if e.get("staleness_steps") is not None:
                r["staleness_steps"] = e["staleness_steps"]
        elif kind == "replica_down" and r is not None:
            r["downs"] += 1
            r["state"] = "dead"
            r["last_rc"] = e.get("rc")
            if e.get("ts") is not None:
                r["_down_ts"] = e["ts"]
            r["_drain_ts"] = None  # it died: a crash, not a partition
        elif kind == "replica_drained" and r is not None:
            r["state"] = "suspect"
            r["drains"] += 1
            if r["_drain_ts"] is None:
                r["_drain_ts"] = e.get("ts")
        elif kind == "replica_parked" and r is not None:
            r["state"] = "parked"
        elif kind in ("fleet_shrink", "replica_retired"):
            if r is not None:
                r["state"] = "retired"
        elif kind == "autoscale_decision":
            decisions.append({k: e.get(k) for k in
                              ("ts", "action", "reason", "tick",
                               "n_ready", "to_n", "shed_frac",
                               "fill")})
        elif kind == "frontdoor_summary":
            stats = e  # the door's closing books (flattened stats())
    if stats is None and has_fd:
        stats = {k.split(".", 1)[1].rsplit("_total", 1)[0]: v
                 for k, v in snap_counters.items()
                 if k.startswith("frontdoor.") and k.count(".") == 1}
    counters = {k: int((stats or {}).get(k) or 0)
                for k in ("accepted", "answered", "shed",
                          "shed_queue", "shed_deadline", "rejected",
                          "timeout", "failed", "retries")}
    for r in replicas.values():
        r.pop("_down_ts", None)
        r.pop("_drain_ts", None)
    gens = [r["generation_step"] for r in replicas.values()
            if r["generation_step"] is not None
            and r["state"] == "ready"]
    actions = [d.get("action") for d in decisions]
    return {
        "replicas": [replicas[i] for i in sorted(replicas)],
        "counters": counters,
        "recoveries": recoveries,
        "partitions": partitions,
        "autoscale": {
            "decisions": decisions,
            "grows": actions.count("grow"),
            "shrinks": actions.count("shrink"),
            "direction_changes": sum(
                1 for a, b in zip(actions, actions[1:]) if a != b),
        },
        "generation_skew": (max(gens) - min(gens)) if gens else 0,
    }


def fleet_findings(fleet: dict | None) -> list[str]:
    """Serving-fleet one-liners for the diagnosis section."""
    if fleet is None:
        return []
    out = []
    c = fleet["counters"]
    offered = c["accepted"] + c["shed"] + c["rejected"]
    if c["shed"] and offered and c["shed"] / offered > 0.25:
        out.append(
            f"FRONT DOOR SHEDDING {c['shed'] / offered:.0%} of "
            f"offered load ({c['shed']} of {offered}) — unbounded "
            "shed growth means the fleet is undersized for the "
            "offered SLO (add replicas or loosen deadlines)")
    if fleet["generation_skew"] > 0:
        out.append(
            f"GENERATION SKEW across ready replicas: "
            f"{fleet['generation_skew']} step(s) — identical "
            "requests score differently depending on the replica "
            "drawn; check the lagging replica's reload journal")
    closed = c["answered"] + c["timeout"] + c["failed"]
    if c["accepted"] != closed:
        out.append(
            f"FLEET BOOKS OPEN: accepted={c['accepted']} but "
            f"answered+timeout+failed={closed} — admitted request(s) "
            "without a terminal outcome")
    for rec in fleet["recoveries"]:
        out.append(
            f"replica {rec['replica']} lost (rc={rec['rc']}) and "
            f"re-admitted after {rec['recovery_s']:.3f}s — CRASH "
            "(process died, respawned)")
    for p in fleet.get("partitions", []):
        out.append(
            f"replica {p['replica']} PARTITIONED (drained with no "
            f"process death) and readmitted after "
            f"{p['heal_s']:.3f}s — link fault, not a crash; no "
            "respawn was spent on it")
    auto = fleet.get("autoscale") or {}
    if auto.get("decisions"):
        out.append(
            f"autoscaler: {auto['grows']} grow / {auto['shrinks']} "
            f"shrink decision(s), {auto['direction_changes']} "
            "direction change(s)")
        if auto["direction_changes"] > 1:
            out.append(
                "AUTOSCALER FLAPPING: more than one grow<->shrink "
                "reversal — widen the hysteresis dead band or "
                "lengthen the cooldown")
    flapping = [r for r in fleet["replicas"] if r["downs"] >= 3]
    for r in flapping:
        out.append(
            f"replica {r['replica']} CRASH-LOOPING: {r['downs']} "
            f"death(s) over {r['spawns']} spawn(s) — check "
            "fleet/replica_*.stderr")
    if not out and (c["accepted"] or fleet["replicas"]):
        out.append(
            f"fleet clean: {c['accepted']} accepted / "
            f"{c['answered']} answered, {c['shed']} shed, "
            f"{c['retries']} retried, {len(fleet['replicas'])} "
            "replica(s)")
    return out


def tracing_diagnose(obs_dir: str) -> dict | None:
    """The distributed-tracing view of a run (ISSUE 18): merge every
    process's span file under the shared obs ROOT (the run dir's
    parent — front door, fleet parent, replicas and the client each
    keep their own run dir there), rank traces by end-to-end wall,
    and name the dominant hop of each. ``None`` when nothing under
    the root carries a ``trace`` id."""
    tr = _load_tool("trace_report")
    root = os.path.dirname(os.path.normpath(obs_dir))
    merged = tr.merge(root)
    if not merged:
        return None
    ranked = sorted(merged.values(), key=lambda t: -t["total_ms"])
    rows = []
    for t in ranked[:5]:
        bd = tr.breakdown(t)
        rows.append({
            "trace_id": t["trace_id"], "total_ms": t["total_ms"],
            "hops": t["hops"], "pids": len(t["pids"]),
            "dominant": bd.get("dominant"),
            "incomplete": t["incomplete"],
        })
    ex = tr.tail_exemplar(root)
    if ex is not None:
        ex = dict(ex)
        ex["resolved"] = ex["trace_id"] in merged
    return {
        "n_traces": len(merged),
        "incomplete": sum(t["incomplete"] for t in merged.values()),
        "top": rows,
        "exemplar": ex,
        "root": root,
    }


def tracing_findings(tracing: dict | None) -> list[str]:
    """Distributed-tracing one-liners for the diagnosis section."""
    if tracing is None:
        return []
    out = []
    if tracing["top"]:
        t = tracing["top"][0]
        out.append(
            f"slowest trace {t['trace_id']}: {t['total_ms']:.2f} ms "
            f"end-to-end across {t['pids']} process(es) — dominant "
            f"hop {t['dominant'] or '?'}")
    if tracing["incomplete"]:
        out.append(
            f"{tracing['incomplete']} of {tracing['n_traces']} "
            "trace(s) INCOMPLETE (torn span file, or a replica lost "
            "mid-request) — the surviving hops still render; "
            "tools/trace_report.py --trace <id> shows the hole")
    ex = tracing.get("exemplar")
    if ex is not None and not ex["resolved"]:
        out.append(
            f"tail exemplar trace {ex['trace_id']} does NOT resolve "
            "to a merged trace — a process's trace.jsonl is missing "
            "from the obs root (sampled out, or the writer died "
            "before its first flush)")
    return out


def diagnose(run: dict, legs: list[dict],
             flight_events: list[dict]) -> dict:
    """The attribution numbers (testable separately from rendering)."""
    spans = run["spans"]
    totals = _span_totals(spans)
    starts = [s["t_start"] for s in spans
              if s.get("t_start") is not None]
    ends = [s["t_start"] + float(s.get("dur_ms") or 0.0) / 1e3
            for s in spans if s.get("t_start") is not None]
    wall = (max(ends) - min(starts)) if starts else 0.0

    # Bench legs: span wall minus the ledger's timed window is the
    # compile + warmup (+ retry) share of that leg.
    timed_by_label = {r.get("variant"): float(r.get("dt_s") or 0.0)
                      for r in legs}
    leg_span_s = 0.0
    leg_timed_s = 0.0
    for s in spans:
        if s.get("name") != "bench/leg":
            continue
        dur = float(s.get("dur_ms") or 0.0) / 1e3
        leg_span_s += dur
        leg_timed_s += min(timed_by_label.get(s.get("label"), 0.0), dur)

    # Train runs: the first-step fence records the compile directly.
    compile_events = [e for e in flight_events
                      if e.get("kind") == "compile_split"]
    fence_compile_s = sum(float(e.get("first_step_ms") or 0.0) / 1e3
                          for e in compile_events)
    fresh_compiles = sum(int(e.get("fresh_compiles") or 0)
                         for e in compile_events)

    compile_s = max(leg_span_s - leg_timed_s, 0.0) + fence_compile_s
    execute_s = leg_timed_s + totals.get("train/steps", 0.0)
    fault_s = (totals.get("resilience/backoff", 0.0)
               + totals.get("resilience/probe", 0.0))
    eval_s = totals.get("train/eval", 0.0)

    snap = run.get("snapshot") or {}
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    rows_ok = counters.get("ingest.rows_ok_total") or 0.0
    rate = gauges.get("ingest.rows_per_sec")
    ingest_s = (rows_ok / rate) if rate else 0.0

    attributed = compile_s + execute_s + fault_s + eval_s
    other_s = max(wall - attributed, 0.0)

    timeline = run["timeline"]
    kinds: dict[str, int] = {}
    for e in timeline:
        kinds[e["kind"]] = kinds.get(e["kind"], 0) + 1

    return {
        "wall_s": wall,
        "phases": {
            "compile+warmup": compile_s,
            "execute": execute_s,
            "faults/backoff": fault_s,
            "eval": eval_s,
            "other": other_s,
        },
        "ingest_busy_s": ingest_s,
        "fresh_compiles": fresh_compiles,
        "fault_kinds": kinds,
        "backoff_s": totals.get("resilience/backoff", 0.0),
    }


def load_chaos_verdict(obs_dir: str) -> dict | None:
    """The run's chaos-campaign verdict (``chaos_verdict.json``,
    written by tools/chaos_drill.py), if this run dir holds one."""
    path = os.path.join(obs_dir, "chaos_verdict.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def chaos_findings(chaos: dict | None) -> list[str]:
    """Chaos-verdict one-liners for the diagnosis section."""
    if not chaos:
        return []
    out = []
    if chaos.get("all_green"):
        out.append(
            f"chaos campaign green: {chaos.get('n_green')} seeded "
            "schedule(s), every invariant held "
            f"({chaos.get('total_s', 0):.1f}s)")
        return out
    for f in chaos.get("failures", []):
        inv = ", ".join(sorted({v["invariant"]
                                for v in f.get("violations", [])}))
        line = (f"CHAOS: seed {f.get('seed')} "
                f"({f.get('scenario')}) violated [{inv}]")
        if f.get("minimized_plan"):
            line += (f" — minimized repro "
                     f"FM_SPARK_FAULTS='{f['minimized_plan']}'")
        out.append(line)
    if chaos.get("budget_exhausted"):
        out.append(
            f"chaos campaign ran out of budget: "
            f"{chaos.get('n_skipped', 0)} schedule(s) skipped")
    return out


def load_fmlint_report(obs_dir: str) -> dict | None:
    """The run's static-analysis report (``fmlint.json``, written by
    tools/fmlint.py — ISSUE 15), if this run dir holds one."""
    path = os.path.join(obs_dir, "fmlint.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def fmlint_findings(rep: dict | None) -> list[str]:
    """Static-analysis one-liners for the diagnosis section — analysis
    regressions render next to perf ones (ISSUE 15)."""
    if not rep:
        return []
    out = []
    new = rep.get("new") or []
    if new:
        out.append(
            f"STATIC ANALYSIS: {len(new)} unbaselined finding(s) — "
            "the build is red until fixed, suppressed with a reason, "
            "or baselined")
        for f in new[:5]:
            out.append(f"  fmlint {f.get('rule')}: {f.get('path')}:"
                       f"{f.get('line')} {f.get('message', '')[:90]}")
    elif rep.get("baselined_total"):
        out.append(
            f"fmlint: clean vs baseline, {rep['baselined_total']} "
            "baselined finding(s) still burning down")
    else:
        out.append("fmlint: clean — zero findings beyond reasoned "
                   "suppressions")
    if rep.get("burned_down"):
        out.append(
            f"fmlint baseline burn-down: {len(rep['burned_down'])} "
            "(rule, file) cell(s) below budget — run tools/fmlint.py "
            "--write-baseline to lock the progress in")
    return out


def render_fmlint(rep: dict | None) -> list[str]:
    """The Static-analysis section lines ('' terminated), or []."""
    if not rep:
        return []
    counts = rep.get("counts") or {}
    out = [f"## Static analysis (fmlint — "
           f"{len(rep.get('rules') or {})} rule(s), "
           f"{'OK' if rep.get('ok') else 'FAILING'})"]
    total = rep.get("total_findings", 0)
    out.append(f"  findings {total}  new {len(rep.get('new') or [])}  "
               f"baselined {rep.get('baselined_total', 0)}  "
               f"suppressed {len(rep.get('suppressed') or [])}  "
               f"burned-down {len(rep.get('burned_down') or [])}")
    for rule_id in sorted(counts):
        files = counts[rule_id]
        out.append(f"  {rule_id:24} {sum(files.values()):>4}  "
                   f"in {len(files)} file(s)")
    for f in (rep.get("new") or [])[:10]:
        out.append(f"  NEW {f.get('path')}:{f.get('line')} "
                   f"[{f.get('rule')}] {f.get('message', '')[:80]}")
    out.append("")
    return out


def findings(diag: dict, legs: list[dict]) -> list[str]:
    """The doctor's opinionated one-liners."""
    out = []
    wall = diag["wall_s"] or 1e-9
    ph = diag["phases"]
    if ph["compile+warmup"] / wall > 0.30:
        fresh = (f" ({diag['fresh_compiles']} fresh XLA compiles)"
                 if diag["fresh_compiles"] else "")
        out.append(
            f"compile-dominated: {ph['compile+warmup'] / wall:.0%} of "
            f"wall-clock in compile/warmup{fresh} — warm the "
            "persistent cache (--compile-cache)")
    if ph["faults/backoff"] / wall > 0.10 or diag["fault_kinds"].get(
            "circuit_open") or diag["fault_kinds"].get("permanent_fault"):
        out.append(
            "attachment weather: "
            f"{diag['fault_kinds'].get('failure', 0)} failure(s), "
            f"{diag['backoff_s']:.1f}s in backoff"
            + (", circuit opened"
               if diag["fault_kinds"].get("circuit_open") else ""))
    if diag["ingest_busy_s"] > 0.5 * max(ph["execute"], 1e-9) \
            and diag["ingest_busy_s"] > 1.0:
        out.append(
            f"ingest-bound: {diag['ingest_busy_s']:.1f}s of host parse "
            f"busy time vs {ph['execute']:.1f}s device execute — "
            "consider --native-ingest / more prefetch")
    for r in legs:
        fp = r.get("fingerprint") or {}
        v = (r.get("sentinel") or {}).get("verdict")
        if v == "regressed":
            out.append(
                f"REGRESSED: {r.get('variant')} at "
                f"{r.get('value'):,.0f} — "
                f"{(r.get('sentinel') or {}).get('reason')}")
        elif v == "attachment_transient":
            out.append(
                f"transient (weather, not code): {r.get('variant')} — "
                f"{(r.get('sentinel') or {}).get('reason')}")
        if fp.get("degraded"):
            out.append(f"degraded leg (shrunk mesh): {r.get('variant')}")
        if fp.get("fused_fallback"):
            out.append("fused-embed fallback (XLA path measured): "
                       f"{r.get('variant')}")
    if not out:
        out.append("clean run: no faults, no regressions, "
                   f"{ph['execute'] / wall:.0%} of wall-clock executing")
    return out


def capture_findings(captures: list[dict]) -> list[str]:
    """Deep-capture one-liners (ISSUE 14): a fired capture is evidence
    the operator should open, so each bundle gets a pointer."""
    out = []
    for m in captures or []:
        ctx = m.get("context") or {}
        detail = ctx.get("reason") or " ".join(
            f"{k}={v}" for k, v in sorted(ctx.items()))
        out.append(
            f"DEEP CAPTURE [{m.get('trigger')}]: {str(detail)[:120]} "
            f"— evidence at {m.get('dir')}")
    return out


def render(run: dict, diag: dict, legs: list[dict],
           chaos: dict | None = None, serve: dict | None = None,
           serve_legs: list[dict] | None = None,
           online: dict | None = None,
           cost_rows: list[dict] | None = None,
           fmlint_rep: dict | None = None,
           embed: dict | None = None,
           fleet: dict | None = None,
           tracing: dict | None = None,
           storage: dict | None = None) -> str:
    out = [f"# fm_spark_tpu run doctor — {run['run_id']}",
           f"obs dir: {run['dir']}", ""]

    out.append("## Where the time went "
               f"(observed wall-clock {diag['wall_s']:,.1f} s)")
    wall = diag["wall_s"] or 1e-9
    for name, secs in diag["phases"].items():
        out.append(f"  {name:16} {secs:>10,.2f} s  {secs / wall:>6.1%}")
    if diag["ingest_busy_s"]:
        out.append(f"  {'ingest busy':16} {diag['ingest_busy_s']:>10,.2f}"
                   " s  (host-side, overlaps execute)")
    out.append("")

    out.append(f"## Per-leg verdicts ({len(legs)} ledger record(s))")
    if legs:
        out.append(f"  {'variant':52} {'value':>12} {'verdict':>22} "
                   f"{'weather':>9} {'hbm_peak':>10}")
        for r in legs:
            fp = r.get("fingerprint") or {}
            v = r.get("value")
            peak = r.get("hbm_peak_bytes")
            stamps = "".join(
                s for s, on in (("/degraded", fp.get("degraded")),
                                ("/fallback", fp.get("fused_fallback")))
                if on)
            out.append(
                f"  {str(r.get('variant'))[:52]:52} "
                f"{(f'{v:,.0f}' if isinstance(v, (int, float)) else '-'):>12} "
                f"{((r.get('sentinel') or {}).get('verdict') or '?') + stamps:>22} "
                f"{fp.get('attachment_health', '?'):>9} "
                f"{(f'{peak / 2**30:.2f}G' if peak else '-'):>10}")
    else:
        out.append("  (no ledger records for this run — pre-ledger run, "
                   "or a train-only run)")
    out.append("")

    cost_rows = cost_rows or []
    if cost_rows:
        out.append(f"## Cost attribution ({len(cost_rows)} record(s): "
                   "measured step time x bytes-moved model)")
        out.append(f"  {'variant':52} {'GB/s(model)':>12} "
                   f"{'step_ms':>10} {'bytes/step':>12}")
        for r in cost_rows:
            v = r.get("value")
            ms = r.get("step_ms")
            bts = r.get("bytes_per_step")
            v_s = f"{v:,.1f}" if isinstance(v, (int, float)) else "-"
            ms_s = f"{ms:,.2f}" if isinstance(ms, (int, float)) else "-"
            b_s = (f"{bts / 2**20:,.1f}M"
                   if isinstance(bts, (int, float)) else "-")
            out.append(f"  {str(r.get('variant'))[:52]:52} "
                       f"{v_s:>12} {ms_s:>10} {b_s:>12}")
        out.append("")

    captures = run.get("captures") or []
    if captures:
        # One shared renderer (obs_report.render_captures) — the
        # section format can never drift between the two tools.
        out.extend(_load_tool("obs_report").render_captures(captures))

    if diag["fault_kinds"]:
        out.append("## Fault timeline (event counts)")
        for kind in sorted(diag["fault_kinds"]):
            out.append(f"  {kind:28} {diag['fault_kinds'][kind]:>5}")
        out.append("")

    if chaos is not None:
        out.append(
            f"## Chaos verdict ({chaos.get('mode', '?')} campaign, "
            f"{chaos.get('n_schedules', 0)} schedule(s))")
        out.append(
            f"  green {chaos.get('n_green', 0)}  failed "
            f"{chaos.get('n_failed', 0)}  skipped "
            f"{chaos.get('n_skipped', 0)}  "
            f"({chaos.get('total_s', 0):.1f}s)")
        for e in chaos.get("schedules", []):
            if e.get("verdict") == "green":
                continue
            out.append(f"  seed {e.get('seed')}: {e.get('verdict')} "
                       f"[{e.get('scenario')}] {e.get('plan') or ''}")
            for viol in e.get("violations", []):
                out.append(f"    - {viol['invariant']}: "
                           f"{viol['detail']}")
            if e.get("minimized_plan"):
                out.append("    minimized repro: FM_SPARK_FAULTS="
                           f"'{e['minimized_plan']}'")
        out.append("")

    serve_legs = serve_legs or []
    if serve is not None:
        out.append("## Serving")
        if serve["histograms"]:
            out.append(f"  {'latency':28} {'count':>8} {'mean_ms':>10} "
                       f"{'p50':>10} {'p95':>10} {'p99':>10}")
            for name in sorted(serve["histograms"]):
                s = serve["histograms"][name]
                out.append(
                    f"  {name:28} {s.get('count', 0):>8.0f} "
                    f"{s.get('mean') if s.get('mean') is not None else '-':>10} "
                    f"{s.get('p50') if s.get('p50') is not None else '-':>10} "
                    f"{s.get('p95') if s.get('p95') is not None else '-':>10} "
                    f"{s.get('p99') if s.get('p99') is not None else '-':>10}")
        if serve_legs:
            out.append(f"  {'serve leg':24} {'rows/s/chip':>14} "
                       f"{'p50_ms':>9} {'p99_ms':>9} {'verdict':>22}")
            for r in serve_legs:
                v = r.get("value")
                out.append(
                    f"  {str(r.get('leg'))[:24]:24} "
                    f"{(f'{v:,.0f}' if isinstance(v, (int, float)) else '-'):>14} "
                    f"{r.get('p50_ms', '-'):>9} {r.get('p99_ms', '-'):>9} "
                    f"{((r.get('sentinel') or {}).get('verdict') or '?'):>22}")
        if serve["timeline"]:
            out.append("  reload timeline:")
            t0 = serve["timeline"][0].get("ts") or 0.0
            for e in serve["timeline"]:
                extras = {k: v for k, v in e.items()
                          if k not in ("ts", "kind", "seq")}
                detail = " ".join(f"{k}={v}" for k, v in
                                  sorted(extras.items()))
                out.append(f"    +{(e.get('ts') or t0) - t0:>8.3f}s "
                           f"{e.get('kind'):20} {detail}"[:160])
        out.append(
            f"  swaps {serve['swaps']:.0f}  reload_failures "
            f"{serve['reload_failures']:.0f}  staleness "
            f"{serve['staleness_steps'] or 0:.0f}  degraded "
            f"{str(serve['degraded']).lower()}")
        out.append("")

    if fleet is not None:
        out.append("## Serving fleet")
        c = fleet["counters"]
        out.append(
            f"  accepted {c['accepted']}  answered {c['answered']}  "
            f"shed {c['shed']} (queue {c['shed_queue']} / deadline "
            f"{c['shed_deadline']})  rejected {c['rejected']}  "
            f"timeout {c['timeout']}  failed {c['failed']}  retries "
            f"{c['retries']}")
        if fleet["replicas"]:
            out.append(f"  {'replica':>8} {'state':>9} {'spawns':>7} "
                       f"{'downs':>6} {'generation':>11} "
                       f"{'staleness':>10}")
            for r in fleet["replicas"]:
                out.append(
                    f"  {r['replica']:>8} {r['state']:>9} "
                    f"{r['spawns']:>7} {r['downs']:>6} "
                    f"{str(r['generation_step'] if r['generation_step'] is not None else '-'):>11} "
                    f"{str(r['staleness_steps'] if r['staleness_steps'] is not None else '-'):>10}")
        if fleet["recoveries"] or fleet.get("partitions"):
            out.append("  replica-loss timeline (crash vs "
                       "partition):")
            losses = ([dict(r, _t=r["down_ts"], _kind="crash")
                       for r in fleet["recoveries"]]
                      + [dict(p, _t=p["drain_ts"], _kind="partition")
                         for p in fleet.get("partitions", [])])
            losses.sort(key=lambda x: x["_t"])
            t0 = losses[0]["_t"]
            for x in losses:
                if x["_kind"] == "crash":
                    out.append(
                        f"    +{x['_t'] - t0:>8.3f}s replica "
                        f"{x['replica']} down (rc={x['rc']}) -> "
                        f"ready after {x['recovery_s']:.3f}s "
                        "[crash: respawned]")
                else:
                    out.append(
                        f"    +{x['_t'] - t0:>8.3f}s replica "
                        f"{x['replica']} drained -> readmitted "
                        f"after {x['heal_s']:.3f}s [partition: "
                        "process stayed alive, no respawn]")
        auto = fleet.get("autoscale") or {}
        if auto.get("decisions"):
            out.append(
                f"  autoscale decision log ({auto['grows']} grow / "
                f"{auto['shrinks']} shrink, "
                f"{auto['direction_changes']} direction change(s)):")
            d0 = auto["decisions"][0].get("ts") or 0.0
            for d in auto["decisions"]:
                out.append(
                    f"    +{(d.get('ts') or d0) - d0:>8.3f}s "
                    f"{d.get('action'):6} -> {d.get('to_n')} "
                    f"replica(s)  [{d.get('reason')}]"[:160])
        out.append("")

    if tracing is not None:
        out.append(
            f"## Request tracing ({tracing['n_traces']} merged "
            f"trace(s), {tracing['incomplete']} incomplete)")
        out.append(f"  {'trace':>18} {'total_ms':>10} {'hops':>5} "
                   f"{'pids':>5}  dominant hop")
        for t in tracing["top"]:
            flag = "  INCOMPLETE" if t["incomplete"] else ""
            out.append(
                f"  {str(t['trace_id'])[:18]:>18} "
                f"{t['total_ms']:>10.2f} {t['hops']:>5} "
                f"{t['pids']:>5}  {t['dominant'] or '?'}{flag}")
        ex = tracing.get("exemplar")
        if ex is not None:
            out.append(
                f"  tail exemplar: trace {ex['trace_id']} at "
                f"{ex['value']:.2f} ms — "
                + ("resolves to a merged trace" if ex["resolved"]
                   else "NOT in the merged set"))
        out.append("  full hop tables: python tools/trace_report.py "
                   f"{tracing['root']}")
        out.append("")

    if embed is not None:
        out.append("## Embedding tier")
        hr = embed.get("hit_rate")
        ev = embed.get("evictions")
        stall = embed.get("stall_ms")
        out.append(
            "  hot-tier hit rate "
            + (f"{hr:.4f}" if isinstance(hr, (int, float)) else "-")
            + f"  evictions {ev if ev is not None else '-'}"
            + "  blocking stalls "
            + (f"{stall:.1f} ms" if isinstance(stall, (int, float))
               else "-"))
        if embed["rows"]:
            out.append(f"  {'ladder rung':22} {'rows/s':>12} "
                       f"{'hit':>7} {'stall_ms':>9} {'host RSS':>10} "
                       f"{'parity':>7} {'verdict':>22}")
            for r in embed["rows"]:
                v = r.get("value")
                rhr = r.get("hit_rate")
                rss = r.get("host_rss_bytes")
                par = r.get("parity_ok")
                out.append(
                    f"  {str(r.get('leg'))[:22]:22} "
                    f"{(f'{v:,.0f}' if isinstance(v, (int, float)) else '-'):>12} "
                    f"{(f'{rhr:.3f}' if isinstance(rhr, (int, float)) else '-'):>7} "
                    f"{r.get('stall_ms', '-'):>9} "
                    f"{(f'{rss / 1e9:.2f}GB' if isinstance(rss, (int, float)) else '-'):>10} "
                    f"{('-' if par is None else 'OK' if par else 'FAIL'):>7} "
                    f"{((r.get('sentinel') or {}).get('verdict') or '?'):>22}")
        out.append("")

    if storage is not None:
        out.append("## Storage health")
        cls = " / ".join(f"{k} {v:.0f}" for k, v in
                         storage["by_class"].items())
        out.append(
            f"  write failures {storage['write_failed_total']:.0f}"
            + (f" ({cls})" if cls else "")
            + f"  ckpt retries {storage['retries']:.0f}"
            + f"  emergency GCs {storage['emergency_gcs']:.0f}"
            + "  obs degraded "
            + str(bool(storage["degraded"])).lower())
        w = storage["degraded_window"]
        if w:
            out.append(
                f"  degraded-obs window: {w['n']} swallowed "
                "best-effort failure(s) over "
                f"{w['last_ts'] - w['first_ts']:.3f}s")
        if storage["retry_rows"]:
            out.append(f"  {'retry of':24} {'attempt':>8} "
                       f"{'errno':>6} {'backoff_s':>10}")
            for e in storage["retry_rows"]:
                out.append(
                    f"  {str(e.get('path'))[:24]:24} "
                    f"{e.get('attempt', '-'):>8} "
                    f"{str(e.get('errno', '-')):>6} "
                    f"{str(e.get('delay_s', '-')):>10}")
        if storage["events"]:
            out.append("  io-fault timeline:")
            t0 = storage["events"][0].get("ts") or 0.0
            for e in storage["events"][:40]:
                extras = {k: v for k, v in e.items()
                          if k not in ("ts", "kind", "seq")}
                detail = " ".join(f"{k}={v}" for k, v in
                                  sorted(extras.items()))
                out.append(f"    +{(e.get('ts') or t0) - t0:>8.3f}s "
                           f"{e.get('kind'):22} {detail}"[:160])
            if len(storage["events"]) > 40:
                out.append(f"    ... {len(storage['events']) - 40} "
                           "more io-fault event(s)")
        out.append("")

    if online is not None:
        out.append("## Continuous learning")
        if online["quality_rows"]:
            out.append(f"  {'eval day':>8} {'step':>8} {'auc':>8} "
                       f"{'verdict':>22}")
            for r in online["quality_rows"]:
                v = r.get("value")
                out.append(
                    f"  {str(r.get('day', '-')):>8} "
                    f"{str(r.get('step', '-')):>8} "
                    f"{(f'{v:.4f}' if isinstance(v, (int, float)) else '-'):>8} "
                    f"{((r.get('sentinel') or {}).get('verdict') or '?'):>22}")
        if online["events"]:
            out.append("  drift timeline:")
            t0 = online["events"][0].get("ts") or 0.0
            for e in online["events"]:
                extras = {k: v for k, v in e.items()
                          if k not in ("ts", "kind", "seq")}
                detail = " ".join(f"{k}={v}" for k, v in
                                  sorted(extras.items()))
                out.append(f"    +{(e.get('ts') or t0) - t0:>8.3f}s "
                           f"{e.get('kind'):22} {detail}"[:160])
        out.append(
            f"  days {online['days']:.0f}  rollbacks "
            f"{online['rollbacks']:.0f}  demoted generations "
            f"{online['demotions']:.0f}  quarantined "
            f"{online['quarantined']:.0f}  drift_score "
            f"{online['drift_score']}")
        out.append("")

    out.extend(render_fmlint(fmlint_rep))

    out.append("## Diagnosis")
    for line in (findings(diag, legs) + chaos_findings(chaos)
                 + serve_findings(serve, serve_legs)
                 + fleet_findings(fleet)
                 + online_findings(online)
                 + tracing_findings(tracing)
                 + embed_findings(embed)
                 + storage_findings(storage)
                 + capture_findings(run.get("captures"))
                 + fmlint_findings(fmlint_rep)):
        out.append(f"  - {line}")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    obs_report = _load_tool("obs_report")
    ledger_path = None
    if "--ledger" in args:
        i = args.index("--ledger")
        if i + 1 >= len(args):
            print(__doc__, file=sys.stderr)
            return 2
        ledger_path = args[i + 1]
        del args[i:i + 2]
    # Shared --latest / --run-id / positional selection (ISSUE 14).
    obs_dir = obs_report.select_run_dir(
        args, os.path.join(_REPO, "artifacts", "obs"))
    if isinstance(obs_dir, int):
        if obs_dir == 2:
            print(__doc__, file=sys.stderr)
        return obs_dir
    if not os.path.isdir(obs_dir):
        print(f"not a directory: {obs_dir}", file=sys.stderr)
        return 1

    run = obs_report.load_run(obs_dir)
    flight_events = obs_report._read_jsonl(
        os.path.join(obs_dir, "flight.jsonl"))
    if ledger_path is None:
        ledger_path = os.path.join(
            os.path.dirname(os.path.normpath(obs_dir)), "ledger.jsonl")
    legs = _leg_rows(ledger_path, run["run_id"])
    serve_legs = _serve_rows(ledger_path, run["run_id"])
    diag = diagnose(run, legs, flight_events)
    serve = serve_diagnose(run, obs_report.serve_timeline(flight_events),
                           serve_legs)
    online = online_diagnose(run, obs_report.online_timeline(flight_events),
                             _quality_rows(ledger_path, run["run_id"]))
    embed = embed_diagnose(run, _embed_rows(ledger_path, run["run_id"]))
    fleet = fleet_diagnose(run, obs_report._read_jsonl(
        os.path.join(obs_dir, "fleet_health.jsonl")))
    sys.stdout.write(render(run, diag, legs,
                            chaos=load_chaos_verdict(obs_dir),
                            serve=serve, serve_legs=serve_legs,
                            online=online,
                            cost_rows=_cost_rows(ledger_path,
                                                 run["run_id"]),
                            fmlint_rep=load_fmlint_report(obs_dir),
                            embed=embed, fleet=fleet,
                            tracing=tracing_diagnose(obs_dir),
                            storage=storage_diagnose(run,
                                                     flight_events)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
