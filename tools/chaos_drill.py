#!/usr/bin/env python
"""Chaos drill runner: seeded multi-fault campaigns with one verdict.

The operational front-end of :mod:`fm_spark_tpu.resilience.chaos`
(ISSUE 10). Runs N seeded schedules through the invariant auditor,
delta-debugs any failure down to a minimal reproducible plan string,
and writes the machine-readable verdict to
``artifacts/obs/<run_id>/chaos_verdict.json`` (rendered by
``tools/run_doctor.py``). Exit code 0 iff every schedule was green.

Modes::

    python tools/chaos_drill.py                      # bounded: 25 seeds
    python tools/chaos_drill.py --seeds 3,17,42      # replay exact seeds
    python tools/chaos_drill.py --soak               # long mode: 200
                                                     # seeds + subprocess
                                                     # kill/hang drills
                                                     # (nightly / TPU
                                                     # window)
    python tools/chaos_drill.py --canary             # prove the auditor
                                                     # catches a broken
                                                     # recovery path
                                                     # (exit 0 iff caught
                                                     # AND minimized)

The bounded default is exactly what tier-1 runs (tests/test_chaos.py's
soak), so a green CI round certifies the same invariants this tool
checks interactively. Every schedule is a pure function of its seed —
``--seeds <failing-seed>`` replays a verdict's repro, and the verdict's
``minimized_plan`` can be run directly via ``FM_SPARK_FAULTS``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

VERDICT_FILE = "chaos_verdict.json"

#: The tier-1 bounded campaign: fixed seed list + time budget. Fixed —
#: not configurable per run — so every CI round drills the SAME plans
#: and a regression bisects cleanly.
TIER1_SEEDS = tuple(range(25))
TIER1_BUDGET_S = 300.0
TIER1_PER_SCHEDULE_S = 30.0


def _atomic_write_json(path: str, obj) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_verdict(verdict: dict, obs_root: str,
                  run_id: str | None = None) -> str:
    """Persist one campaign verdict under ``<obs_root>/<run_id>/`` —
    the per-run obs directory convention, so run_doctor/obs_report
    find it next to any telemetry the drills produced."""
    from fm_spark_tpu import obs

    run_id = run_id or obs.new_run_id()
    run_dir = os.path.join(obs_root, run_id)
    os.makedirs(run_dir, exist_ok=True)
    verdict["run_id"] = run_id  # in place: callers render the id too
    path = os.path.join(run_dir, VERDICT_FILE)
    _atomic_write_json(path, verdict)
    return path


def render(verdict: dict) -> str:
    out = [f"# chaos campaign — {verdict.get('run_id', '?')}",
           f"schedules: {verdict['n_schedules']}  "
           f"green: {verdict['n_green']}  failed: {verdict['n_failed']}"
           f"  skipped: {verdict.get('n_skipped', 0)}  "
           f"({verdict['total_s']:.1f}s"
           + (f" of {verdict['budget_s']:.0f}s budget"
              if verdict.get("budget_s") else "") + ")", ""]
    for e in verdict["schedules"]:
        mark = {"green": "ok ", "failed": "FAIL",
                "skipped_budget": "skip"}.get(e["verdict"], "?   ")
        out.append(f"  [{mark}] seed {str(e.get('seed', '-')):>4} "
                   f"{(e.get('scenario') or '-'):14} "
                   f"{(e.get('outcome') or '-'):16} {e.get('plan') or ''}")
        for viol in e.get("violations", []):
            out.append(f"         - {viol['invariant']}: "
                       f"{viol['detail']}")
        if e.get("minimized_plan"):
            out.append(f"         minimized repro: "
                       f"FM_SPARK_FAULTS='{e['minimized_plan']}' "
                       f"(seed {e['seed']})")
    out.append("")
    out.append("ALL GREEN" if verdict["all_green"]
               else f"{verdict['n_failed']} FAILING SCHEDULE(S)")
    return "\n".join(out) + "\n"


def _soak_subprocess_drills(cfg, base_dir: str) -> list[dict]:
    """The process-fatal scenarios the in-process campaign cannot
    express: SIGKILL mid-run (spool-compaction pressure via a small
    flight ring), a watchdog-bounded real hang, and an injected init
    exit — each respawned to completion and held to the exactly-once
    + rc-discipline invariants."""
    import dataclasses

    from fm_spark_tpu.resilience import chaos

    sub_cfg = dataclasses.replace(cfg, flight_capacity=4)
    golden = chaos.golden_run(sub_cfg, os.path.join(base_dir, "golden"))
    drills = [
        ("sigkill_midrun", dict(plan="", kill_at_step=9),
         dict()),
        ("hang_ingest_watchdog",
         dict(plan="ingest_truncate@2=hang:300",
              watchdog_spec="ingest_chunk=1.5"), dict()),
        ("init_exit_respawn", dict(plan="backend_init@1=exit:3"),
         dict(expected_rcs=(0, 3))),
    ]
    entries = []
    for name, kw, extra in drills:
        t0 = time.perf_counter()
        plan = kw.pop("plan")
        r = chaos.run_schedule_subproc(
            plan, sub_cfg,
            os.path.join(base_dir, f"sub_{name}"), **kw, **extra)
        violations = []
        if r.outcome != "completed":
            violations.append({"invariant": "completion",
                               "detail": f"{r.outcome}: {r.error}"})
        else:
            try:
                if chaos.stitch_taps(r) != golden.tap:
                    violations.append({
                        "invariant": "exactly_once_stream",
                        "detail": "stitched stream != clean run"})
            except ValueError as e:
                violations.append({"invariant": "exactly_once_stream",
                                   "detail": str(e)})
            if r.loss_history != golden.loss_history:
                violations.append({"invariant": "loss_continuity",
                                   "detail": "loss curve diverged"})
        entries.append({
            "seed": None, "scenario": f"subprocess:{name}",
            "plan": plan, "expects": "completed",
            "outcome": r.outcome, "rcs": list(r.rcs),
            "verdict": "green" if not violations else "failed",
            "violations": violations,
            "duration_s": round(time.perf_counter() - t0, 3),
        })
    return entries


def _drift_entries(base_dir: str, soak: bool) -> list[dict]:
    """The continuous-learning half of the campaign (ISSUE 13): five
    seeded drift/rollback schedules drilled against the production
    online loop (planted label-flip drift, demotion tombstones,
    coordinated rollback), plus — in soak mode — the subprocess
    SIGKILL-mid-demotion drill."""
    from fm_spark_tpu.resilience import chaos

    entries = chaos.run_drift_campaign(
        base_dir=os.path.join(base_dir, "drift"))
    if soak:
        t0 = time.perf_counter()
        r = chaos.run_demote_kill_drill(
            os.path.join(base_dir, "demote_kill"))
        entries.append({
            "seed": None, "scenario": "subprocess:demote_kill",
            "plan": "ckpt_demote@1=exit:23", "expects": "recovered",
            "outcome": ("recovered" if not r["violations"]
                        else "violated"),
            "rcs": r["rcs"],
            "verdict": "green" if not r["violations"] else "failed",
            "violations": r["violations"],
            "duration_s": round(time.perf_counter() - t0, 3),
        })
    return entries


def _fleet_entries(base_dir: str, soak: bool) -> list[dict]:
    """The serving-fleet half of the campaign (ISSUE 17): seeded
    traffic-replay schedules (flash crowds, retry storms, slow
    clients) against a REAL two-replica fleet behind the front door,
    with mid-burst replica SIGKILLs, injected dispatch faults, and
    publish+demote races — audited from artifacts alone
    (:func:`chaos_audit.audit_fleet`)."""
    from fm_spark_tpu.resilience import chaos

    seeds = chaos.FLEET_SOAK_SEEDS if soak else chaos.FLEET_TIER1_SEEDS
    return chaos.run_fleet_campaign(
        seeds, base_dir=os.path.join(base_dir, "fleet"))


def _partition_entries(base_dir: str, soak: bool) -> list[dict]:
    """The partition half of the fleet campaign (ISSUE 19): seeded
    network-fault schedules (peer-scoped connect/send windows, slow
    links, response truncations) against a shared fleet with the
    autoscaler armed — graded by ``audit_fleet``'s partition
    extensions (partition_not_a_crash, autoscale_converged) on top of
    exactly-once and closed books."""
    from fm_spark_tpu.resilience import chaos

    seeds = (chaos.PARTITION_TIER1_SEEDS if not soak
             else tuple(range(4)))  # soak adds the 4th scenario class
    return chaos.run_partition_campaign(
        seeds, base_dir=os.path.join(base_dir, "partition"))


def _disk_entries(base_dir: str, soak: bool) -> list[dict]:
    """The storage half of the campaign (ISSUE 20): seeded disk-fault
    schedules (ENOSPC at a checkpoint commit over demoted
    generations, torn tombstone renames racing a serve reload, fsync
    stalls on the day-boundary save, EIO bursts on flight-spool
    compaction, a read-only obs plane) through the durable-write
    seam, graded by :func:`chaos_audit.audit_disk` — golden run first
    for the byte-identity baseline. Soak adds the subprocess
    SIGKILL-during-emergency-GC drill."""
    from fm_spark_tpu.resilience import chaos

    return chaos.run_disk_campaign(
        chaos.DISK_TIER1_SEEDS,
        base_dir=os.path.join(base_dir, "disk"),
        include_kill_drill=soak)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded chaos campaigns over the resilience stack")
    ap.add_argument("--seeds", default=None,
                    help="comma-separated seed list (default: the "
                         "fixed tier-1 list)")
    ap.add_argument("--schedules", type=int, default=None,
                    help="run seeds 0..N-1 instead of the fixed list")
    ap.add_argument("--soak", action="store_true",
                    help="long mode (nightly/TPU window): 200 seeds + "
                         "the subprocess kill/hang/init-exit drills")
    ap.add_argument("--canary", action="store_true",
                    help="deliberately break the recovery path "
                         "(restore stops rewinding the cursor) and "
                         "exit 0 iff the auditor catches it and the "
                         "minimizer reduces it to <= 2 rules")
    ap.add_argument("--budget", type=float, default=None,
                    help="campaign wall-clock budget in seconds "
                         f"(default {TIER1_BUDGET_S:.0f}, soak: none)")
    ap.add_argument("--per-schedule-timeout", type=float,
                    default=TIER1_PER_SCHEDULE_S,
                    dest="per_schedule",
                    help="flag any single drill exceeding this many "
                         "seconds")
    ap.add_argument("--no-minimize", action="store_true",
                    help="skip delta-debugging failing schedules")
    ap.add_argument("--out", default=os.path.join(_REPO, "artifacts",
                                                  "obs"),
                    help="obs root for <run_id>/chaos_verdict.json")
    ap.add_argument("--work-dir", default=None,
                    help="drill scratch dir (default: a tempdir)")
    args = ap.parse_args(argv)

    import dataclasses

    from fm_spark_tpu.resilience import chaos

    if args.seeds:
        seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    elif args.schedules is not None:
        seeds = list(range(args.schedules))
    elif args.soak:
        seeds = list(range(200))
    else:
        seeds = list(TIER1_SEEDS)
    budget = args.budget
    if budget is None and not args.soak:
        budget = TIER1_BUDGET_S

    cfg = chaos.DrillConfig(break_restore=args.canary)
    base_dir = args.work_dir or tempfile.mkdtemp(prefix="chaos_drill_")
    # The canary's success criterion IS a minimized repro, so canary
    # mode always minimizes (--no-minimize would otherwise turn a
    # caught canary into a false "auditor is blind" verdict).
    verdict = chaos.run_campaign(
        seeds, cfg=cfg, base_dir=base_dir, time_budget_s=budget,
        per_schedule_timeout_s=args.per_schedule,
        minimize_failures=args.canary or not args.no_minimize)
    extra = []
    if not args.canary and args.seeds is None and args.schedules is None:
        # Drift/rollback schedules ride every default bounded and soak
        # campaign (ISSUE 13); an explicit --seeds/--schedules run is
        # a targeted replay and drills exactly what it names, and the
        # canary's broken-restore hook has no business in the online
        # loop. Fleet/traffic schedules (ISSUE 17) ride along under
        # the same rule.
        extra.extend(_drift_entries(base_dir, soak=args.soak))
        extra.extend(_fleet_entries(base_dir, soak=args.soak))
        extra.extend(_partition_entries(base_dir, soak=args.soak))
        extra.extend(_disk_entries(base_dir, soak=args.soak))
    if args.soak:
        extra.extend(_soak_subprocess_drills(
            dataclasses.replace(cfg, break_restore=False), base_dir))
    if extra:
        verdict["schedules"].extend(extra)
        verdict["n_schedules"] += len(extra)
        verdict["n_green"] += sum(e["verdict"] == "green"
                                  for e in extra)
        fails = [e for e in extra if e["verdict"] != "green"]
        verdict["failures"].extend(fails)
        verdict["n_failed"] += len(fails)
        verdict["all_green"] = (verdict["all_green"] and not fails)
    verdict["mode"] = ("canary" if args.canary
                       else "soak" if args.soak else "bounded")

    path = write_verdict(verdict, args.out)
    sys.stdout.write(render(verdict))
    print(f"verdict: {path}")

    if args.canary:
        # Success = the broken recovery path was CAUGHT and minimized
        # to a <=2-rule reproducible plan (the ISSUE 10 acceptance
        # criterion); an all-green canary run means the auditor is
        # blind and must fail loudly.
        caught = [f for f in verdict["failures"]
                  if f.get("minimized_plan")
                  and f.get("minimized_rules", 99) <= 2]
        if caught:
            print("canary CAUGHT and minimized: "
                  f"{caught[0]['minimized_plan']!r}")
            return 0
        print("canary NOT caught — the auditor missed a broken "
              "recovery path", file=sys.stderr)
        return 1
    return 0 if verdict["all_green"] else 1


if __name__ == "__main__":
    sys.exit(main())
