#!/usr/bin/env python
"""Seed the perf-provenance ledger from the repo's historical artifacts.

Day-one history for the regression sentinel (ISSUE 9 satellite): the
rounds measured BEFORE the ledger existed — BENCH_r01–r05.json,
MULTICHIP_r01–r05.json, and the MEASURED.json keep-best records — are
replayed into ``artifacts/obs/ledger.jsonl`` in chronological order,
each judged by the sentinel as it lands, so today's first real sweep
already classifies against a measured band instead of opening with
``insufficient_history``.

The nulled rounds are the point: BENCH_r03–r05 (the flaky-attachment
hangs/rc-3 runs that PERF.md adjudicated by hand) land as records with
``value: null`` and ``attachment_health: "down"`` — which the sentinel
classifies ``attachment_transient`` — **not** as gaps. BENCH_r02's
five ``all_variants`` rates each land as their own leg record, so the
fm metric's leg-wide band starts five values deep.

Idempotent AND day-one-only: a ledger that already contains ANY
records is left alone (re-running reports and exits 0). Cohort history
is append order, so seeding 2026-07 values BEHIND live measurements
would drag every trailing band back to the old rates — if you need
history in a live ledger, backfill a fresh file and concatenate it in
front.

Usage::

    python tools/ledger_backfill.py [--ledger PATH] [--repo DIR]
"""

from __future__ import annotations

import calendar
import importlib.util
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: MEASURED.json entry -> (metric leg name, model) — the inverse of
#: bench.py's METRIC_ENTRY map.
MEASURED_LEGS = {
    "headline": ("criteo_fm_rank64_10Mfeat_samples_per_sec_per_chip",
                 "fm"),
    "ffm_avazu": ("avazu_ffm_rank16_samples_per_sec_per_chip", "ffm"),
    "deepfm_criteo": ("criteo_deepfm_rank16_samples_per_sec_per_chip",
                      "deepfm"),
    "fm_kaggle": ("kaggle_fm_rank32_1Mfeat_samples_per_sec_per_chip",
                  "fm_kaggle"),
}

#: All BENCH_r0N artifacts measured the fm headline metric.
FM_LEG = MEASURED_LEGS["headline"][0]
MULTICHIP_LEG = "multichip_projected_aggregate"


def _load_mods():
    mods = {}
    for name in ("ledger", "sentinel"):
        spec = importlib.util.spec_from_file_location(
            f"_backfill_{name}",
            os.path.join(_REPO, "fm_spark_tpu", "obs", f"{name}.py"))
        mod = importlib.util.module_from_spec(spec)
        # Register before exec: dataclass processing looks the module
        # up in sys.modules.
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        mods[name] = mod
    return mods["ledger"], mods["sentinel"]


def _epoch(date: str, hour: int = 12) -> float:
    y, m, d = (int(p) for p in date.split("-"))
    return float(calendar.timegm((y, m, d, hour, 0, 0)))


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def bench_round_records(n: int, doc: dict, lg) -> list[dict]:
    """Ledger records for one BENCH_r0N artifact: one per measured
    variant when the round parsed, else ONE null attachment-transient
    record — a dead round is a data point, not a gap."""
    tail = doc.get("tail") or ""
    parsed = doc.get("parsed") or None
    run_id = f"backfill-bench-r{n:02d}"
    # The rounds ran 2026-07-30 .. 2026-07-31 (tail timestamps);
    # deterministic synthetic ts keeps replays bit-identical.
    ts = _epoch("2026-07-30") + n * 3600.0
    m = re.search(r"device=(.+?) chips=(\d+)", tail)
    device = m.group(1) if m else None
    chips = int(m.group(2)) if m else None
    m = re.search(r"batch=(\d+) steps=(\d+)", tail)
    batch = int(m.group(1)) if m else None
    steps = int(m.group(2)) if m else None

    if parsed and parsed.get("value"):
        out = []
        variants = parsed.get("all_variants") or {
            parsed.get("variant", "?"): parsed["value"]}
        for variant, value in variants.items():
            out.append({
                "kind": "bench_leg", "leg": FM_LEG, "run_id": run_id,
                "variant": variant, "value": float(value),
                "unit": parsed.get("unit", "samples/sec/chip"),
                "ts": ts, "source": "backfill",
                "fingerprint": lg.measurement_fingerprint(
                    variant=variant, model="fm", batch=batch,
                    steps=steps, device_kind=device, n_chips=chips,
                    attachment_health="healthy"),
            })
        return out
    # Nulled round: rc!=0 / no parseable value — the flaky-attachment
    # shape PERF.md used to argue about in prose.
    err = f"rc={doc.get('rc')}"
    m = re.search(r'"error": "([^"]{0,200})', tail)
    if m:
        err += f"; {m.group(1)}"
    return [{
        "kind": "bench_leg", "leg": FM_LEG, "run_id": run_id,
        "variant": None, "value": None, "unit": "samples/sec/chip",
        "ts": ts, "source": "backfill", "error": err,
        "fingerprint": lg.measurement_fingerprint(
            variant="(error)", model="fm", device_kind=device,
            n_chips=chips, attachment_health="down"),
    }]


def multichip_records(n: int, doc: dict, lg) -> list[dict]:
    """One record per MULTICHIP_r0N dryrun: the projected aggregate
    rate when the tail carries a projection block, else a null."""
    tail = doc.get("tail") or ""
    ok = bool(doc.get("ok"))
    value = None
    m = re.search(r"projection=(\{.*\})", tail)
    if m:
        try:
            value = json.loads(m.group(1)).get(
                "projected_aggregate_scaled_batch")
        except json.JSONDecodeError:
            value = None
    return [{
        "kind": "multichip_dryrun", "leg": MULTICHIP_LEG,
        "run_id": f"backfill-multichip-r{n:02d}",
        "variant": "dryrun_multichip",
        "value": float(value) if value else None,
        "unit": "samples/sec_projected_aggregate",
        "ts": _epoch("2026-07-30") + n * 3600.0 + 600.0,
        "source": "backfill", "ok": ok,
        "fingerprint": lg.measurement_fingerprint(
            variant="dryrun_multichip", model="multichip",
            n_chips=doc.get("n_devices"),
            attachment_health="healthy" if ok else "down"),
    }]


def measured_records(measured: dict, lg) -> list[dict]:
    """One record per MEASURED.json entry — the keep-best rates with
    their recorded provenance (date, attachment, variant)."""
    out = []
    for key, (leg, model) in MEASURED_LEGS.items():
        entry = measured.get(key)
        if not entry:
            continue
        out.append({
            "kind": "bench_leg", "leg": leg,
            "run_id": f"backfill-measured-{key}",
            "variant": entry.get("variant"),
            "value": float(entry["rate_samples_per_sec_per_chip"]),
            "unit": "samples/sec/chip",
            # hour=20 on the record's own date: a keep-best postdates
            # the round artifacts measured that same day.
            "ts": _epoch(entry.get("date", "2026-07-31"), hour=20),
            "source": "backfill",
            "measured_entry": key,
            "fingerprint": lg.measurement_fingerprint(
                variant=entry.get("variant"), model=model,
                device_kind=entry.get("attachment"), n_chips=1,
                attachment_health="healthy"),
        })
    return out


def backfill(ledger_path: str, repo: str = _REPO) -> list[dict]:
    """Replay every historical artifact into the ledger (chronological,
    sentinel-judged). Returns the appended records (each carrying its
    ``sentinel`` verdict block); empty when already seeded."""
    lg, st = _load_mods()
    ledger = lg.PerfLedger(ledger_path)
    # Any existing record OF A SEEDED KIND refuses the seed, not just
    # a prior backfill: cohort history is append order, and historical
    # values appended AFTER live measurements would become the band's
    # "most recent" entries — a regressed new rate could then classify
    # flat against the dragged-down band (see module docstring).
    # attachment_probe / kernel_pricing records never enter a bench
    # cohort, so a tpu_watch poll must not forfeit the seed.
    if any(r.get("kind") in ("bench_leg", "multichip_dryrun")
           for r in ledger.records()):
        return []
    sentinel = st.Sentinel(ledger)

    records = []
    for n in range(1, 6):
        doc = _read_json(os.path.join(repo, f"BENCH_r{n:02d}.json"))
        if doc:
            records.extend(bench_round_records(n, doc, lg))
    measured = _read_json(os.path.join(repo, "MEASURED.json"))
    if measured:
        records.extend(measured_records(measured, lg))
    for n in range(1, 6):
        doc = _read_json(os.path.join(repo, f"MULTICHIP_r{n:02d}.json"))
        if doc:
            records.extend(multichip_records(n, doc, lg))

    out = []
    for rec in records:
        block = sentinel.observe(rec)
        out.append(dict(rec, sentinel=block))
    return out


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    ledger_path = None
    repo = _REPO
    while args:
        if args[0] == "--ledger" and len(args) > 1:
            ledger_path = args[1]
            del args[:2]
        elif args[0] == "--repo" and len(args) > 1:
            repo = args[1]
            del args[:2]
        else:
            print(__doc__, file=sys.stderr)
            return 2
    if ledger_path is None:
        ledger_path = os.path.join(repo, "artifacts", "obs",
                                   "ledger.jsonl")
    appended = backfill(ledger_path, repo)
    if not appended:
        print(json.dumps({"ledger": ledger_path, "appended": 0,
                          "note": "ledger already has records — "
                                  "backfill is day-one seeding only "
                                  "(append order IS history order)"}))
        return 0
    verdicts = {}
    for r in appended:
        v = r["sentinel"]["verdict"]
        verdicts[v] = verdicts.get(v, 0) + 1
    print(json.dumps({"ledger": ledger_path, "appended": len(appended),
                      "verdicts": verdicts}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
