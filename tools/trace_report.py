#!/usr/bin/env python3
"""Merge per-process span files into per-request distributed traces.

ISSUE 18's reader half. A traced request crosses at least three
processes — loadgen/client, the front-door+fleet parent, a replica —
and each writes its own ``trace.jsonl`` under its own obs run dir
(all under ONE obs root). This tool stitches them back into one
timeline per ``trace`` id and decomposes the request's latency into a
hop table:

======================  =============================================
``client``              the client's full round trip (loadgen span)
``admission``           front-door admission decision
``frontdoor``           admitted request end-to-end at the door
``dispatch``            fleet parent's dispatch attempt (incl. wire)
``transport``           dispatch minus the replica's server-side time
``replica``             replica request handling (submit + wait)
``coalesce wait``       time queued in the micro-batcher
``execute``             the shared padded-batch device dispatch
``split``               result split/fan-out back to the request
======================  =============================================

Cross-process clocks disagree (span ``t_start`` is wall-clock); the
dispatch hop's send/receive pair gives an NTP-style offset estimate —
``offset = ((t1-t0) + (t2-t3)) / 2`` with t0/t3 the parent's dispatch
span bounds and t1/t2 the replica's handle span bounds — averaged per
(parent pid, replica pid) and applied when laying spans on one
timeline. PIDs are recovered from span ids (``<pid hex>-<seq hex>``).

Torn input is expected, not fatal: junk/truncated JSONL lines are
skipped (the ledger discipline), and a trace whose dispatch span
carries an ``error`` attribute — or that is missing an expected hop
(replica SIGKILL'd mid-request) — renders with the hole flagged.

Usage::

    python tools/trace_report.py artifacts/obs            # top-k table
    python tools/trace_report.py artifacts/obs --trace ID # one trace
"""

from __future__ import annotations

import argparse
import json
import os
import sys

TRACE_FILE = "trace.jsonl"
METRICS_FILE = "metrics.jsonl"

#: Hops every fleet-path trace should have (the client hop is optional
#: — the loadgen may run without an obs plane).
EXPECTED_HOPS = ("frontdoor/admit", "frontdoor/request",
                 "fleet/dispatch", "replica/handle", "serve/coalesce")


def _read_jsonl(path: str) -> list[dict]:
    """Best-effort JSONL reader: junk/truncated lines are skipped —
    a SIGKILL'd writer leaves a torn tail, never a broken report."""
    out = []
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict):
                    out.append(doc)
    except OSError:
        pass
    return out


def span_pid(span_id) -> "int | None":
    """The emitting process, recovered from ``<pid hex>-<seq hex>``."""
    try:
        return int(str(span_id).split("-", 1)[0], 16)
    except (ValueError, AttributeError):
        return None


def collect(root: str) -> list[dict]:
    """Every traced span (records carrying a ``trace`` attribute) from
    every ``trace.jsonl`` under ``root``, recursively."""
    spans = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if TRACE_FILE not in filenames:
            continue
        for doc in _read_jsonl(os.path.join(dirpath, TRACE_FILE)):
            if doc.get("event") == "span" and doc.get("trace"):
                spans.append(doc)
    return spans


def estimate_skew(spans: list[dict]) -> dict:
    """Per (parent pid, replica pid) clock-offset estimates, seconds.

    For every (``fleet/dispatch``, ``replica/handle``) pair stitched
    by ``remote_parent``: the handle interval sits inside the dispatch
    interval on the true timeline, so the midpoint difference is the
    replica-minus-parent clock offset (symmetric-transport assumption
    — the classic NTP estimator). Averaged over all pairs of a pid
    pair."""
    dispatch = {s.get("span_id"): s for s in spans
                if s.get("name") == "fleet/dispatch"}
    sums: dict[tuple, list] = {}
    for s in spans:
        if s.get("name") != "replica/handle":
            continue
        d = dispatch.get(s.get("remote_parent"))
        if d is None:
            continue
        try:
            t0 = float(d["t_start"])
            t3 = t0 + float(d.get("dur_ms") or 0.0) / 1e3
            t1 = float(s["t_start"])
            t2 = t1 + float(s.get("dur_ms") or 0.0) / 1e3
        except (KeyError, TypeError, ValueError):
            continue
        off = ((t1 - t0) + (t2 - t3)) / 2.0
        key = (span_pid(d.get("span_id")), span_pid(s.get("span_id")))
        sums.setdefault(key, []).append(off)
    return {k: sum(v) / len(v) for k, v in sums.items() if v}


def _hop_ms(spans_by_name: dict, name: str) -> "float | None":
    s = spans_by_name.get(name)
    if s is None:
        return None
    try:
        return float(s.get("dur_ms"))
    except (TypeError, ValueError):
        return None


def breakdown(trace: dict) -> dict:
    """Exclusive per-hop milliseconds for one merged trace (None =
    that hop's span is missing). ``dominant`` names the biggest."""
    by = trace["by_name"]
    d_ms = _hop_ms(by, "fleet/dispatch")
    h_ms = _hop_ms(by, "replica/handle")
    f_ms = _hop_ms(by, "frontdoor/request")
    co = by.get("serve/coalesce") or {}

    def attr(k):
        try:
            return float(co[k])
        except (KeyError, TypeError, ValueError):
            return None

    co_ms = _hop_ms(by, "serve/coalesce")
    out = {
        "client": _hop_ms(by, "client/request"),
        "admission": _hop_ms(by, "frontdoor/admit"),
        "frontdoor": (f_ms - d_ms
                      if f_ms is not None and d_ms is not None
                      else f_ms),
        "dispatch": d_ms,
        "transport": (d_ms - h_ms
                      if d_ms is not None and h_ms is not None
                      else None),
        "replica": (h_ms - co_ms
                    if h_ms is not None and co_ms is not None
                    else h_ms),
        "coalesce_wait": attr("queue_ms"),
        "execute": attr("exec_ms"),
        "split": attr("split_ms"),
    }
    ranked = [(v, k) for k, v in out.items()
              if v is not None and k not in ("client", "dispatch")]
    out["dominant"] = max(ranked)[1] if ranked else None
    return out


def merge(root: str) -> dict:
    """All spans under ``root`` merged per trace id. Returns
    ``{trace_id: {"spans", "by_name", "pids", "total_ms", "hops",
    "missing", "error_hops", "incomplete"}}``, skew-corrected onto the
    front-door process's clock."""
    spans = collect(root)
    skew = estimate_skew(spans)
    by_trace: dict[str, list] = {}
    for s in spans:
        by_trace.setdefault(str(s["trace"]), []).append(s)

    out = {}
    for tid, group in by_trace.items():
        group.sort(key=lambda s: float(s.get("t_start") or 0.0))
        pids = sorted({p for p in (span_pid(s.get("span_id"))
                                   for s in group) if p is not None})
        # Skew-correct replica spans onto the dispatching parent's
        # clock where an estimate exists.
        offsets = {rep: off for (_par, rep), off in skew.items()}
        t_bounds = []
        for s in group:
            try:
                t0 = float(s["t_start"])
            except (KeyError, TypeError, ValueError):
                continue
            t0 -= offsets.get(span_pid(s.get("span_id")), 0.0)
            t_bounds.append(t0)
            t_bounds.append(t0 + float(s.get("dur_ms") or 0.0) / 1e3)
        # Last span per name wins (a retried dispatch's second attempt
        # is the one the answer rode).
        by_name = {}
        for s in group:
            by_name[str(s.get("name"))] = s
        error_hops = sorted(s.get("name") for s in group
                            if s.get("error"))
        missing = [h for h in EXPECTED_HOPS if h not in by_name]
        out[tid] = {
            "trace_id": tid,
            "spans": group,
            "by_name": by_name,
            "pids": pids,
            "hops": len(by_name),
            "total_ms": (round((max(t_bounds) - min(t_bounds)) * 1e3,
                               3) if t_bounds else 0.0),
            "missing": missing,
            "error_hops": error_hops,
            "incomplete": bool(missing or error_hops),
        }
    return out


def tail_exemplar(root: str,
                  metric: str = "frontdoor/request_ms"
                  ) -> "dict | None":
    """The slowest recorded exemplar of ``metric`` across every run
    dir under ``root``: ``{"trace_id", "value", "le"}`` from the
    highest populated bucket of the LAST metrics snapshot — the
    concrete request behind the p99 figure."""
    best = None
    for dirpath, _dirnames, filenames in os.walk(root):
        if METRICS_FILE not in filenames:
            continue
        snaps = _read_jsonl(os.path.join(dirpath, METRICS_FILE))
        if not snaps:
            continue
        hist = (snaps[-1].get("histograms") or {}).get(metric) or {}
        for le, ex in (hist.get("exemplars") or {}).items():
            try:
                v = float(ex["value"])
                tid = str(ex["trace_id"])
            except (KeyError, TypeError, ValueError):
                continue
            if best is None or v > best["value"]:
                best = {"trace_id": tid, "value": v, "le": le}
    return best


# ----------------------------------------------------------- rendering


def _fmt_ms(v) -> str:
    return f"{v:9.2f}" if isinstance(v, float) else "(missing)"


def render_trace(trace: dict) -> str:
    bd = breakdown(trace)
    lines = [f"trace {trace['trace_id']}  "
             f"total {trace['total_ms']:.2f} ms  "
             f"{trace['hops']} hops  pids {trace['pids']}"]
    if trace["incomplete"]:
        what = ", ".join(trace["missing"]
                         + [f"{h} (error)" for h in
                            trace["error_hops"]])
        lines.append(f"  INCOMPLETE: {what}")
    for key, label in (("client", "client round trip"),
                       ("admission", "admission"),
                       ("frontdoor", "front door (excl. dispatch)"),
                       ("transport", "dispatch transport"),
                       ("replica", "replica (excl. coalesce)"),
                       ("coalesce_wait", "coalesce wait"),
                       ("execute", "execute"),
                       ("split", "split")):
        mark = " <-- dominant" if key == bd["dominant"] else ""
        lines.append(f"  {label:<28}{_fmt_ms(bd[key])} ms{mark}")
    return "\n".join(lines)


def render(merged: dict, top: int = 5, root: "str | None" = None
           ) -> str:
    if not merged:
        return "no traced requests found\n"
    ranked = sorted(merged.values(), key=lambda t: -t["total_ms"])
    lines = [f"# Request traces ({len(merged)} merged)", ""]
    for tr in ranked[:max(int(top), 1)]:
        lines.append(render_trace(tr))
        lines.append("")
    incomplete = sum(t["incomplete"] for t in merged.values())
    if incomplete:
        lines.append(f"{incomplete} trace(s) incomplete "
                     "(torn/missing hops flagged above)")
    if root:
        ex = tail_exemplar(root)
        if ex:
            resolved = ("resolves to a merged trace"
                        if ex["trace_id"] in merged
                        else "NOT in the merged set")
            lines.append(
                f"tail exemplar: trace {ex['trace_id']} at "
                f"{ex['value']:.2f} ms (le={ex['le']}) — {resolved}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process span JSONL into per-request "
                    "distributed traces")
    ap.add_argument("root", help="obs ROOT holding every process's "
                                 "run dir (e.g. artifacts/obs)")
    ap.add_argument("--top", type=int, default=5,
                    help="show the K slowest traces (default 5)")
    ap.add_argument("--trace", default=None,
                    help="render exactly this trace id")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"not a directory: {args.root}", file=sys.stderr)
        return 2
    merged = merge(args.root)
    if args.trace:
        tr = merged.get(args.trace)
        if tr is None:
            print(f"trace {args.trace!r} not found "
                  f"({len(merged)} merged)", file=sys.stderr)
            return 1
        print(render_trace(tr))
        return 0
    sys.stdout.write(render(merged, top=args.top, root=args.root))
    return 0


if __name__ == "__main__":
    sys.exit(main())
