"""AST lint: resilience/ state transitions go through EventLog, period.

The resilience subsystem's whole value is that a degraded round leaves a
MACHINE-READABLE account of what happened (utils/logging.EventLog —
JSONL, schema'd by ``event``). That property dies the day someone adds a
``print(...)`` or hand-rolls a JSON write inside a recovery path: the
transition becomes stderr prose (or a second, uncoordinated artifact
format) that no tool can consume, and nothing turns red. Same failure
shape as the shadowed-test bug (tests/test_no_shadowed_tests.py): a
silent convention, enforced by nobody.

This lint IS the enforcement, wired into tier-1 via
tests/test_resilience_lint.py. It AST-parses every module under
``fm_spark_tpu/resilience/`` — plus the hardened-ingest modules
``fm_spark_tpu/data/stream.py`` (ISSUE 5) and the native chunk path
``fm_spark_tpu/data/native_stream.py`` / ``fm_spark_tpu/native/
__init__.py`` (ISSUE 6), whose quarantine/abort state transitions
(dead-letter records, the rate-breaker abort) carry the same
machine-readability contract — and flags:

- any ``print(...)`` call (state narration belongs in the journal);
- any ``json.dump``/``json.dumps`` call (an ad-hoc JSON write bypassing
  EventLog's schema/atomicity/best-effort contract);
- any ``sys.stdout``/``sys.stderr`` write.

Allowlist: ``faults.py::_next_count`` persists cross-process occurrence
COUNTERS (bookkeeping the injection harness needs before a journal can
even exist) — it is not a state transition. Anything else wanting an
exemption should probably be an EventLog event instead.

Since ISSUE 7 the lint is also the OBSERVABILITY lint: beyond the
strict EventLog-only scope above, every library module under
``fm_spark_tpu/`` is scanned for *bare* ``print()`` — a print with no
``file=`` destination, i.e. stdout narration that bypasses the
telemetry plane. Numbers belong in the metrics registry
(:mod:`fm_spark_tpu.obs.metrics` / ``MetricsLogger``), narrative in
``EventLog``/spans. A ``print(..., file=...)`` is a *directed*
transport (MetricsLogger's own JSONL stream writes that way) and is
allowed outside the strict scope. The CLI surface (``cli.py``,
``cli_levers.py``, ``__main__.py``) is exempt — a command-line tool's
stdout IS its interface.

Since ISSUE 9 the lint is also the MEASUREMENT-PROVENANCE lint:

- ``time.time()`` inside a subtraction is banned across
  ``fm_spark_tpu/`` (:func:`duration_time_violations`): wall-clock is
  for TIMESTAMPS; a duration computed from it jumps with NTP slews and
  DST — every measured interval goes through
  ``time.perf_counter()``/``time.monotonic()`` (the round-2 "timing
  note" rule, now enforced).
- ``bench.py``'s per-leg sweep record must carry ``run_id`` and
  ``fingerprint`` keys (:func:`bench_leg_record_violations`): a leg
  record that cannot be traced to its run and comparability cohort is
  exactly the hand-adjudicated number the perf ledger retires.

Since ISSUE 10 the lint is also the FAULT-COVERAGE lint: every entry
in ``faults.KNOWN_POINTS`` must be exercised by at least one tier-1
test (:func:`fault_point_coverage_violations`) — a new injection point
cannot ship untested, because an unexercised recovery path is exactly
the blind spot the chaos campaign exists to close.

Since ISSUE 12 the serving runtime (``fm_spark_tpu/serve/``,
:data:`SERVE_DIR`) joins the strict EventLog-only scope, and the
fault-coverage idea extends to the watchdog:
every ``watchdog.KNOWN_PHASES`` entry — including the new
``serve_request`` SLO phase — must appear in at least one tier-1 test
(:func:`watchdog_phase_coverage_violations`).

Since ISSUE 14 the same coverage idea extends to the introspection
plane: every capture trigger registered in
``obs/introspect.py::TRIGGERS`` must appear in at least one tier-1
test (:func:`introspect_trigger_coverage_violations`) — a trigger no
test ever fires is a capture path that can rot silently, exactly like
an unexercised fault point.

Usage::

    python tools/resilience_lint.py        # exit 1 on violations
"""

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESILIENCE_DIR = os.path.join(REPO, "fm_spark_tpu", "resilience")

#: Modules OUTSIDE resilience/ held to the same EventLog-only rule:
#: data/stream.py journals quarantine/abort transitions (ISSUE 5);
#: data/native_stream.py replays the same guard policy from the native
#: chunk parse and native/__init__.py is its binding layer (ISSUE 6) —
#: a stray print/JSON write in either would fork the dead-letter
#: contract the moment ingest goes native.
EXTRA_FILES = (
    os.path.join(REPO, "fm_spark_tpu", "data", "stream.py"),
    os.path.join(REPO, "fm_spark_tpu", "data", "native_stream.py"),
    os.path.join(REPO, "fm_spark_tpu", "native", "__init__.py"),
    # The continuous-learning loop (ISSUE 13): drift verdicts,
    # demotions and rollbacks are operator-facing state transitions —
    # EventLog-only, like the rest of the recovery narrative.
    os.path.join(REPO, "fm_spark_tpu", "online.py"),
)

#: The serving runtime (ISSUE 12) is held to the same EventLog-only
#: rule as resilience/: its state transitions (generation swaps,
#: degraded-mode reload failures, batch failures) are exactly the
#: machine-readable narrative a serving fleet's operator tooling
#: consumes — a stray print or hand-rolled JSON write there forks the
#: contract at the highest-QPS spot in the codebase.
SERVE_DIR = os.path.join(REPO, "fm_spark_tpu", "serve")

#: (filename, enclosing function) pairs exempt from the JSON-write rule.
ALLOWLIST = {
    ("faults.py", "_next_count"),
}

#: The library-wide bare-print scan root (ISSUE 7).
LIBRARY_DIR = os.path.join(REPO, "fm_spark_tpu")

#: Kernel modules (ISSUE 8): every Pallas kernel file under ops/. An
#: attachment without a working Pallas lowering must DEGRADE (the
#: fused_embed='auto' XLA fallback), not die — so kernel availability
#: checks raise the structured ops.PallasUnavailable, never ``assert``
#: (stripped under -O, and an AssertionError is uncatchable-by-contract
#: for the fallback path) and never a bare ``ValueError`` (the fallback
#: resolver pins the PallasUnavailable type).
KERNEL_DIR = os.path.join(REPO, "fm_spark_tpu", "ops")
KERNEL_PREFIX = "pallas_"

#: Top-level library modules whose stdout IS their interface.
CLI_EXEMPT = frozenset({"cli.py", "cli_levers.py", "__main__.py"})


def _call_name(node: ast.Call) -> str:
    """Dotted name of the called object, best-effort ('' if dynamic)."""
    parts = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def _violations_in_tree(tree: ast.AST, filename: str) -> list[str]:
    out = []
    # Parent-function context: walk with an explicit stack so each Call
    # knows its enclosing def (the allowlist granularity).
    def visit(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name == "print":
                out.append(
                    f"{filename}:{node.lineno} [{func or '<module>'}] "
                    "bare print() — emit a journal event "
                    "(utils/logging.EventLog) instead"
                )
            elif name in ("json.dump", "json.dumps"):
                if (filename, func) not in ALLOWLIST:
                    out.append(
                        f"{filename}:{node.lineno} [{func or '<module>'}] "
                        f"ad-hoc JSON write ({name}) — state transitions "
                        "go through EventLog, not hand-rolled JSON"
                    )
            elif name in ("sys.stdout.write", "sys.stderr.write"):
                out.append(
                    f"{filename}:{node.lineno} [{func or '<module>'}] "
                    f"direct {name} — emit a journal event instead"
                )
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return out


def _check_file(path: str) -> list[str]:
    fname = os.path.basename(path)
    with open(path) as f:
        tree = ast.parse(f.read(), filename=fname)
    return _violations_in_tree(tree, fname)


def _bare_prints_in_tree(tree: ast.AST, filename: str) -> list[str]:
    """Library-wide rule (ISSUE 7): ``print()`` with no ``file=``
    destination is stdout narration — route it through the obs plane
    (EventLog / MetricsLogger / obs spans) instead."""
    out = []

    def visit(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if (isinstance(node, ast.Call) and _call_name(node) == "print"
                and not any(kw.arg == "file" for kw in node.keywords)):
            out.append(
                f"{filename}:{node.lineno} [{func or '<module>'}] "
                "bare print() in library code — use MetricsLogger/"
                "EventLog/obs APIs (fm_spark_tpu.obs) instead"
            )
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return out


def library_print_violations(root: str | None = None) -> list[str]:
    """Bare-print violations across every ``.py`` under ``root``
    (default: the whole ``fm_spark_tpu`` package), CLI modules exempt.
    Filenames are reported repo-relative so two modules sharing a
    basename stay distinguishable."""
    root = root or LIBRARY_DIR
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, REPO)
            if (fname in CLI_EXEMPT
                    and os.path.dirname(rel) == "fm_spark_tpu"):
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=rel)
            out.extend(_bare_prints_in_tree(tree, rel))
    return out


def _kernel_fallback_violations_in_tree(tree: ast.AST,
                                        filename: str) -> list[str]:
    """Kernel-module rule (ISSUE 8): no ``assert`` statements, and no
    ``raise ValueError(...)`` — availability/shape constraints raise the
    structured :class:`fm_spark_tpu.ops.PallasUnavailable` so the
    ``fused_embed='auto'`` lever can catch-and-degrade."""
    out = []

    def visit(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, ast.Assert):
            out.append(
                f"{filename}:{node.lineno} [{func or '<module>'}] "
                "assert in a Pallas kernel module — raise "
                "ops.PallasUnavailable so fused_embed='auto' can "
                "degrade to the XLA path instead of dying"
            )
        if isinstance(node, ast.Raise) and isinstance(node.exc, ast.Call):
            f = node.exc.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name == "ValueError":
                out.append(
                    f"{filename}:{node.lineno} [{func or '<module>'}] "
                    "bare ValueError in a Pallas kernel module — raise "
                    "ops.PallasUnavailable (the structured fallback "
                    "signal fused_embed='auto' pins)"
                )
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return out


def kernel_fallback_violations(root: str | None = None) -> list[str]:
    """Structured-fallback violations across every ``pallas_*.py``
    kernel module under ``root`` (default: ``fm_spark_tpu/ops``)."""
    root = root or KERNEL_DIR
    out = []
    for fname in sorted(os.listdir(root)):
        if not (fname.startswith(KERNEL_PREFIX)
                and fname.endswith(".py")):
            continue
        path = os.path.join(root, fname)
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=rel)
        out.extend(_kernel_fallback_violations_in_tree(tree, rel))
    return out


def _time_aliases(tree: ast.AST) -> tuple[set, set]:
    """The file's actual names for the time module and for
    ``time.time`` itself — ``import time as t`` / ``from time import
    time as now`` must not evade the duration rule. Seeded with the
    conventional spellings so a bare ``time()`` is always caught."""
    mods = {"time", "_time"}
    funcs = {"time"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or a.name)
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or a.name)
    return mods, funcs


def _is_wallclock_time_call(node: ast.AST, mods: set = frozenset(),
                            funcs: set = frozenset()) -> bool:
    """Is ``node`` a ``time.time()`` call under any of the file's
    aliases (see :func:`_time_aliases`)?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in (funcs or {"time"})
    if isinstance(f, ast.Attribute) and f.attr == "time":
        return (isinstance(f.value, ast.Name)
                and f.value.id in (mods or {"time", "_time"}))
    return False


def _duration_violations_in_tree(tree: ast.AST,
                                 filename: str) -> list[str]:
    """Provenance rule (ISSUE 9): ``time.time()`` as an operand of a
    subtraction is a DURATION measured on the wall clock — use
    ``time.perf_counter()``/``time.monotonic()``. Timestamp uses
    (record stamps, filenames) stay legal."""
    out = []
    mods, funcs = _time_aliases(tree)

    def flag(node, func):
        out.append(
            f"{filename}:{node.lineno} [{func or '<module>'}] "
            "time.time() in a subtraction — durations go through "
            "time.perf_counter()/time.monotonic(), wall-clock is for "
            "timestamps only"
        )

    def visit(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            func = node.name
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            if (_is_wallclock_time_call(node.left, mods, funcs)
                    or _is_wallclock_time_call(node.right, mods, funcs)):
                flag(node, func)
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.op, ast.Sub)
                and _is_wallclock_time_call(node.value, mods, funcs)):
            flag(node, func)
        for child in ast.iter_child_nodes(node):
            visit(child, func)

    visit(tree, None)
    return out


def duration_time_violations(root: str | None = None) -> list[str]:
    """Wall-clock-duration violations across every ``.py`` under
    ``root`` (default: the whole ``fm_spark_tpu`` package)."""
    root = root or LIBRARY_DIR
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, REPO)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=rel)
            out.extend(_duration_violations_in_tree(tree, rel))
    return out


#: The per-leg sweep-record keys every bench leg must carry (ISSUE 9).
LEG_RECORD_REQUIRED_KEYS = ("run_id", "fingerprint")


def _known_points(faults_path: str) -> list[str]:
    """AST-extract the ``KNOWN_POINTS`` literal from faults.py — no
    package import, so the lint stays runnable from a bare checkout."""
    with open(faults_path) as f:
        tree = ast.parse(f.read(), filename=os.path.basename(faults_path))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "KNOWN_POINTS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def fault_point_coverage_violations(tests_dir: str | None = None,
                                    faults_path: str | None = None
                                    ) -> list[str]:
    """Fault-registry coverage rule (ISSUE 10 satellite): every
    ``KNOWN_POINTS`` entry must appear in at least one tier-1 test
    module — an injection point nobody's test ever names is a recovery
    path that can rot silently, the exact blind spot the chaos
    campaign exists to close. (String-level scan: plans are strings,
    so the point name appearing in a test file IS the exercise
    anchor.)"""
    tests_dir = tests_dir or os.path.join(REPO, "tests")
    faults_path = faults_path or os.path.join(
        REPO, "fm_spark_tpu", "resilience", "faults.py")
    points = _known_points(faults_path)
    if not points:
        return [f"{os.path.basename(faults_path)}: no KNOWN_POINTS "
                "literal found — the fault registry has no anchor to "
                "check coverage against"]
    texts = []
    try:
        for fname in sorted(os.listdir(tests_dir)):
            if fname.startswith("test_") and fname.endswith(".py"):
                with open(os.path.join(tests_dir, fname)) as f:
                    texts.append(f.read())
    except OSError as e:
        return [f"tests dir unreadable ({e})"]
    blob = "\n".join(texts)
    return [
        f"fault point {p!r} (KNOWN_POINTS) is exercised by no test "
        "under tests/ — a new injection point must ship with at least "
        "one tier-1 test that names it"
        for p in points if p not in blob
    ]


def _known_phases(watchdog_path: str) -> list[str]:
    """AST-extract the ``KNOWN_PHASES`` literal from watchdog.py —
    same no-import policy as :func:`_known_points`."""
    with open(watchdog_path) as f:
        tree = ast.parse(f.read(),
                         filename=os.path.basename(watchdog_path))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "KNOWN_PHASES"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def watchdog_phase_coverage_violations(tests_dir: str | None = None,
                                       watchdog_path: str | None = None
                                       ) -> list[str]:
    """Watchdog-phase coverage rule (ISSUE 12 satellite): every
    ``KNOWN_PHASES`` entry must appear in at least one tier-1 test
    module — the ``serve_request`` phase (deadline = the serving SLO)
    joins the registry with this PR, and a guarded phase no test ever
    arms is a deadline that can rot silently, exactly like an
    unexercised fault point."""
    tests_dir = tests_dir or os.path.join(REPO, "tests")
    watchdog_path = watchdog_path or os.path.join(
        REPO, "fm_spark_tpu", "resilience", "watchdog.py")
    phases = _known_phases(watchdog_path)
    if not phases:
        return [f"{os.path.basename(watchdog_path)}: no KNOWN_PHASES "
                "literal found — the watchdog registry has no anchor "
                "to check coverage against"]
    texts = []
    try:
        for fname in sorted(os.listdir(tests_dir)):
            if fname.startswith("test_") and fname.endswith(".py"):
                with open(os.path.join(tests_dir, fname)) as f:
                    texts.append(f.read())
    except OSError as e:
        return [f"tests dir unreadable ({e})"]
    blob = "\n".join(texts)
    return [
        f"watchdog phase {p!r} (KNOWN_PHASES) is exercised by no test "
        "under tests/ — a guarded phase must ship with at least one "
        "tier-1 test that names it"
        for p in phases if p not in blob
    ]


def _known_triggers(introspect_path: str) -> list[str]:
    """AST-extract the ``TRIGGERS`` literal from obs/introspect.py —
    same no-import policy as :func:`_known_points`."""
    with open(introspect_path) as f:
        tree = ast.parse(f.read(),
                         filename=os.path.basename(introspect_path))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "TRIGGERS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            return [e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def introspect_trigger_coverage_violations(
        tests_dir: str | None = None,
        introspect_path: str | None = None) -> list[str]:
    """Introspection-trigger coverage rule (ISSUE 14 satellite): every
    ``TRIGGERS`` entry in obs/introspect.py must appear in at least one
    tier-1 test module — a capture trigger nobody's test ever fires is
    a deep-profiling path that can rot silently, the exact blind spot
    the fault-point and watchdog-phase rules already close."""
    tests_dir = tests_dir or os.path.join(REPO, "tests")
    introspect_path = introspect_path or os.path.join(
        REPO, "fm_spark_tpu", "obs", "introspect.py")
    triggers = _known_triggers(introspect_path)
    if not triggers:
        return [f"{os.path.basename(introspect_path)}: no TRIGGERS "
                "literal found — the introspection registry has no "
                "anchor to check coverage against"]
    texts = []
    try:
        for fname in sorted(os.listdir(tests_dir)):
            if fname.startswith("test_") and fname.endswith(".py"):
                with open(os.path.join(tests_dir, fname)) as f:
                    texts.append(f.read())
    except OSError as e:
        return [f"tests dir unreadable ({e})"]
    blob = "\n".join(texts)
    return [
        f"introspection trigger {t!r} (TRIGGERS) is exercised by no "
        "test under tests/ — a capture trigger must ship with at "
        "least one tier-1 test that fires it"
        for t in triggers if t not in blob
    ]


def bench_leg_record_violations(path: str | None = None) -> list[str]:
    """Provenance rule (ISSUE 9): bench.py's ``leg_record`` dict
    literal must carry :data:`LEG_RECORD_REQUIRED_KEYS` — the AST half
    of the runtime check ``PerfLedger.append`` enforces."""
    path = path or os.path.join(REPO, "bench.py")
    fname = os.path.basename(path)
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=fname)
    except OSError as e:
        return [f"{fname}: unreadable ({e})"]
    found_literal = False
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "leg_record"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        found_literal = True
        keys = {k.value for k in node.value.keys
                if isinstance(k, ast.Constant)}
        missing = [k for k in LEG_RECORD_REQUIRED_KEYS if k not in keys]
        if missing:
            out.append(
                f"{fname}:{node.lineno} leg_record literal missing "
                f"provenance key(s) {missing} — every bench leg record "
                "must carry run_id + fingerprint"
            )
    if not found_literal:
        out.append(
            f"{fname}: no leg_record dict literal found — the sweep's "
            "per-leg provenance contract has no anchor to lint"
        )
    return out


def violations(root: str | None = None) -> list[str]:
    """Violations under ``root`` (a directory); with the default root,
    the shipped surface is checked — every resilience/ module plus
    :data:`EXTRA_FILES` (data/stream.py) and the serving runtime
    (:data:`SERVE_DIR`, ISSUE 12)."""
    default = root is None
    root = root or RESILIENCE_DIR
    out = []
    for fname in sorted(os.listdir(root)):
        if not fname.endswith(".py"):
            continue
        out.extend(_check_file(os.path.join(root, fname)))
    if default:
        for path in EXTRA_FILES:
            out.extend(_check_file(path))
        if os.path.isdir(SERVE_DIR):
            for fname in sorted(os.listdir(SERVE_DIR)):
                if fname.endswith(".py"):
                    out.extend(_check_file(
                        os.path.join(SERVE_DIR, fname)))
    return out


def main() -> int:
    found = (violations() + library_print_violations()
             + kernel_fallback_violations()
             + duration_time_violations()
             + bench_leg_record_violations()
             + fault_point_coverage_violations()
             + watchdog_phase_coverage_violations()
             + introspect_trigger_coverage_violations())
    for v in found:
        print(v, file=sys.stderr)
    if found:
        print(f"{len(found)} observability-logging violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
