"""Compatibility shim over the fmlint registry (ISSUE 15).

The six hand-rolled AST checks that lived here (ISSUEs 4–14: the
EventLog-only scope, the library-wide bare-print ban, the Pallas
structured-fallback rule, the wall-clock-duration ban, the bench
leg-record provenance keys, and the fault/phase/trigger coverage
rules) are now REGISTERED RULES in :mod:`fm_spark_tpu.analysis` —
see ``tools/fmlint.py`` for the CLI, inline suppressions, and the
committed baseline. This module keeps the old entry points alive for
anything still importing them; each delegates to the registry and
renders findings in the historical ``path:line [func] message``
string form.

Usage::

    python tools/resilience_lint.py        # exit 1 on violations
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fmlint import load_analysis  # noqa: E402

_analysis = load_analysis(REPO)

#: Historical names, re-exported for old callers.
RESILIENCE_DIR = os.path.join(REPO, "fm_spark_tpu", "resilience")
SERVE_DIR = os.path.join(REPO, "fm_spark_tpu", "serve")
LIBRARY_DIR = os.path.join(REPO, "fm_spark_tpu")
EXTRA_FILES = tuple(
    os.path.join(REPO, *rel.split("/"))
    for rel in _analysis.rules_obs.STRICT_EXTRA_FILES)


def _render(findings) -> list[str]:
    return [f"{f.path}:{f.line} [{f.func or '<module>'}] {f.message}"
            for f in findings]


def _reject_overrides(**kw) -> None:
    """The shim scans THE SHIPPED REPO through the registry's own
    scope. The old per-call root/path overrides cannot be honored here
    — silently returning whole-repo results to a caller who passed a
    fixture dir would make their check vacuously pass/fail — so a
    non-None override is a loud error pointing at the replacement
    (``analysis.Context(repo)`` + ``run_rules``)."""
    bad = {k: v for k, v in kw.items() if v is not None}
    if bad:
        raise TypeError(
            f"resilience_lint is a shim over the fmlint registry and "
            f"no longer honors {sorted(bad)} — scan a custom root via "
            "fm_spark_tpu.analysis: run_rules(Context(repo), "
            "rules=[...]) (see tests/test_fmlint.py)")


def _run(rule_id: str) -> list[str]:
    found, _suppressed = _analysis.run_rules(
        _analysis.Context(REPO), rules=[rule_id])
    return _render(found)


def violations(root=None) -> list[str]:
    """The strict EventLog-only scope over the shipped tree."""
    _reject_overrides(root=root)
    return _run("eventlog-only")


def library_print_violations(root=None) -> list[str]:
    _reject_overrides(root=root)
    return _run("bare-print")


def kernel_fallback_violations(root=None) -> list[str]:
    _reject_overrides(root=root)
    return _run("pallas-fallback")


def duration_time_violations(root=None) -> list[str]:
    _reject_overrides(root=root)
    return _run("wallclock-duration")


def bench_leg_record_violations(path=None) -> list[str]:
    _reject_overrides(path=path)
    return _run("leg-provenance")


def _coverage(kind_prefix: str) -> list[str]:
    found, _ = _analysis.run_rules(_analysis.Context(REPO),
                                   rules=["registry-coverage"])
    return _render([f for f in found
                    if kind_prefix in f.message])


def fault_point_coverage_violations(tests_dir=None,
                                    faults_path=None) -> list[str]:
    _reject_overrides(tests_dir=tests_dir, faults_path=faults_path)
    return _coverage("fault point")


def watchdog_phase_coverage_violations(tests_dir=None,
                                       watchdog_path=None) -> list[str]:
    _reject_overrides(tests_dir=tests_dir, watchdog_path=watchdog_path)
    return _coverage("watchdog phase")


def introspect_trigger_coverage_violations(tests_dir=None,
                                           introspect_path=None
                                           ) -> list[str]:
    _reject_overrides(tests_dir=tests_dir,
                      introspect_path=introspect_path)
    return _coverage("introspection trigger")


def main() -> int:
    """Full fmlint run (all rules, baseline applied) — the historical
    exit-status contract: 0 clean, 1 on violations."""
    import fmlint

    return fmlint.main(["--no-report", "--quiet"])


if __name__ == "__main__":
    sys.exit(main())
