"""fmlint CLI: run the pluggable static-analysis framework (ISSUE 15).

Usage::

    python tools/fmlint.py                 # full run, exit 1 on NEW findings
    python tools/fmlint.py --list-rules    # the rule glossary
    python tools/fmlint.py --rules jax-host-sync,thread-lock-discipline
    python tools/fmlint.py --write-baseline  # absorb current findings
    python tools/fmlint.py --out DIR       # report dir override (tests)

Exit status: 0 when every (rule, file) finding count is at or under the
committed baseline (``fmlint_baseline.json``; an empty/missing baseline
means any finding fails), 1 otherwise, 2 on usage errors. Every run
writes a JSON report — by default into ``artifacts/obs/<run_id>/
fmlint.json`` (run id minted here, or ``--run-id`` to join an existing
run directory) so ``run_doctor``/``obs_report`` render analysis
regressions next to perf ones.

The analysis package is loaded BY PATH (stdlib-only), so this tool
works from a bare checkout without jax installed.
"""

import argparse
import importlib.util
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis(repo: str = REPO):
    """Import ``fm_spark_tpu.analysis`` WITHOUT importing the jax-heavy
    top-level package: the package is loaded by file path under an
    alias, with submodule search enabled so its relative imports work."""
    pkg_dir = os.path.join(repo, "fm_spark_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "fm_spark_tpu_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def mint_run_id() -> str:
    """Sortable fmlint-prefixed run id (the obs convention, without
    importing the obs plane)."""
    return ("fmlint-" + time.strftime("%Y%m%d-%H%M%S", time.gmtime())
            + f"-p{os.getpid()}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fmlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--repo", default=REPO,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids (default: all)")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: <repo>/fmlint_baseline"
                         ".json; missing file = empty baseline)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="absorb the current findings into the baseline "
                         "and exit 0")
    ap.add_argument("--run-id", default=None,
                    help="write the report into artifacts/obs/<run-id>/ "
                         "(default: a fresh fmlint-… id)")
    ap.add_argument("--out", default=None,
                    help="report directory override (bypasses "
                         "artifacts/obs/)")
    ap.add_argument("--no-report", action="store_true",
                    help="skip writing the JSON report")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule glossary and exit")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-finding stderr lines")
    args = ap.parse_args(argv)

    # Rules always come from THIS checkout's analysis package — --repo
    # only changes what gets scanned (synthetic fixture repos in tests).
    analysis = load_analysis(REPO)

    if args.list_rules:
        for r in analysis.all_rules():
            print(f"{r.id:24s} {r.doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in analysis.RULES]
        if unknown:
            print(f"unknown rule id(s): {unknown} "
                  "(see --list-rules)", file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(
        args.repo, analysis.BASELINE_FILE)
    run_id = args.run_id or mint_run_id()
    report = analysis.analyze(repo=args.repo,
                              baseline_path=baseline_path,
                              rules=rules, run_id=run_id)

    if args.write_baseline:
        # A --rules subset only rewrites the SELECTED rules' cells —
        # every other rule's baselined debt survives untouched (a
        # targeted run must never erase another rule's ledger).
        merged = {r: files for r, files
                  in analysis.load_baseline(baseline_path).items()
                  if rules is not None and r not in rules}
        merged.update(report["counts"])
        analysis.write_baseline_counts(baseline_path, merged)
        print(f"baseline written: {baseline_path} "
              f"({report['total_findings']} finding(s) absorbed"
              + (f" for rules {rules}" if rules is not None else "")
              + ")")
        return 0

    if not args.no_report:
        out_dir = args.out or os.path.join(
            args.repo, "artifacts", "obs", run_id)
        path = analysis.write_report(report, out_dir)
        if path:
            print(f"report: {os.path.relpath(path, args.repo)}",
                  file=sys.stderr)

    if not args.quiet:
        for f in report["new"]:
            ctx_name = f["func"] or "<module>"
            print(f"{f['path']}:{f['line']} [{ctx_name}] "
                  f"{f['rule']}: {f['message']}", file=sys.stderr)
    n_new = len(report["new"])
    n_sup = len(report["suppressed"])
    n_base = report["baselined_total"]
    burn = len(report["burned_down"])
    print(f"fmlint: {report['total_findings']} finding(s) — "
          f"{n_new} new, {n_base} baselined, {n_sup} suppressed"
          + (f", {burn} baseline cell(s) burned down "
             "(run --write-baseline)" if burn else ""),
          file=sys.stderr)
    return 1 if n_new else 0


if __name__ == "__main__":
    sys.exit(main())
