"""Quality-parity protocol: committed, reproducible AUC envelope.

BASELINE.md's quality bar is "AUC within 1e-3 of the Spark CPU baseline"
on config 1 (MovieLens-100K). Neither the reference implementation nor
real MovieLens/Criteo data exists in this image (SURVEY.md §0), so the
committed stand-in oracle chain is:

  numpy float64 full-batch SGD  (this file — independent of JAX; the
        reference's runMiniBatchSGD semantics, SURVEY.md §3.1)
    ⇕  budget 5e-3: different implementation, RNG stream, and init —
       this rung checks the IMPLEMENTATION, not bitwise numerics
    ⇕  the same exact rank-sum AUC is applied to both sides
  fm_spark_tpu fp32 fused step  (the shipped path)
    ⇕  budget 1e-3 (the BASELINE-style bar): same code path, same
       batches — only the numeric shortcut under test differs
  every numeric variant         (bf16+dedup_sr, host_dedup, dedup, ...)

Run `python bench_quality.py` (CPU or TPU); it prints one JSON line per
variant plus a `pass` verdict per comparison. QUALITY.md records the
committed numbers from this exact script. The planted-FM task
(data/synthetic.py) is fully deterministic from its seed, so drift in
any committed number is a regression signal, not noise.
"""

import argparse
import json
import sys

import numpy as np

TASK = dict(n=20_000, num_fields=8, bucket=128, rank=8, planted_rank=4,
            seed=7)
TRAIN = dict(steps=1500, batch=512, lr=0.15)


def _log(msg):
    print(f"bench_quality: {msg}", file=sys.stderr, flush=True)


def _data():
    from fm_spark_tpu.data import synthetic_ctr, train_test_split

    ids, vals, labels = synthetic_ctr(
        TASK["n"], TASK["num_fields"] * TASK["bucket"], TASK["num_fields"],
        rank=TASK["planted_rank"], seed=TASK["seed"],
    )
    offs = (np.arange(TASK["num_fields"]) * TASK["bucket"]).astype(np.int32)
    return train_test_split(ids - offs[None, :], vals, labels, 0.25,
                            seed=TASK["seed"])


def _auc(scores, labels):
    """Exact rank-sum AUC with tie-averaged (mid) ranks — the SAME metric
    is applied to the oracle and to every framework variant so the deltas
    measure numerics, not metric definition (the framework's streaming
    histogram AUC is deliberately NOT used here)."""
    scores = np.asarray(scores, np.float64)
    order = np.argsort(scores, kind="stable")
    s = scores[order]
    ranks_sorted = np.arange(1, len(s) + 1, dtype=np.float64)
    # Average ranks within tied runs.
    boundary = np.concatenate([[True], s[1:] != s[:-1]])
    grp = np.cumsum(boundary) - 1
    sums = np.bincount(grp, weights=ranks_sorted)
    cnts = np.bincount(grp)
    ranks = np.empty(len(s), np.float64)
    ranks[order] = (sums / cnts)[grp]
    pos = np.asarray(labels) > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float(
        (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    )


def numpy_float64_oracle(tr, te):
    """Minibatch SGD on the FM identity in float64 numpy — an
    implementation with no JAX, no fused step, no scatter tricks: the
    independent oracle the fp32 path is judged against."""
    rng = np.random.default_rng(TASK["seed"])
    F, bucket, k = TASK["num_fields"], TASK["bucket"], TASK["rank"]
    n_rows = F * bucket
    v = rng.normal(0, 0.05, size=(n_rows, k)).astype(np.float64)
    w = np.zeros(n_rows, np.float64)
    w0 = 0.0
    ids_tr, vals_tr, y_tr = (np.asarray(a) for a in tr)
    gids = ids_tr + (np.arange(F) * bucket)[None, :]
    n = len(y_tr)
    order = rng.permutation(n)
    lr, B = TRAIN["lr"], TRAIN["batch"]
    pos = 0
    for step in range(TRAIN["steps"]):
        if pos + B > n:
            order = rng.permutation(n)
            pos = 0
        sel = order[pos: pos + B]
        pos += B
        bi, bx, by = gids[sel], vals_tr[sel].astype(np.float64), y_tr[sel]
        rows = v[bi]                                   # [B, F, k]
        xv = rows * bx[..., None]
        s = xv.sum(axis=1)                             # [B, k]
        scores = (w0 + (w[bi] * bx).sum(axis=1)
                  + 0.5 * ((s * s).sum(axis=1) - (xv * xv).sum(axis=(1, 2))))
        p = 1.0 / (1.0 + np.exp(-scores))
        d = (p - by) / B                               # dL/dscore
        g_rows = d[:, None, None] * bx[..., None] * (s[:, None, :] - xv)
        np.add.at(v, bi, -lr * g_rows)
        np.add.at(w, bi, -lr * (d[:, None] * bx))
        w0 -= lr * d.sum()
    ids_te, vals_te, y_te = (np.asarray(a) for a in te)
    gte = ids_te + (np.arange(F) * bucket)[None, :]
    rows = v[gte]
    xv = rows * vals_te[..., None].astype(np.float64)
    s = xv.sum(axis=1)
    scores = (w0 + (w[gte] * vals_te).sum(axis=1)
              + 0.5 * ((s * s).sum(axis=1) - (xv * xv).sum(axis=(1, 2))))
    return _auc(scores, y_te)


def _jax():
    """Import jax honoring an explicit JAX_PLATFORMS=cpu request — the
    installed TPU plugin ignores the env var (same guard as bench.py and
    cli.main; without it a hung TPU attachment hangs this script too)."""
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    return jax


def framework_variant(tr, te, param_dtype="float32",
                      sparse_update="scatter_add", host_dedup=False,
                      compact_cap=0, compute_dtype="float32"):
    jax = _jax()
    import jax.numpy as jnp

    from fm_spark_tpu import models
    from fm_spark_tpu.data import Batches, DedupAuxBatches
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step
    from fm_spark_tpu.train import TrainConfig

    spec = models.FieldFMSpec(
        num_features=TASK["num_fields"] * TASK["bucket"], rank=TASK["rank"],
        num_fields=TASK["num_fields"], bucket=TASK["bucket"], init_std=0.05,
        param_dtype=param_dtype, compute_dtype=compute_dtype,
    )
    config = TrainConfig(
        learning_rate=TRAIN["lr"], lr_schedule="constant", optimizer="sgd",
        sparse_update=sparse_update, host_dedup=host_dedup,
        compact_cap=compact_cap, seed=TASK["seed"],
    )
    step = make_field_sparse_sgd_step(spec, config)
    params = spec.init(jax.random.key(TASK["seed"]))
    batches = Batches(*tr, TRAIN["batch"], seed=TASK["seed"])
    if host_dedup:
        batches = DedupAuxBatches(batches, cap=compact_cap)
    for i in range(TRAIN["steps"]):
        b = tuple(jax.tree_util.tree_map(jnp.asarray, tuple(
            batches.next_batch()
        )))
        params, _ = step(params, jnp.int32(i), *b)
    # Score the held-out set and apply the SAME exact AUC as the oracle
    # (evaluate_params' histogram AUC would conflate metric quantization
    # with numeric parity).
    ids_te, vals_te, y_te = te
    scores = np.asarray(
        spec.scores(params, jnp.asarray(ids_te), jnp.asarray(vals_te)),
        np.float64,
    )
    return _auc(scores, np.asarray(y_te))


VARIANTS = {
    "fp32_scatter_add": dict(),
    "fp32_dedup": dict(sparse_update="dedup"),
    "fp32_host_dedup": dict(sparse_update="dedup", host_dedup=True),
    "bf16_scatter_add": dict(param_dtype="bfloat16"),
    "bf16_dedup_sr": dict(param_dtype="bfloat16", sparse_update="dedup_sr"),
    "bf16_dedup_sr_host": dict(param_dtype="bfloat16",
                               sparse_update="dedup_sr", host_dedup=True),
    # COMPACT host-dedup (the round-2 headline winner): cap=bucket is
    # always sufficient on this task (a field can't have more unique ids
    # than its bucket), so the cap-overflow path never triggers here.
    "fp32_dedup_compact": dict(sparse_update="dedup", host_dedup=True,
                               compact_cap=128),
    "bf16_dedup_sr_compact": dict(param_dtype="bfloat16",
                                  sparse_update="dedup_sr",
                                  host_dedup=True, compact_cap=128),
    # bf16 COMPUTE buffers on top of the compact bf16 path (the [B, w]
    # forward/backward passes in bf16; reductions/cumsum stay fp32).
    "bf16_compact_cdbf16": dict(param_dtype="bfloat16",
                                sparse_update="dedup_sr",
                                host_dedup=True, compact_cap=128,
                                compute_dtype="bfloat16"),
}

# The committed protocol budgets (QUALITY.md): fp32-vs-oracle is expected
# to sit within the BASELINE-style 1e-3 band up to seed noise; the bf16
# scatter_add row is EXPECTED to fail (that is the measured failure
# dedup_sr exists to fix).
BUDGET_VS_FP32 = {
    "fp32_dedup": 1e-3,
    "fp32_host_dedup": 1e-3,
    "bf16_dedup_sr": 5e-3,
    "bf16_dedup_sr_host": 5e-3,
    "fp32_dedup_compact": 1e-3,
    "bf16_dedup_sr_compact": 5e-3,
    "bf16_compact_cdbf16": 5e-3,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--variants", nargs="*", default=list(VARIANTS),
                    choices=list(VARIANTS))
    ap.add_argument("--skip-oracle", action="store_true")
    args = ap.parse_args()

    tr, te = _data()
    out = {}
    if not args.skip_oracle:
        _log("numpy float64 oracle...")
        out["numpy_float64_oracle"] = numpy_float64_oracle(tr, te)
        _log(f"  auc={out['numpy_float64_oracle']:.4f}")
    for name in args.variants:
        _log(f"variant {name}...")
        out[name] = framework_variant(tr, te, **VARIANTS[name])
        _log(f"  auc={out[name]:.4f}")

    checks = {}
    fp32 = out.get("fp32_scatter_add")
    if fp32 is not None and "numpy_float64_oracle" in out:
        d = abs(fp32 - out["numpy_float64_oracle"])
        checks["fp32_vs_float64_oracle"] = {
            "delta": round(d, 5), "budget": 5e-3, "pass": d <= 5e-3,
        }
    for name, budget in BUDGET_VS_FP32.items():
        if fp32 is not None and name in out:
            d = abs(out[name] - fp32)
            checks[f"{name}_vs_fp32"] = {
                "delta": round(d, 5), "budget": budget, "pass": d <= budget,
            }
    # An empty check set must never read as success (a --variants subset
    # that skips the fp32 reference would otherwise vacuously pass).
    ok = bool(checks) and all(c["pass"] for c in checks.values())
    print(json.dumps({
        "task": TASK, "train": TRAIN,
        "auc": {k: round(v, 5) for k, v in out.items()},
        "checks": checks,
        "all_pass": ok,
        **({} if checks else {"error": "no comparisons ran — include "
                              "fp32_scatter_add and/or the oracle"}),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
