"""Quality-parity protocol: committed, reproducible AUC envelope.

BASELINE.md's quality bar is "AUC within 1e-3 of the Spark CPU baseline"
on config 1 (MovieLens-100K). Neither the reference implementation nor
real MovieLens/Criteo data exists in this image (SURVEY.md §0), so the
committed stand-in oracle chain is:

  numpy float64 full-batch SGD  (this file — independent of JAX; the
        reference's runMiniBatchSGD semantics, SURVEY.md §3.1)
    ⇕  budget 5e-3: different implementation, RNG stream, and init —
       this rung checks the IMPLEMENTATION, not bitwise numerics
    ⇕  the same exact rank-sum AUC is applied to both sides
  fm_spark_tpu fp32 fused step  (the shipped path)
    ⇕  budget 1e-3 (the BASELINE-style bar): same code path, same
       batches — only the numeric shortcut under test differs
  every numeric variant         (bf16+dedup_sr, host_dedup, dedup, ...)

Run `python bench_quality.py` (CPU or TPU); it prints one JSON line per
variant plus a `pass` verdict per comparison. `--model ffm|deepfm`
runs the same protocol against model-matched float64 oracles (the
field-aware pairwise term; a hand-written relu-MLP forward/backward) —
VERDICT r2 #5. QUALITY.md records the committed numbers from this
exact script. The planted-FM task (data/synthetic.py) is fully
deterministic from its seed, so drift in any committed number is a
regression signal, not noise.
"""

import argparse
import json
import sys

import numpy as np

TASK = dict(n=20_000, num_fields=8, bucket=128, rank=8, planted_rank=4,
            seed=7)
TRAIN = dict(steps=1500, batch=512, lr=0.15)
# DeepFM quality task: small relu stack over the shared embedding; the
# oracle replicates exactly this architecture in numpy float64.
MLP_DIMS = (32, 32)


def _log(msg):
    print(f"bench_quality: {msg}", file=sys.stderr, flush=True)


def _data():
    from fm_spark_tpu.data import synthetic_ctr, train_test_split

    ids, vals, labels = synthetic_ctr(
        TASK["n"], TASK["num_fields"] * TASK["bucket"], TASK["num_fields"],
        rank=TASK["planted_rank"], seed=TASK["seed"],
    )
    offs = (np.arange(TASK["num_fields"]) * TASK["bucket"]).astype(np.int32)
    return train_test_split(ids - offs[None, :], vals, labels, 0.25,
                            seed=TASK["seed"])


def _auc(scores, labels):
    """Exact rank-sum AUC with tie-averaged (mid) ranks — the SAME metric
    is applied to the oracle and to every framework variant so the deltas
    measure numerics, not metric definition (the framework's streaming
    histogram AUC is deliberately NOT used here)."""
    scores = np.asarray(scores, np.float64)
    order = np.argsort(scores, kind="stable")
    s = scores[order]
    ranks_sorted = np.arange(1, len(s) + 1, dtype=np.float64)
    # Average ranks within tied runs.
    boundary = np.concatenate([[True], s[1:] != s[:-1]])
    grp = np.cumsum(boundary) - 1
    sums = np.bincount(grp, weights=ranks_sorted)
    cnts = np.bincount(grp)
    ranks = np.empty(len(s), np.float64)
    ranks[order] = (sums / cnts)[grp]
    pos = np.asarray(labels) > 0.5
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float(
        (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    )


def numpy_float64_oracle(tr, te):
    """Minibatch SGD on the FM identity in float64 numpy — an
    implementation with no JAX, no fused step, no scatter tricks: the
    independent oracle the fp32 path is judged against."""
    rng = np.random.default_rng(TASK["seed"])
    F, bucket, k = TASK["num_fields"], TASK["bucket"], TASK["rank"]
    n_rows = F * bucket
    v = rng.normal(0, 0.05, size=(n_rows, k)).astype(np.float64)
    w = np.zeros(n_rows, np.float64)
    w0 = 0.0
    ids_tr, vals_tr, y_tr = (np.asarray(a) for a in tr)
    gids = ids_tr + (np.arange(F) * bucket)[None, :]
    n = len(y_tr)
    order = rng.permutation(n)
    lr, B = TRAIN["lr"], TRAIN["batch"]
    pos = 0
    for step in range(TRAIN["steps"]):
        if pos + B > n:
            order = rng.permutation(n)
            pos = 0
        sel = order[pos: pos + B]
        pos += B
        bi, bx, by = gids[sel], vals_tr[sel].astype(np.float64), y_tr[sel]
        rows = v[bi]                                   # [B, F, k]
        xv = rows * bx[..., None]
        s = xv.sum(axis=1)                             # [B, k]
        scores = (w0 + (w[bi] * bx).sum(axis=1)
                  + 0.5 * ((s * s).sum(axis=1) - (xv * xv).sum(axis=(1, 2))))
        p = 1.0 / (1.0 + np.exp(-scores))
        d = (p - by) / B                               # dL/dscore
        g_rows = d[:, None, None] * bx[..., None] * (s[:, None, :] - xv)
        np.add.at(v, bi, -lr * g_rows)
        np.add.at(w, bi, -lr * (d[:, None] * bx))
        w0 -= lr * d.sum()
    ids_te, vals_te, y_te = (np.asarray(a) for a in te)
    gte = ids_te + (np.arange(F) * bucket)[None, :]
    rows = v[gte]
    xv = rows * vals_te[..., None].astype(np.float64)
    s = xv.sum(axis=1)
    scores = (w0 + (w[gte] * vals_te).sum(axis=1)
              + 0.5 * ((s * s).sum(axis=1) - (xv * xv).sum(axis=(1, 2))))
    return _auc(scores, y_te)


def numpy_float64_oracle_ffm(tr, te):
    """Minibatch SGD on the FIELD-AWARE interaction in float64 numpy —
    the FFM analog of :func:`numpy_float64_oracle` (VERDICT r2 #5):
    ``½ Σ_{i≠j} ⟨v[id_i, field j], v[id_j, field i]⟩ x_i x_j`` plus the
    linear/bias terms, no JAX anywhere."""
    rng = np.random.default_rng(TASK["seed"])
    F, bucket, k = TASK["num_fields"], TASK["bucket"], TASK["rank"]
    n_rows = F * bucket
    v = rng.normal(0, 0.05, size=(n_rows, F, k)).astype(np.float64)
    w = np.zeros(n_rows, np.float64)
    w0 = 0.0
    ids_tr, vals_tr, y_tr = (np.asarray(a) for a in tr)
    offs = (np.arange(F) * bucket)[None, :]
    gids = ids_tr + offs
    n = len(y_tr)
    order = rng.permutation(n)
    lr, B = TRAIN["lr"], TRAIN["batch"]
    eye = np.eye(F, dtype=np.float64)[None, :, :, None]

    def ffm_scores(bi, bx, vv, ww, b0):
        sel = vv[bi] * bx[..., None, None]          # [B, F(i), F(j), k]
        a = np.einsum("bijk,bjik->bij", sel, sel)
        diag = np.trace(a, axis1=1, axis2=2)
        return (b0 + (ww[bi] * bx).sum(axis=1)
                + 0.5 * (a.sum(axis=(1, 2)) - diag)), sel

    pos = 0
    for step in range(TRAIN["steps"]):
        if pos + B > n:
            order = rng.permutation(n)
            pos = 0
        sel_idx = order[pos: pos + B]
        pos += B
        bi, bx = gids[sel_idx], vals_tr[sel_idx].astype(np.float64)
        by = y_tr[sel_idx]
        scores, sel = ffm_scores(bi, bx, v, w, w0)
        p = 1.0 / (1.0 + np.exp(-scores))
        d = (p - by) / B
        # dsel[b,i,j] = d · sel[b,j,i], zero diagonal; dv = dsel · x_i.
        dsel = d[:, None, None, None] * np.swapaxes(sel, 1, 2) * (1.0 - eye)
        np.add.at(v, bi, -lr * dsel * bx[..., None, None])
        np.add.at(w, bi, -lr * (d[:, None] * bx))
        w0 -= lr * d.sum()
    ids_te, vals_te, y_te = (np.asarray(a) for a in te)
    scores, _ = ffm_scores(ids_te + offs, vals_te.astype(np.float64), v,
                           w, w0)
    return _auc(scores, y_te)


def numpy_float64_oracle_deepfm(tr, te):
    """Minibatch SGD on DeepFM (shared-embedding FM + relu MLP head) in
    float64 numpy — same architecture as FieldDeepFMSpec with
    ``mlp_dims=MLP_DIMS``, every parameter updated by plain SGD (the
    framework rung below uses optimizer='sgd' to match)."""
    rng = np.random.default_rng(TASK["seed"])
    F, bucket, k = TASK["num_fields"], TASK["bucket"], TASK["rank"]
    n_rows = F * bucket
    v = rng.normal(0, 0.05, size=(n_rows, k)).astype(np.float64)
    w = np.zeros(n_rows, np.float64)
    w0 = 0.0
    dims = (F * k, *MLP_DIMS, 1)
    layers = []
    for d_in, d_out in zip(dims[:-1], dims[1:]):
        layers.append([
            rng.normal(0, np.sqrt(2.0 / d_in),
                       size=(d_in, d_out)).astype(np.float64),
            np.zeros(d_out, np.float64),
        ])
    ids_tr, vals_tr, y_tr = (np.asarray(a) for a in tr)
    offs = (np.arange(F) * bucket)[None, :]
    gids = ids_tr + offs
    n = len(y_tr)
    order = rng.permutation(n)
    lr, B = TRAIN["lr"], TRAIN["batch"]

    def forward(bi, bx, train=True):
        rows = v[bi]
        xv = rows * bx[..., None]                      # [B, F, k]
        s = xv.sum(axis=1)
        fm = (w0 + (w[bi] * bx).sum(axis=1)
              + 0.5 * ((s * s).sum(axis=1) - (xv * xv).sum(axis=(1, 2))))
        h = xv.reshape(len(bi), F * k)
        acts = [h]
        a = h
        for li, (kern, bias) in enumerate(layers):
            a = a @ kern + bias
            if li < len(MLP_DIMS):
                a = np.maximum(a, 0.0)
            acts.append(a)
        return fm + a[:, 0], xv, s, acts

    pos = 0
    for step in range(TRAIN["steps"]):
        if pos + B > n:
            order = rng.permutation(n)
            pos = 0
        sel = order[pos: pos + B]
        pos += B
        bi, bx, by = gids[sel], vals_tr[sel].astype(np.float64), y_tr[sel]
        scores, xv, s, acts = forward(bi, bx)
        p = 1.0 / (1.0 + np.exp(-scores))
        d = (p - by) / B
        # MLP backward (relu stack), collecting the pullback to h.
        g = d[:, None]                                # d wrt last act
        grads = []
        for li in range(len(layers) - 1, -1, -1):
            kern, bias = layers[li]
            a_in = acts[li]
            grads.append((a_in.T @ g, g.sum(axis=0)))
            g = g @ kern.T
            if li > 0:
                g = g * (acts[li] > 0)                # relu mask
        g_h = g.reshape(len(bi), F, k)
        for li, (gk, gb) in enumerate(reversed(grads)):
            layers[li][0] -= lr * gk
            layers[li][1] -= lr * gb
        g_rows = (d[:, None, None] * bx[..., None] * (s[:, None, :] - xv)
                  + g_h * bx[..., None])
        np.add.at(v, bi, -lr * g_rows)
        np.add.at(w, bi, -lr * (d[:, None] * bx))
        w0 -= lr * d.sum()
    ids_te, vals_te, y_te = (np.asarray(a) for a in te)
    scores, _, _, _ = forward(ids_te + offs, vals_te.astype(np.float64))
    return _auc(scores, y_te)


def _jax():
    """Import jax honoring an explicit JAX_PLATFORMS=cpu request — the
    installed TPU plugin ignores the env var, and a dead attachment hangs
    its factory outright (same guard as bench.py and cli.main; without it
    a hung TPU attachment hangs this script too)."""
    import jax

    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()
    return jax


def framework_variant(tr, te, model="fm", param_dtype="float32",
                      sparse_update="scatter_add", host_dedup=False,
                      compact_cap=0, compute_dtype="float32",
                      compact_device=False, sharded=False,
                      collective_dtype="float32", score_sharded=False,
                      deep_sharded=False):
    jax = _jax()
    import jax.numpy as jnp

    from fm_spark_tpu import models
    from fm_spark_tpu.data import Batches, DedupAuxBatches
    from fm_spark_tpu.sparse import (
        make_field_deepfm_sparse_step,
        make_field_ffm_sparse_sgd_step,
        make_field_sparse_sgd_step,
    )
    from fm_spark_tpu.train import TrainConfig

    common = dict(
        num_features=TASK["num_fields"] * TASK["bucket"],
        rank=TASK["rank"], num_fields=TASK["num_fields"],
        bucket=TASK["bucket"], init_std=0.05, param_dtype=param_dtype,
        compute_dtype=compute_dtype,
    )
    config = TrainConfig(
        learning_rate=TRAIN["lr"], lr_schedule="constant", optimizer="sgd",
        sparse_update=sparse_update, host_dedup=host_dedup,
        compact_cap=compact_cap, compact_device=compact_device,
        seed=TASK["seed"], collective_dtype=collective_dtype,
        score_sharded=score_sharded, deep_sharded=deep_sharded,
    )
    opt = None
    if sharded:
        # The wire-precision rows (collective_dtype / score_sharded /
        # deep_sharded) exist only on the sharded steps — run them on
        # every available device (the 8-fake-device CPU mesh in CI; a
        # real slice on hardware). All three families (round 5: FFM
        # budgets the sel-a2a wire dtype — the step's dominant ICI term
        # — and DeepFM the example-sharded head).
        from fm_spark_tpu.parallel import (
            make_field_ffm_sharded_step,
            make_field_mesh,
            make_field_sharded_sgd_step,
            pad_field_batch,
            shard_field_batch,
            shard_field_params,
            stack_field_params,
            unstack_field_params,
        )
        from fm_spark_tpu.parallel.deepfm_step import (
            make_field_deepfm_sharded_step,
            shard_field_deepfm_params,
            stack_field_deepfm_params,
            unstack_field_deepfm_params,
        )

        n = jax.device_count()
        if n < 2:
            raise ValueError(
                "sharded quality rows need >1 device (set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
            )
        mesh = make_field_mesh(n)
        opt_sh = None
        if model == "fm":
            spec = models.FieldFMSpec(**common)
            step_sh = make_field_sharded_sgd_step(spec, config, mesh)
        elif model == "ffm":
            spec = models.FieldFFMSpec(**common)
            step_sh = make_field_ffm_sharded_step(spec, config, mesh)
        elif model == "deepfm":
            spec = models.FieldDeepFMSpec(**common, mlp_dims=MLP_DIMS)
            step_sh = make_field_deepfm_sharded_step(spec, config, mesh)
        else:
            raise ValueError(f"unknown model {model!r}")
        init = spec.init(jax.random.key(TASK["seed"]))
        if model == "deepfm":
            params = shard_field_deepfm_params(
                stack_field_deepfm_params(spec, init, n), mesh
            )
            opt_sh = step_sh.init_opt_state(params)
        else:
            params = shard_field_params(
                stack_field_params(spec, init, n), mesh
            )
        batches = Batches(*tr, TRAIN["batch"], seed=TASK["seed"])
        nf = TASK["num_fields"]
        for i in range(TRAIN["steps"]):
            b = shard_field_batch(
                pad_field_batch(tuple(batches.next_batch()), nf, n), mesh
            )
            if model == "deepfm":
                params, opt_sh, _ = step_sh(params, opt_sh,
                                            jnp.int32(i), *b)
            else:
                params, _ = step_sh(params, jnp.int32(i), *b)
        host = jax.device_get(params)
        params = (unstack_field_deepfm_params(spec, host)
                  if model == "deepfm"
                  else unstack_field_params(spec, host))
        ids_te, vals_te, y_te = te
        scores = np.asarray(
            spec.scores(params, jnp.asarray(ids_te), jnp.asarray(vals_te)),
            np.float64,
        )
        return _auc(scores, np.asarray(y_te))
    if model == "fm":
        spec = models.FieldFMSpec(**common)
        step = make_field_sparse_sgd_step(spec, config)
    elif model == "ffm":
        spec = models.FieldFFMSpec(**common)
        step = make_field_ffm_sparse_sgd_step(spec, config)
    elif model == "deepfm":
        # optimizer='sgd' keeps the dense head on the same rule as the
        # numpy oracle (config 5's Adam is an optimizer choice, not a
        # numerics variant — this chain isolates numerics).
        spec = models.FieldDeepFMSpec(**common, mlp_dims=MLP_DIMS)
        step = make_field_deepfm_sparse_step(spec, config)
    else:
        raise ValueError(f"unknown model {model!r}")
    params = spec.init(jax.random.key(TASK["seed"]))
    if model == "deepfm":
        opt = step.init_opt_state(params)
    batches = Batches(*tr, TRAIN["batch"], seed=TASK["seed"])
    if host_dedup:
        batches = DedupAuxBatches(batches, cap=compact_cap)
    for i in range(TRAIN["steps"]):
        b = tuple(jax.tree_util.tree_map(jnp.asarray, tuple(
            batches.next_batch()
        )))
        if model == "deepfm":
            params, opt, _ = step(params, opt, jnp.int32(i), *b)
        else:
            params, _ = step(params, jnp.int32(i), *b)
    # Score the held-out set and apply the SAME exact AUC as the oracle
    # (evaluate_params' histogram AUC would conflate metric quantization
    # with numeric parity).
    ids_te, vals_te, y_te = te
    scores = np.asarray(
        spec.scores(params, jnp.asarray(ids_te), jnp.asarray(vals_te)),
        np.float64,
    )
    return _auc(scores, np.asarray(y_te))


VARIANTS = {
    "fp32_scatter_add": dict(),
    "fp32_dedup": dict(sparse_update="dedup"),
    "fp32_host_dedup": dict(sparse_update="dedup", host_dedup=True),
    "bf16_scatter_add": dict(param_dtype="bfloat16"),
    "bf16_dedup_sr": dict(param_dtype="bfloat16", sparse_update="dedup_sr"),
    "bf16_dedup_sr_host": dict(param_dtype="bfloat16",
                               sparse_update="dedup_sr", host_dedup=True),
    # COMPACT host-dedup (the round-2 headline winner): cap=bucket is
    # always sufficient on this task (a field can't have more unique ids
    # than its bucket), so the cap-overflow path never triggers here.
    "fp32_dedup_compact": dict(sparse_update="dedup", host_dedup=True,
                               compact_cap=128),
    "bf16_dedup_sr_compact": dict(param_dtype="bfloat16",
                                  sparse_update="dedup_sr",
                                  host_dedup=True, compact_cap=128),
    # bf16 COMPUTE buffers on top of the compact bf16 path (the [B, w]
    # forward/backward passes in bf16; reductions/cumsum stay fp32).
    "bf16_compact_cdbf16": dict(param_dtype="bfloat16",
                                sparse_update="dedup_sr",
                                host_dedup=True, compact_cap=128,
                                compute_dtype="bfloat16"),
    # bf16 COMPUTE over EXACT fp32 storage + plain scatter_add — the
    # measured config-4 (FFM avazu) winner: only the forward/backward
    # buffers round to bf16; tables, gradients-at-rest, and the
    # scatter_add accumulation stay fp32, so no SR is needed.
    "fp32_cdbf16": dict(compute_dtype="bfloat16"),
    # The round-4 wire-precision rows (multi-device only — skipped on a
    # single device): fp32-wire sharded pins the sharded step's own
    # numerics; the bf16-wire rows budget the collective_dtype lever and
    # its composition with the exact score-sharded path.
    "sharded_fp32_wire": dict(sharded=True),
    "sharded_bf16_wire": dict(sharded=True, collective_dtype="bfloat16"),
    "sharded_bf16_wire_ss": dict(sharded=True,
                                 collective_dtype="bfloat16",
                                 score_sharded=True),
    # Round 5: the example-sharded deep head under the bf16 wire
    # (deepfm only — _variant_applies): budgets the lever's end-to-end
    # AUC cost on top of the wire dtype's.
    "sharded_bf16_wire_deep": dict(sharded=True,
                                   collective_dtype="bfloat16",
                                   deep_sharded=True),
}

# The committed protocol budgets (QUALITY.md): fp32-vs-oracle is expected
# to sit within the BASELINE-style 1e-3 band up to seed noise; the bf16
# scatter_add row is EXPECTED to fail (that is the measured failure
# dedup_sr exists to fix).
#
# The ORACLE rung compares two INDEPENDENT implementations (different
# RNG streams, inits, batch orders) — it checks the implementation, not
# numerics. For the convex-ish FM/FFM objectives 5e-3 absorbs that
# variance; DeepFM's nonconvex relu head adds optimization-path variance
# on top (measured fp32-vs-oracle delta 6.2e-3 with tight ≤3e-4
# variant-vs-fp32 rows — i.e. the spread is the TASK, not the code), so
# its rung gets 1e-2. The numerics budgets below are per-variant and
# model-independent.
ORACLE_BUDGET = {"fm": 5e-3, "ffm": 5e-3, "deepfm": 1e-2}
BUDGET_VS_FP32 = {
    "fp32_dedup": 1e-3,
    "fp32_host_dedup": 1e-3,
    "bf16_dedup_sr": 5e-3,
    "bf16_dedup_sr_host": 5e-3,
    "fp32_dedup_compact": 1e-3,
    "bf16_dedup_sr_compact": 5e-3,
    "bf16_compact_cdbf16": 5e-3,
    "fp32_cdbf16": 5e-3,
    "sharded_fp32_wire": 1e-3,
    "sharded_bf16_wire": 5e-3,
    "sharded_bf16_wire_ss": 5e-3,
    "sharded_bf16_wire_deep": 1e-2,
}


def _variant_applies(name: str, kw: dict, model: str) -> bool:
    """Per-model variant applicability (replaces the old FM-only gate on
    every sharded row — round 5 runs the sharded wire rows for all
    three families; only the family-specific levers stay scoped)."""
    if kw.get("score_sharded") and model != "fm":
        return False
    if kw.get("deep_sharded") and model != "deepfm":
        return False
    return True


ORACLES = {
    "fm": numpy_float64_oracle,
    "ffm": numpy_float64_oracle_ffm,
    "deepfm": numpy_float64_oracle_deepfm,
}


def online_smoke():
    """The continuous-learning quality trajectory (ISSUE 13): run the
    online protocol on the planted task with a label-flip drift at
    ``drift_day`` and report the day-over-day eval AUC series, the
    sentry verdict, and the rollback accounting as one JSON line —
    the maintained source of PERF.md's round-17 reference trajectory.
    Passes iff the sentry fires at exactly the first drifted eval day
    and the post-rollback chain tip is a non-demoted generation."""
    import tempfile

    jax = _jax()  # noqa: F841 — force the CPU-guarded backend up front
    from fm_spark_tpu import models, online
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.data import synthetic_ctr
    from fm_spark_tpu.train import FMTrainer, TrainConfig

    n_days, drift_day = 8, 5
    ids, vals, labels = synthetic_ctr(
        4096, TASK["num_fields"] * TASK["bucket"], TASK["num_fields"],
        rank=TASK["planted_rank"], seed=TASK["seed"])
    days = online.flip_labels(
        online.split_days(ids, vals, labels, n_days), drift_day)
    spec = models.FMSpec(num_features=TASK["num_fields"] * TASK["bucket"],
                         rank=TASK["rank"], init_std=0.05)
    trainer = FMTrainer(spec, TrainConfig(
        num_steps=0, batch_size=128, learning_rate=TRAIN["lr"],
        lr_schedule="constant", optimizer="ftrl", log_every=10_000))
    trainer.logger._stream = None
    with tempfile.TemporaryDirectory() as d:
        ck = Checkpointer(d, save_every=10**9, async_save=False)
        summary = online.run_online(trainer, days, ck,
                                    sentry=online.drift_guard())
        stones = ck.tombstoned_steps()
        ck.close()
    rolled = [e for e in summary["days"] if e["rolled_back"]]
    ok = (summary["rollbacks"] >= 1
          and bool(rolled) and rolled[0]["eval_day"] == drift_day
          and summary["last_good"] not in stones)
    print(json.dumps({
        "online_smoke": True, "drift_day": drift_day,
        "days": summary["days"], "rollbacks": summary["rollbacks"],
        "demoted_steps": summary["demoted_steps"],
        "last_good": summary["last_good"],
        "all_pass": ok,
    }))
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="fm", choices=list(ORACLES),
                    help="which oracle chain to run (VERDICT r2 #5: the "
                         "FM protocol, extended to FFM and DeepFM)")
    ap.add_argument("--variants", nargs="*", default=None,
                    choices=list(VARIANTS))
    ap.add_argument("--skip-oracle", action="store_true")
    ap.add_argument("--online-smoke", action="store_true",
                    dest="online_smoke",
                    help="run the continuous-learning quality "
                         "trajectory instead of the oracle chains "
                         "(ISSUE 13): planted drift at day 5 must "
                         "fire the sentry at exactly that eval day "
                         "and roll back")
    args = ap.parse_args()

    if args.online_smoke:
        return online_smoke()

    names = args.variants
    if names is None:
        # Full-B host_dedup rows are FM-only history; the shared compact
        # machinery is what FFM/DeepFM exercise. Sharded wire rows need
        # devices to shard over.
        jax = _jax()
        multi = jax.device_count() > 1
        names = [n for n in VARIANTS
                 if (args.model == "fm" or "host" not in n)
                 and _variant_applies(n, VARIANTS[n], args.model)
                 and (multi or "sharded" not in n)]
    tr, te = _data()
    out = {}
    if not args.skip_oracle:
        _log(f"numpy float64 {args.model} oracle...")
        out["numpy_float64_oracle"] = ORACLES[args.model](tr, te)
        _log(f"  auc={out['numpy_float64_oracle']:.4f}")
    for name in names:
        _log(f"variant {name}...")
        out[name] = framework_variant(tr, te, model=args.model,
                                      **VARIANTS[name])
        _log(f"  auc={out[name]:.4f}")

    checks = {}
    fp32 = out.get("fp32_scatter_add")
    if fp32 is not None and "numpy_float64_oracle" in out:
        d = abs(fp32 - out["numpy_float64_oracle"])
        ob = ORACLE_BUDGET[args.model]
        checks["fp32_vs_float64_oracle"] = {
            "delta": round(d, 5), "budget": ob, "pass": d <= ob,
        }
    for name, budget in BUDGET_VS_FP32.items():
        if fp32 is not None and name in out:
            d = abs(out[name] - fp32)
            checks[f"{name}_vs_fp32"] = {
                "delta": round(d, 5), "budget": budget, "pass": d <= budget,
            }
    # An empty check set must never read as success (a --variants subset
    # that skips the fp32 reference would otherwise vacuously pass).
    ok = bool(checks) and all(c["pass"] for c in checks.values())
    print(json.dumps({
        "model": args.model,
        "task": TASK, "train": TRAIN,
        "auc": {k: round(v, 5) for k, v in out.items()},
        "checks": checks,
        "all_pass": ok,
        **({} if checks else {"error": "no comparisons ran — include "
                              "fp32_scatter_add and/or the oracle"}),
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
