#!/usr/bin/env python
"""Serving latency/throughput ladder: p50/p99 + QPS/chip, sentinel-gated.

The measurement half of the ISSUE 12 serving runtime. Runs the
production :class:`fm_spark_tpu.serve.PredictEngine` through a ladder
of request sizes — batch-1 (pure latency) up through bucket-max (pure
throughput) — plus the two serving-specific legs no training bench
covers:

- **cold vs warm cache**: warmup is timed with compile-cache stats
  around it, so "a warm process never compiles on the request path" is
  a measured number (``fresh_compiles_after_warmup`` must be 0), not a
  claim;
- **reload-under-load**: a writer thread advances a real checkpoint
  chain while closed-loop requests flow; every response is checked for
  generation uniformity (the no-torn-swap invariant), and the run is
  held to :func:`fm_spark_tpu.resilience.chaos.audit_serve_events`.

Every ladder rung lands in the PR-9 perf ledger as a ``serve_bench``
record (full measurement fingerprint, p50/p99 + QPS/chip) and is judged
by the sentinel against its own cohort — serving legs have their own
leg names, so they never share a trailing band with training legs. The
bucket-max rung is the serving headline: on an improved/flat verdict
it promotes into MEASURED.json's ``serving`` entry through the same
keep-best gate bench.py uses (a CPU smoke can seed the entry but never
clobber a TPU-attachment number).

Usage::

    python bench_serve.py                      # full CPU/TPU ladder
    python bench_serve.py --smoke              # bounded tier-1 leg
    python bench_serve.py --buckets 1,8,64,512 --requests 500
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _percentile(sorted_vals: list[float], p: float) -> float:
    """Exact interpolated percentile over a SORTED sample (the ladder
    keeps every latency, so no histogram coarseness here)."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    rank = p * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def _build_engine(args):
    import jax

    from fm_spark_tpu import models
    from fm_spark_tpu.serve import PredictEngine

    spec = models.FieldFMSpec(
        num_features=args.fields * args.bucket, rank=args.rank,
        num_fields=args.fields, bucket=args.bucket, init_std=0.05,
    )
    params = spec.init(jax.random.key(0))
    engine = PredictEngine(
        spec, params, buckets=args.bucket_list,
        latency_budget_ms=args.latency_budget_ms,
    )
    return spec, params, engine


def _run_rung(engine, rows: int, requests: int, rng) -> dict:
    """One ladder rung, two traffic shapes:

    - **trickle** (sequential closed loop) measures what one caller
      sees — p50/p99 include the coalescer's latency-budget wait, so
      the percentiles are honest for the configured budget;
    - **burst** (all requests offered concurrently) measures
      throughput with the micro-batcher actually coalescing — QPS and
      rows/s come from here.
    """
    nnz = engine.nnz
    bucket = engine.spec.bucket
    ids = rng.integers(0, bucket, (rows, nnz)).astype("int32")
    vals = rng.random((rows, nnz)).astype("float32")
    lat = []
    for _ in range(requests):
        t0 = time.perf_counter()
        engine.predict(ids, vals)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    t_burst = time.perf_counter()
    futures = [engine.submit(ids, vals) for _ in range(requests)]
    for f in futures:
        f.result(120)
    burst_s = time.perf_counter() - t_burst
    return {
        "rows_per_request": rows,
        "requests": requests,
        "p50_ms": round(_percentile(lat, 0.50), 4),
        "p99_ms": round(_percentile(lat, 0.99), 4),
        "mean_ms": round(sum(lat) / len(lat), 4),
        "qps": round(requests / burst_s, 2),
        "rows_per_sec": round(rows * requests / burst_s, 2),
        "burst_s": round(burst_s, 3),
    }


def _reload_drill(args, spec, params, engine, run_dir, journal_path
                  ) -> dict:
    """Reload-under-load: a writer advances a real checkpoint chain
    while closed-loop requests flow. Identical request rows per call
    make generation mixing visible: with generation-k params scaled by
    (k+1), every response must be row-uniform (one generation) and the
    observed value set a subset of the planted ones."""
    import numpy as np

    import jax
    from fm_spark_tpu.checkpoint import Checkpointer
    from fm_spark_tpu.resilience import chaos
    from fm_spark_tpu.serve import ReloadFollower
    from fm_spark_tpu.utils.logging import EventLog, read_events

    chain_dir = os.path.join(run_dir, "serve_chain")
    journal = EventLog(journal_path)
    # The drill's engine journals its swaps into the SAME stream the
    # auditor reads — without this, the no-torn-swap monotonicity
    # audit would iterate over zero serve_swap events and be vacuous.
    engine.journal = journal
    gens = args.reload_gens
    scale = lambda k: jax.tree_util.tree_map(
        lambda a: a * float(k + 1), params)

    ck = Checkpointer(chain_dir, save_every=1, async_save=False)
    ck.save(1, scale(0), {}, None, force=True)
    ck.wait()

    follower = ReloadFollower(engine, chain_dir, poll_s=args.poll_s,
                              journal=journal, opt_state_example={})
    assert follower.poll_once() == "swapped"  # generation 1 installed

    rng = np.random.default_rng(7)
    nnz = engine.nnz
    ids = rng.integers(0, spec.bucket, (4, nnz)).astype("int32")
    ids[:] = ids[:1]  # identical rows → per-generation-constant scores
    vals = np.ones((4, nnz), "float32")

    stop = threading.Event()

    def writer():
        for k in range(1, gens):
            time.sleep(args.reload_write_gap_s)
            ck.save(k + 1, scale(k), {}, None, force=True)
            ck.wait()
        stop.set()

    wt = threading.Thread(target=writer, daemon=True)
    follower.start()
    wt.start()
    torn = 0
    responses = 0
    t0 = time.perf_counter()
    while not stop.is_set() and time.perf_counter() - t0 < 60:
        out = engine.predict(ids, vals)
        responses += 1
        if not np.all(out == out[0]):
            torn += 1  # rows from different generations in ONE response
    wt.join(timeout=30)
    # Convergence: the follower must reach the chain tip (bounded
    # staleness after the writer stops).
    deadline = time.monotonic() + 30
    while (engine.generation().step < gens
           and time.monotonic() < deadline):
        time.sleep(args.poll_s)
    follower.stop()
    ck.close()
    from fm_spark_tpu import obs

    final_staleness = int(obs.gauge("serve/staleness_steps").value or 0)
    violations = chaos.audit_serve_events(
        read_events(journal_path), final_staleness=final_staleness,
        staleness_bound=0)
    if torn:
        violations.append({"invariant": "no_torn_swap",
                           "detail": f"{torn} mixed-generation "
                                     "response(s) observed"})
    return {
        "generations": gens,
        "responses_under_load": responses,
        "swaps": follower.reloads,
        "reload_failures": follower.failures,
        "final_step": engine.generation().step,
        "final_staleness_steps": final_staleness,
        "torn_responses": torn,
        "violations": violations,
    }


def _fleet_stats_delta(before: dict, after: dict) -> dict:
    return {k: int(after.get(k) or 0) - int(before.get(k) or 0)
            for k in ("accepted", "answered", "shed", "shed_queue",
                      "shed_deadline", "rejected", "timeout",
                      "failed", "retries")}


def _fleet_ladder(args, run_dir: str, cache_dir
                  ) -> tuple[list[dict], list[dict]]:
    """Fleet rungs (ISSUE 17): aggregate QPS, p99 under shed, and
    replica-loss recovery time for an ``--fleet N`` replica fleet
    behind the production front door, driven by the seeded traffic
    replayer. Each rung is its own ``serve_bench`` leg — its own
    sentinel cohort, never compared against the single-engine ladder
    (a fleet multiplies processes, not chips) — and fleet rungs NEVER
    promote into MEASURED.json. Every rung's tap + counter delta is
    held to :func:`chaos.audit_fleet` (exactly-once, closed books,
    shed accounting)."""
    import jax

    from fm_spark_tpu import models
    from fm_spark_tpu.resilience import chaos
    from fm_spark_tpu.serve import loadgen
    from fm_spark_tpu.serve.fleet import Fleet
    from fm_spark_tpu.serve.frontdoor import (
        AdmissionController,
        FrontDoor,
    )
    from fm_spark_tpu.utils.logging import EventLog, read_events

    n = args.fleet
    fleet_dir = os.path.join(run_dir, "fleet")
    spec = models.FieldFMSpec(
        num_features=args.fields * args.bucket, rank=args.rank,
        num_fields=args.fields, bucket=args.bucket, init_std=0.05)
    params = spec.init(jax.random.key(0))
    model_dir = os.path.join(fleet_dir, "model")
    models.save_model(model_dir, spec, params)
    fleet = Fleet(
        model_dir, n_replicas=n,
        work_dir=os.path.join(fleet_dir, "work"),
        journal=EventLog(os.path.join(run_dir, "fleet_health.jsonl")),
        buckets=args.fleet_buckets,
        latency_budget_ms=args.latency_budget_ms,
        compile_cache_dir=cache_dir)
    fleet.start()
    door = FrontDoor(fleet,
                     admission=AdmissionController(
                         service_est_ms=2.0)).start()
    rows = max(int(b) for b in args.fleet_buckets.split(","))
    kw = dict(nnz=args.fields, num_features=spec.num_features)
    rungs: list[dict] = []
    violations: list[dict] = []
    try:
        # ---- rung 1: aggregate QPS (comfortable deadlines, no shed)
        sched = loadgen.make_schedule(
            "diurnal", 0, duration_s=args.fleet_duration_s,
            base_rps=args.fleet_rps, rows=rows, deadline_ms=8000.0)
        tap = os.path.join(fleet_dir, "tap_qps.jsonl")
        before = door.stats()
        t0 = time.perf_counter()
        loadgen.run_loadgen("127.0.0.1", door.port, sched, tap,
                            threads=16, **kw)
        elapsed = time.perf_counter() - t0
        counters = _fleet_stats_delta(before, door.stats())
        violations += chaos.audit_fleet(
            read_events(tap), counters,
            expected_requests=sched.n_requests)
        s = loadgen.summarize_tap(tap)
        n_ok = s["by_outcome"].get("ok", 0)
        rungs.append({
            "leg": f"fleet_qps_n{n}",
            "requests": sched.n_requests, "ok": n_ok,
            "value": round(n_ok * rows / elapsed, 2),
            "qps": round(n_ok / elapsed, 2),
            "p50_ms": s["ok_p50_ms"], "p99_ms": s["ok_p99_ms"],
            "counters": counters,
        })

        # ---- rung 2: p99 under shed — a retry storm with an
        # unpayable SLO, so admission sheds BEFORE the coalescer;
        # the rung is only honest if the clients' observed sheds
        # match the door's books (audit_fleet's shed_accounting).
        sched = loadgen.make_schedule(
            "retry_storm", 1, duration_s=args.fleet_duration_s,
            base_rps=args.fleet_rps * 2, rows=rows,
            deadline_ms=args.fleet_shed_deadline_ms)
        tap = os.path.join(fleet_dir, "tap_shed.jsonl")
        before = door.stats()
        loadgen.run_loadgen("127.0.0.1", door.port, sched, tap,
                            threads=16, **kw)
        counters = _fleet_stats_delta(before, door.stats())
        violations += chaos.audit_fleet(
            read_events(tap), counters,
            expected_requests=sched.n_requests)
        s = loadgen.summarize_tap(tap)
        p99 = s["ok_p99_ms"]
        rungs.append({
            "leg": f"fleet_p99_shed_n{n}",
            "requests": sched.n_requests,
            "ok": s["by_outcome"].get("ok", 0),
            # Sentinel semantics: lower value = regressed, so the
            # rung's value is answers-per-second at p99 (faster p99
            # under shed pressure = better).
            "value": round(1e3 / p99, 2) if p99 == p99 and p99 > 0
            else 0.0,
            "p99_ms": p99,
            "shed": counters["shed"],
            "shed_fired": counters["shed"] > 0,
            "counters": counters,
        })

        # ---- rung 3: recovery time after a replica SIGKILL under
        # load — kill to every live replica back through the
        # readiness gate.
        sched = loadgen.make_schedule(
            "diurnal", 2, duration_s=max(1.0, args.fleet_duration_s),
            base_rps=args.fleet_rps, rows=rows, deadline_ms=8000.0)
        tap = os.path.join(fleet_dir, "tap_recovery.jsonl")
        before = door.stats()
        lg = threading.Thread(
            target=loadgen.run_loadgen,
            args=("127.0.0.1", door.port, sched, tap),
            kwargs=dict(threads=8, **kw), daemon=True)
        lg.start()
        time.sleep(0.3 * sched.duration_s)
        with fleet._lock:
            ready = [r for r in fleet.replicas
                     if r.state == "ready" and r.proc is not None]
        killed = None
        t_kill = time.monotonic()
        if ready:
            killed = ready[0].idx
            os.kill(ready[0].proc.pid, 9)
        lg.join()
        recovery_s = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            h = fleet.healthz()
            live = [r for r in h["replicas"]
                    if r["state"] not in ("retired", "parked")]
            if live and all(r["state"] == "ready" for r in live):
                recovery_s = round(time.monotonic() - t_kill, 3)
                break
            time.sleep(0.05)
        counters = _fleet_stats_delta(before, door.stats())
        violations += chaos.audit_fleet(
            read_events(tap), counters,
            expected_requests=sched.n_requests)
        if recovery_s is None:
            violations.append({
                "invariant": "staleness_bounded",
                "detail": "fleet never re-admitted a ready replica "
                          "set after the SIGKILL drill"})
        rungs.append({
            "leg": f"fleet_recovery_n{n}",
            "requests": sched.n_requests,
            "killed_replica": killed,
            "recovery_s": recovery_s,
            # 1/recovery so the sentinel's lower-is-regressed rule
            # reads correctly (slower recovery = lower value).
            "value": (round(1.0 / recovery_s, 4)
                      if recovery_s else 0.0),
            "counters": counters,
        })
    finally:
        door.stop()
    return rungs, violations


def _promote(headline: dict, rate_per_chip: float, device: str,
             args, run_ok: bool) -> tuple[bool, str]:
    """The serving keep-best gate (mirrors bench.py's _emit_final
    rules, minus the TPU-only clause — serving has no carried TPU
    number yet, so a first CPU measurement may SEED the entry; it may
    never replace a different-attachment one, and a TPU number always
    outranks a CPU seed). ``run_ok`` is the ladder's own verdict
    (zero fresh compiles after warmup, reload drill green): a run
    that violated its invariants measured the wrong program and its
    rungs stay out of MEASURED.json — the PERF.md round-16 rule."""
    from fm_spark_tpu.measured import load_measured, update_entry
    from fm_spark_tpu.obs import keepbest_allowed

    if not run_ok:
        return False, ("ladder invariants violated (fresh compiles "
                       "after warmup, or a reload-drill violation) — "
                       "rungs stay out of MEASURED.json")
    if not keepbest_allowed(headline.get("sentinel")):
        return False, (
            f"sentinel verdict "
            f"{(headline.get('sentinel') or {}).get('verdict')!r} — "
            "only improved/flat promote")
    try:
        prev_entry = load_measured(args.measured_path).get("serving")
    except (OSError, ValueError):
        prev_entry = None
    is_tpu = "tpu" in device.lower()
    if prev_entry is not None:
        prev_tpu = "tpu" in str(prev_entry.get("attachment", "")).lower()
        if prev_tpu and not is_tpu:
            return False, ("recorded serving rate is a TPU "
                           "measurement; a CPU run never clobbers it")
        same_class = prev_tpu == is_tpu
        if same_class and rate_per_chip <= prev_entry[
                "rate_samples_per_sec_per_chip"]:
            return False, (
                f"measured {rate_per_chip:.0f} <= recorded "
                f"{prev_entry['rate_samples_per_sec_per_chip']:.0f}")
    update_entry(
        "serving",
        rate=rate_per_chip,
        variant=headline["variant"],
        source="bench_serve.py ladder, metric "
               "serve_scored_rows_per_sec_per_chip",
        attachment=device,
        date=time.strftime("%Y-%m-%d", time.gmtime()),
        path=args.measured_path,
    )
    return True, "MEASURED.json serving entry updated"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_serve")
    ap.add_argument("--buckets", default="1,8,64,512",
                    help="comma-separated padded-batch buckets (the "
                         "ladder runs one rung per bucket)")
    ap.add_argument("--requests", type=int, default=300,
                    help="closed-loop requests per ladder rung")
    ap.add_argument("--latency-budget-ms", type=float, default=2.0,
                    dest="latency_budget_ms")
    ap.add_argument("--fields", type=int, default=16)
    ap.add_argument("--bucket", type=int, default=4096,
                    help="per-field hash bucket (model shape)")
    ap.add_argument("--rank", type=int, default=16)
    ap.add_argument("--reload-gens", type=int, default=4,
                    dest="reload_gens",
                    help="checkpoint generations the reload-under-load "
                         "drill publishes")
    ap.add_argument("--reload-write-gap-s", type=float, default=0.3,
                    dest="reload_write_gap_s")
    ap.add_argument("--poll-s", type=float, default=0.05, dest="poll_s")
    ap.add_argument("--skip-reload-drill", action="store_true",
                    dest="skip_reload_drill")
    ap.add_argument("--fleet", type=int, default=0,
                    help="also run the N-replica fleet rungs "
                         "(aggregate QPS, p99 under shed, replica-"
                         "loss recovery) behind the front door")
    ap.add_argument("--fleet-buckets", default="1,8",
                    dest="fleet_buckets",
                    help="padded-batch buckets for fleet replicas "
                         "(kept small: replica warmup is per-process)")
    ap.add_argument("--fleet-rps", type=float, default=80.0,
                    dest="fleet_rps",
                    help="base offered load for the fleet rungs")
    ap.add_argument("--fleet-duration-s", type=float, default=1.5,
                    dest="fleet_duration_s")
    ap.add_argument("--fleet-shed-deadline-ms", type=float,
                    default=120.0, dest="fleet_shed_deadline_ms",
                    help="base deadline for the shed rung (the retry-"
                         "storm shape tightens it 4x — unpayable by "
                         "construction)")
    ap.add_argument("--slo-ms", type=float, default=None, dest="slo_ms",
                    help="arm the serve_request watchdog at this "
                         "deadline (overrun = structured HangDetected)")
    ap.add_argument("--compile-cache", default=None, dest="compile_cache",
                    metavar="DIR",
                    help="persistent compile-cache dir (default: the "
                         "repo-local cache — the warm path IS the "
                         "point of this bench)")
    ap.add_argument("--art-dir", default=os.path.join(_REPO, "artifacts"),
                    dest="art_dir")
    ap.add_argument("--measured-path", default=None, dest="measured_path",
                    help="MEASURED.json to promote into (default: the "
                         "repo's)")
    ap.add_argument("--run-id", default=None, dest="run_id")
    ap.add_argument("--out", default=None,
                    help="also write the result JSON here")
    ap.add_argument("--smoke", action="store_true",
                    help="bounded CPU smoke: small model, short rungs "
                         "(the tier-1 leg)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.buckets = "1,8,32"
        args.requests = min(args.requests, 40)
        args.fields = min(args.fields, 8)
        args.bucket = min(args.bucket, 512)
        args.rank = min(args.rank, 8)
        args.reload_gens = min(args.reload_gens, 3)
        args.reload_write_gap_s = min(args.reload_write_gap_s, 0.2)
        args.fleet_duration_s = min(args.fleet_duration_s, 1.0)
        args.fleet_rps = min(args.fleet_rps, 50.0)
    args.bucket_list = tuple(sorted(
        {int(b) for b in args.buckets.split(",") if b}))

    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()

    from fm_spark_tpu import obs
    from fm_spark_tpu.resilience import watchdog
    from fm_spark_tpu.utils import compile_cache

    run_id = args.run_id or obs.new_run_id()
    run_dir = os.path.join(args.art_dir, "obs", run_id)
    obs.configure(run_dir, run_id=run_id)
    cache_dir = compile_cache.enable(args.compile_cache or None)
    if args.slo_ms is not None:
        watchdog.configure({"serve_request": args.slo_ms / 1e3},
                           action="raise")

    import numpy as np

    import jax

    device = jax.devices()[0].device_kind
    n_chips = 1  # the engine dispatches on one chip (ROADMAP item 2
    # is the multi-chip serving story)

    spec, params, engine = _build_engine(args)
    cold_stats = compile_cache.cache_stats()
    warm = engine.warmup()
    warm_start = warm["fresh_compiles"] == 0

    rng = np.random.default_rng(0)
    rungs = [_run_rung(engine, rows, args.requests, rng)
             for rows in args.bucket_list]
    after_stats = compile_cache.cache_stats()
    fresh_after_warmup = (after_stats["misses"]
                          - warm["cache_stats"]["misses"])

    journal_path = os.path.join(run_dir, "serve_health.jsonl")
    reload_drill = None
    if not args.skip_reload_drill:
        reload_drill = _reload_drill(args, spec, params, engine,
                                     run_dir, journal_path)
    engine.close()

    fleet_rungs: list[dict] = []
    fleet_violations: list[dict] = []
    if args.fleet > 0:
        fleet_rungs, fleet_violations = _fleet_ladder(
            args, run_dir, cache_dir)

    # ------------------------------------------------- ledger + sentinel
    from fm_spark_tpu.obs import (
        PerfLedger,
        Sentinel,
        default_ledger_path,
        measurement_fingerprint,
    )
    from fm_spark_tpu.obs.ledger import runtime_versions

    ledger = PerfLedger(default_ledger_path(args.art_dir))
    sentinel = Sentinel(ledger)
    versions = runtime_versions()
    model_variant = f"fm{args.fields}x{args.bucket}r{args.rank}"
    for rung in rungs:
        b = rung["rows_per_request"]
        variant = (f"serve/{model_variant}/b{b}"
                   f"/budget{args.latency_budget_ms:g}ms")
        rung["variant"] = variant
        fingerprint = measurement_fingerprint(
            variant=variant, model="field_fm", batch=b,
            rank=args.rank,
            extra={"buckets": list(args.bucket_list),
                   "latency_budget_ms": args.latency_budget_ms,
                   "nnz": args.fields},
            device_kind=device, n_chips=n_chips,
            jax_version=versions["jax_version"],
            libtpu_version=versions["libtpu_version"],
        )
        rung["sentinel"] = sentinel.observe({
            "kind": "serve_bench",
            "leg": f"serve_qps_b{b}",
            "run_id": run_id,
            "fingerprint": fingerprint,
            "value": rung["rows_per_sec"] / n_chips,
            "p50_ms": rung["p50_ms"],
            "p99_ms": rung["p99_ms"],
            "qps": rung["qps"],
            "variant": variant,
            "warm_start": warm_start,
            "fresh_compiles_after_warmup": fresh_after_warmup,
        })

    # Fleet rungs: own leg names = own sentinel cohorts. They ride
    # the same ledger kind but are NEVER candidates for promotion —
    # the promotion gate below only ever sees the single-engine
    # headline.
    for rung in fleet_rungs:
        variant = (f"serve/fleet{args.fleet}/{model_variant}"
                   f"/{rung['leg']}")
        rung["variant"] = variant
        fingerprint = measurement_fingerprint(
            variant=variant, model="field_fm",
            batch=max(int(b) for b in args.fleet_buckets.split(",")),
            rank=args.rank,
            extra={"n_replicas": args.fleet,
                   "fleet_buckets": args.fleet_buckets,
                   "latency_budget_ms": args.latency_budget_ms,
                   "nnz": args.fields},
            device_kind=device, n_chips=n_chips,
            jax_version=versions["jax_version"],
            libtpu_version=versions["libtpu_version"],
        )
        rung["sentinel"] = sentinel.observe({
            "kind": "serve_bench",
            "leg": rung["leg"],
            "run_id": run_id,
            "fingerprint": fingerprint,
            "value": rung["value"],
            "variant": variant,
            **{k: rung[k] for k in ("p99_ms", "recovery_s", "shed")
               if k in rung},
        })

    headline = rungs[-1]  # bucket-max rung = the throughput headline
    rate_per_chip = round(headline["rows_per_sec"] / n_chips, 2)
    run_ok = (fresh_after_warmup == 0
              and not (reload_drill and reload_drill["violations"])
              and not fleet_violations)
    promoted, promote_reason = _promote(headline, rate_per_chip,
                                        device, args, run_ok)

    obs.export_snapshot()
    result = {
        "bench": "serve",
        "run_id": run_id,
        "obs_dir": run_dir,
        "device": device,
        "chips": n_chips,
        "buckets": list(args.bucket_list),
        "latency_budget_ms": args.latency_budget_ms,
        "compile_cache_dir": cache_dir,
        "warmup_s": warm["seconds"],
        "warm_start": warm_start,
        "fresh_compiles_at_warmup": warm["fresh_compiles"],
        "fresh_compiles_after_warmup": fresh_after_warmup,
        "rungs": rungs,
        "fleet": ({"n_replicas": args.fleet, "rungs": fleet_rungs,
                   "violations": fleet_violations}
                  if args.fleet > 0 else None),
        "reload_drill": reload_drill,
        "headline_rows_per_sec_per_chip": rate_per_chip,
        "measured_updated": promoted,
        "measured_reason": promote_reason,
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    obs.shutdown()
    return 0 if run_ok else 1


if __name__ == "__main__":
    sys.exit(main())
