"""Weak-scaling convergence A/B: does an n×-larger GLOBAL batch (the
``--batch-per-chip`` weak-scaling recipe) cost quality at an EQUAL
sample budget?

The projection model (parallel/projection.py, PERF.md "Round-4
scale-out levers") names "larger global batch" as a throughput lever
and flags the convergence question; this script answers it on the
committed deterministic planted-FM task (bench_quality.py's TASK) so
the answer is a number, not a guess. Protocol: EPOCH-EXACT equal real
sample budgets — Batches pads each epoch's final partial batch with
weight-0 rows, so every epoch trains on exactly the train-split size
regardless of batch; each arm therefore runs the SAME epoch count
(the baseline's 1500 steps = 50 epochs at batch 512), with per-arm
steps = epochs × ceil(n_train/batch). lr rules per scaled arm: same /
linear ·m / sqrt ·√m. Reported: held-out exact AUC per arm (same
metric as the oracle chain).

Prints one JSON line. CPU-runnable; nothing here measures speed.
"""

import argparse
import json
import sys

import numpy as np

from bench_quality import TASK, TRAIN, _auc, _data


def _log(msg):
    print(f"bench_convergence: {msg}", file=sys.stderr, flush=True)


def run_arm(tr, te, batch, steps, lr):
    import jax
    import jax.numpy as jnp

    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()

    from fm_spark_tpu import models
    from fm_spark_tpu.data import Batches
    from fm_spark_tpu.sparse import make_field_sparse_sgd_step
    from fm_spark_tpu.train import TrainConfig

    spec = models.FieldFMSpec(
        num_features=TASK["num_fields"] * TASK["bucket"],
        rank=TASK["rank"], num_fields=TASK["num_fields"],
        bucket=TASK["bucket"], init_std=0.05,
    )
    step = make_field_sparse_sgd_step(
        spec, TrainConfig(learning_rate=lr, lr_schedule="constant",
                          optimizer="sgd", seed=TASK["seed"]),
    )
    params = spec.init(jax.random.key(TASK["seed"]))
    batches = Batches(*tr, batch, seed=TASK["seed"])
    for i in range(steps):
        b = tuple(map(jnp.asarray, batches.next_batch()))
        params, _ = step(params, jnp.int32(i), *b)
    ids_te, vals_te, y_te = te
    scores = np.asarray(
        spec.scores(params, jnp.asarray(ids_te), jnp.asarray(vals_te)),
        np.float64,
    )
    return _auc(scores, np.asarray(y_te))


def main():
    ap = argparse.ArgumentParser()
    def _pos_int(v):
        iv = int(v)
        if iv < 2:
            raise argparse.ArgumentTypeError("multiplier must be >= 2")
        return iv

    ap.add_argument("--mults", type=_pos_int, nargs="+", default=[4, 8],
                    help="global-batch multipliers to test vs the "
                         "batch-512 baseline (8 = one v5e-8's weak "
                         "scaling)")
    args = ap.parse_args()

    from bench_quality import _jax

    _jax()

    tr, te = _data()
    n_tr = len(tr[2])
    b0, s0, lr0 = TRAIN["batch"], TRAIN["steps"], TRAIN["lr"]
    spe0 = -(-n_tr // b0)                 # steps per epoch, baseline
    if s0 % spe0:
        raise SystemExit(
            f"baseline steps ({s0}) must be whole epochs "
            f"({spe0} steps/epoch at batch {b0}) for the epoch-exact "
            "budget protocol"
        )
    epochs = s0 // spe0
    out = {"baseline": {"batch": b0, "steps": s0, "lr": lr0,
                        "auc": None}}
    _log(f"baseline batch={b0} steps={s0} ({epochs} epochs) lr={lr0}")
    out["baseline"]["auc"] = round(run_arm(tr, te, b0, s0, lr0), 5)
    arms = {}
    for m in args.mults:
        steps_m = epochs * -(-n_tr // (b0 * m))
        for rule, lr in (("same_lr", lr0),
                         ("linear_lr", lr0 * m),
                         ("sqrt_lr", lr0 * m ** 0.5)):
            name = f"x{m}_{rule}"
            _log(f"{name}: batch={b0 * m} steps={steps_m} lr={lr:.3g}")
            arms[name] = {
                "batch": b0 * m, "steps": steps_m, "lr": round(lr, 4),
                "auc": round(run_arm(tr, te, b0 * m, steps_m, lr), 5),
            }
    base_auc = out["baseline"]["auc"]
    best = max(arms.items(), key=lambda kv: kv[1]["auc"])
    print(json.dumps({
        "task": TASK,
        "epochs": epochs,
        "real_samples_budget": epochs * n_tr,
        **out,
        "arms": arms,
        "best_scaled": {"arm": best[0], **best[1],
                        "delta_vs_baseline": round(
                            best[1]["auc"] - base_auc, 5)},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
