#!/usr/bin/env python
"""Tiered embedding-store ladder: 10M → 100M → 1B features (ISSUE 16).

Prices the ``fm_spark_tpu/embed`` memory hierarchy per feature-axis
decade: each rung trains the tiered flat-FM path over a skewed,
drifting id stream (the CTR access pattern the hot tier exists for) and
stamps gathered-rows/s, hot-tier hit rate, HBM watermark, and host RSS
into the ledger as an ``embed_bench`` record with its own sentinel
cohort — tiered legs are NEVER compared against in-HBM legs (a tiered
rows/s prices host↔HBM traffic the in-HBM path does not have; PERF.md
round 20). A ``cost_attribution`` record per rung carries the
bytes-moved model for the transfer term: measured h2d+d2h bytes from
the store's own counters over the timed window.

Honesty contracts, enforced in code:

- the 100M/1B rungs use the LAZY cold store — host RSS tracks the
  TOUCHED bucket set, not the feature axis (``host_bytes`` is stamped
  per rung so "bounded host RSS" is a number, not a claim);
- blocking misses are counted and timed (``stall_ms``) — a rung whose
  prefetcher missed its window shows it;
- the first rung (10M by default, every rung ≤ ``--parity-max``) runs
  a DIFFERENTIAL leg: the same batches through the untiered in-HBM
  sparse step, asserted BITWISE equal to the tiered merged view —
  ``parity_ok`` gates the process exit code.

Usage::

    python bench_embed.py                  # 10M → 100M → 1B ladder
    python bench_embed.py --scale tiny     # CPU tier-1 smoke (seconds)
    python bench_embed.py --decades 10000000,100000000
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

#: Ladder decades (full scale): the feature-axis sizes the paper's CTR
#: workloads actually run, and the honesty floor for ROADMAP item 2.
FULL_DECADES = (10_000_000, 100_000_000, 1_000_000_000)
#: --scale tiny: the tier-1 CPU smoke — same code path, seconds not
#: minutes (two "decades" so the ladder loop itself is exercised).
TINY_DECADES = (204_800, 2_048_000)


def _human(n: int) -> str:
    if n % 1_000_000_000 == 0:
        return f"{n // 1_000_000_000}B"
    if n % 1_000_000 == 0:
        return f"{n // 1_000_000}M"
    if n % 1_000 == 0:
        return f"{n // 1_000}k"
    return str(n)


def _rss_bytes() -> int:
    # ru_maxrss is KiB on Linux (bytes on macOS; this ladder is a
    # Linux/TPU-host tool and the field is labeled).
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def _batch_stream(n_features: int, bucket_rows: int, steps: int,
                  batch: int, nnz: int, working_buckets: int,
                  drift_every: int, seed: int):
    """Deterministic skewed id stream with a drifting working set.

    Each step draws its buckets zipf-style from a window of
    ``working_buckets`` buckets; the window base advances by one bucket
    every ``drift_every`` steps. Total touched buckets ≈ working set +
    drift — BOUNDED, whatever the feature axis, which is what keeps the
    lazy cold store's host RSS flat across decades.
    """
    import numpy as np

    n_buckets = n_features // bucket_rows
    rng = np.random.default_rng(np.random.SeedSequence([seed, n_features]))
    # Zipf-ish rank weights over the window (finite, normalized).
    ranks = np.arange(1, working_buckets + 1, dtype=np.float64)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    for i in range(steps):
        base = (i // drift_every) % max(n_buckets - working_buckets, 1)
        b = rng.choice(working_buckets, size=(batch, nnz), p=probs) + base
        ids = (b * bucket_rows
               + rng.integers(0, bucket_rows, (batch, nnz))).astype(
                   np.int64)
        vals = rng.standard_normal((batch, nnz)).astype(np.float32)
        labels = (rng.random(batch) < 0.3).astype(np.float32)
        weights = np.ones(batch, np.float32)
        yield ids, vals, labels, weights


def _run_rung(nominal: int, args, run_id: str) -> dict:
    """One ladder rung: tiered training over a skewed stream, plus the
    bitwise differential leg when the axis is small enough to hold an
    untiered table."""
    import jax.numpy as jnp
    import numpy as np

    from fm_spark_tpu import embed, obs, sparse
    from fm_spark_tpu.models.fm import FMSpec
    from fm_spark_tpu.train import TrainConfig

    # Hashed spaces round up for free: pad the axis to a whole number
    # of buckets so every decade works at any --bucket-rows. The leg
    # keeps the NOMINAL decade name (the cohort identity).
    n_features = -(-nominal // args.bucket_rows) * args.bucket_rows

    spec = FMSpec(num_features=n_features, rank=args.rank)
    cfg = TrainConfig(
        num_steps=args.steps, batch_size=args.batch,
        learning_rate=0.05, lr_schedule="constant", seed=args.seed,
        optimizer=args.optimizer, embed_tier="require",
        hot_rows=args.hot_buckets * args.bucket_rows,
        embed_bucket_rows=args.bucket_rows)
    # Parity gates on the NOMINAL decade (the padding above must not
    # knock the 10M rung out of its differential leg).
    parity = nominal <= args.parity_max
    trainer = embed.TieredTrainer(
        spec, cfg, cold="dense" if parity else "lazy")

    def stream():
        return _batch_stream(
            n_features, args.bucket_rows, args.steps, args.batch,
            args.nnz, args.working_buckets, args.drift_every, args.seed)

    pf = embed.BucketPrefetcher(stream(), trainer.store,
                                depth=args.prefetch)
    t0 = time.perf_counter()
    try:
        for ids, vals, labels, weights in pf:
            trainer.step_batch(ids, jnp.asarray(vals),
                               jnp.asarray(labels), jnp.asarray(weights))
    finally:
        pf.close()
    dt = time.perf_counter() - t0

    st = trainer.store.stats()
    mem = obs.device_memory_snapshot() or {}
    rows = args.steps * args.batch * args.nnz
    rung = {
        "leg": f"embed_rows_{_human(nominal)}",
        "num_features": n_features,
        "nominal_features": nominal,
        "cold_mode": "dense" if parity else "lazy",
        "steps": args.steps,
        "rows_gathered": rows,
        "seconds": round(dt, 4),
        "rows_per_sec": round(rows / dt, 2),
        "examples_per_sec": round(args.steps * args.batch / dt, 2),
        "hit_rate": round(st["hit_rate"], 6),
        "evictions": st["evictions"],
        "misses": st["misses"],
        "stall_ms": round(st["stall_ms"], 3),
        "prefetch_issued": st["prefetch_issued"],
        "bytes_h2d": st["bytes_h2d"],
        "bytes_d2h": st["bytes_d2h"],
        "hbm_peak_bytes": mem.get("peak_bytes_in_use"),
        "host_rss_bytes": _rss_bytes(),
        "cold_host_bytes": trainer.store.cold.host_bytes(),
        "touched_buckets": trainer.store.cold.touched_buckets(),
        "parity_checked": parity,
        "parity_ok": None,
    }

    if parity:
        # Differential leg: the SAME stream through the untiered
        # in-HBM step; merged tiered view must match BITWISE.
        import jax

        cfg_off = TrainConfig(
            num_steps=args.steps, batch_size=args.batch,
            learning_rate=0.05, lr_schedule="constant", seed=args.seed,
            optimizer=args.optimizer)
        params = spec.init(jax.random.key(args.seed))
        if args.optimizer == "sgd":
            step = sparse.make_sparse_sgd_step(spec, cfg_off)
            for i, (ids, vals, labels, weights) in enumerate(stream()):
                params, _ = step(params, i, jnp.asarray(ids),
                                 jnp.asarray(vals), jnp.asarray(labels),
                                 jnp.asarray(weights))
        else:
            from fm_spark_tpu import optim

            step = optim.make_sparse_adaptive_step(spec, cfg_off)
            slots = optim.init_adaptive_slots(args.optimizer, spec,
                                              params)
            if args.optimizer == "ftrl":
                slots = optim.seed_ftrl_slots(slots, params, 0.05, 1.0)
            for ids, vals, labels, weights in stream():
                params, slots, _ = step(
                    params, slots, jnp.asarray(ids), jnp.asarray(vals),
                    jnp.asarray(labels), jnp.asarray(weights))
        merged = trainer.merged_params()
        rung["parity_ok"] = all(
            np.array_equal(np.asarray(merged[k]), np.asarray(params[k]))
            for k in ("w0", "w", "v"))
    return rung


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="bench_embed")
    ap.add_argument("--decades", default=None,
                    help="comma-separated feature-axis sizes (default: "
                         "the 10M,100M,1B ladder; --scale tiny "
                         "overrides)")
    ap.add_argument("--scale", default="full", choices=["full", "tiny"],
                    help="'tiny' = the bounded CPU smoke the tier-1 "
                         "suite runs (same code path, small axis)")
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--nnz", type=int, default=8)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "ftrl", "adagrad"])
    ap.add_argument("--bucket-rows", type=int, default=1024,
                    dest="bucket_rows")
    ap.add_argument("--hot-buckets", type=int, default=48,
                    dest="hot_buckets",
                    help="hot-tier capacity in buckets (hot_rows = "
                         "this * --bucket-rows)")
    ap.add_argument("--working-buckets", type=int, default=32,
                    dest="working_buckets",
                    help="per-step zipf window in buckets (must be <= "
                         "--hot-buckets: a batch's working set must "
                         "fit the hot tier)")
    ap.add_argument("--drift-every", type=int, default=1,
                    dest="drift_every",
                    help="steps between one-bucket drifts of the zipf "
                         "window (default 1: over the default 40 steps "
                         "the touched set outgrows the hot tier, so "
                         "every rung exercises real eviction churn)")
    ap.add_argument("--prefetch", type=int, default=2,
                    help="BucketPrefetcher depth (>=2 = double-buffer)")
    ap.add_argument("--parity-max", type=int, default=10_000_000,
                    dest="parity_max",
                    help="run the bitwise tiered-vs-untiered "
                         "differential on rungs up to this many "
                         "features (dense cold mode)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--art-dir", default=os.path.join(_REPO, "artifacts"),
                    dest="art_dir")
    ap.add_argument("--run-id", default=None, dest="run_id")
    ap.add_argument("--out", default=None,
                    help="also write the result JSON here")
    args = ap.parse_args(argv)

    if args.scale == "tiny":
        args.steps = min(args.steps, 12)
        args.batch = min(args.batch, 64)
        args.rank = min(args.rank, 4)
        args.bucket_rows = min(args.bucket_rows, 256)
        args.hot_buckets = min(args.hot_buckets, 8)
        args.working_buckets = min(args.working_buckets, 6)
        # Drift fast enough that the smoke crosses hot capacity and
        # exercises the evict/flush path, not just the install path.
        args.drift_every = min(args.drift_every, 2)
        args.parity_max = min(args.parity_max, 400_000)
    if args.decades:
        decades = tuple(int(d) for d in args.decades.split(",") if d)
    else:
        decades = TINY_DECADES if args.scale == "tiny" else FULL_DECADES
    if args.working_buckets > args.hot_buckets:
        raise SystemExit(
            f"--working-buckets {args.working_buckets} > --hot-buckets "
            f"{args.hot_buckets}: a batch working set larger than the "
            "hot tier cannot be made resident")
    for d in decades:
        if args.hot_buckets * args.bucket_rows >= d:
            raise SystemExit(
                f"hot tier ({args.hot_buckets * args.bucket_rows} rows)"
                f" >= decade {d}: nothing to tier at that rung")

    from fm_spark_tpu.utils.cpuguard import force_cpu_platform

    force_cpu_platform()

    from fm_spark_tpu import obs
    from fm_spark_tpu.utils import compile_cache

    run_id = args.run_id or obs.new_run_id()
    run_dir = os.path.join(args.art_dir, "obs", run_id)
    obs.configure(run_dir, run_id=run_id)
    compile_cache.enable_from_env()

    import jax

    device = jax.devices()[0].device_kind

    rungs = []
    for d in decades:
        rung = _run_rung(d, args, run_id)
        rungs.append(rung)
        print(json.dumps({"rung": rung["leg"],
                          "rows_per_sec": rung["rows_per_sec"],
                          "hit_rate": rung["hit_rate"],
                          "host_rss_bytes": rung["host_rss_bytes"]}),
              flush=True)

    # --------------------------------------------------- ledger + sentinel
    from fm_spark_tpu.obs import (
        PerfLedger,
        Sentinel,
        default_ledger_path,
        measurement_fingerprint,
    )
    from fm_spark_tpu.obs.ledger import runtime_versions

    ledger = PerfLedger(default_ledger_path(args.art_dir))
    sentinel = Sentinel(ledger)
    versions = runtime_versions()
    for rung in rungs:
        variant = (f"embed/{_human(rung['num_features'])}"
                   f"/r{args.rank}/{args.optimizer}"
                   f"/hot{args.hot_buckets}x{args.bucket_rows}")
        rung["variant"] = variant
        fingerprint = measurement_fingerprint(
            variant=variant, model="fm", batch=args.batch,
            rank=args.rank,
            extra={"bucket_rows": args.bucket_rows,
                   "hot_buckets": args.hot_buckets,
                   "working_buckets": args.working_buckets,
                   "drift_every": args.drift_every,
                   "prefetch": args.prefetch, "nnz": args.nnz,
                   "cold_mode": rung["cold_mode"]},
            device_kind=device, n_chips=1,
            jax_version=versions["jax_version"],
            libtpu_version=versions["libtpu_version"],
        )
        rung["sentinel"] = sentinel.observe({
            "kind": "embed_bench",
            "leg": rung["leg"],
            "run_id": run_id,
            "fingerprint": fingerprint,
            "value": rung["rows_per_sec"],
            "unit": "rows/s",
            "hit_rate": rung["hit_rate"],
            "evictions": rung["evictions"],
            "stall_ms": rung["stall_ms"],
            "hbm_peak_bytes": rung["hbm_peak_bytes"],
            "host_rss_bytes": rung["host_rss_bytes"],
            "cold_host_bytes": rung["cold_host_bytes"],
            "parity_ok": rung["parity_ok"],
            "variant": variant,
        })
        # Bytes-moved cost model for the host↔HBM transfer term: the
        # store's own h2d/d2h counters over the timed window (measured
        # bucket traffic, not a guess at it).
        bytes_moved = rung["bytes_h2d"] + rung["bytes_d2h"]
        ledger.append({
            "kind": "cost_attribution",
            "leg": f"cost/{rung['leg']}",
            "run_id": run_id,
            "variant": variant,
            "value": round(bytes_moved / rung["seconds"] / 1e9, 3),
            "unit": "GB/s(model)",
            "step_ms": round(rung["seconds"] * 1e3 / args.steps, 3),
            "bytes_per_step": bytes_moved // args.steps,
            "families": {"h2d_bucket_install": rung["bytes_h2d"],
                         "d2h_evict_flush": rung["bytes_d2h"]},
            "assumptions": [
                "bytes = store-counted bucket transfers (install + "
                "dirty evict flush), all planes",
                "blocking-miss stalls counted in stall_ms, not "
                "subtracted from the timed window",
            ],
            "fingerprint": fingerprint,
        })

    parity_ok = all(r["parity_ok"] is not False for r in rungs)
    parity_run = any(r["parity_checked"] for r in rungs)
    obs.export_snapshot()
    result = {
        "bench": "embed",
        "run_id": run_id,
        "obs_dir": run_dir,
        "device": device,
        "decades": list(decades),
        "optimizer": args.optimizer,
        "hot_rows": args.hot_buckets * args.bucket_rows,
        "bucket_rows": args.bucket_rows,
        "rungs": rungs,
        "parity_checked": parity_run,
        "parity_ok": parity_ok,
    }
    line = json.dumps(result)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    obs.shutdown()
    return 0 if parity_ok else 1


if __name__ == "__main__":
    sys.exit(main())
