"""Headline-SHAPE spot check for the bf16-wire lever (VERDICT r4 #5).

The bf16-wire quality rows in QUALITY.md come from a 20k-example planted
task at batch 512-4096 — toy activation shapes. This script runs ONE
field-sharded FM train step at the HEADLINE activation shapes
(B=131072, k=64, 39 fields) on the 8-fake-device CPU mesh, with fp32
wire vs bf16 wire from identical params and batch, and reports the
relative error the wire precision injects into:

  - the step loss,
  - the parameter UPDATE (||p_bf16 − p_fp32|| / ||p_fp32 − p_init||,
    per param group) — the gradient-error norm as it lands in the
    tables, which is what compounds over training.

The bucket is shrunk to 16384 (wire precision touches only the
[B, k]-shaped activation collectives — the psum of (s, sq, lin) — whose
magnitudes depend on B/F/k, not on table height), keeping host memory
sane. Until real multi-chip hardware exists this is the at-scale
evidence next to the toy AUC rows; paste the JSON into QUALITY.md.
"""

import json
import os
import time

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fm_spark_tpu.utils.cpuguard import force_cpu_platform  # noqa: E402

force_cpu_platform(only_if_env=False)

B, F, K, BUCKET = 131072, 39, 64, 16384


def run_step(wire: str):
    from fm_spark_tpu import models
    from fm_spark_tpu.parallel import (
        make_field_mesh,
        make_field_sharded_sgd_step,
        pad_field_batch,
        shard_field_batch,
        shard_field_params,
        stack_field_params,
    )
    from fm_spark_tpu.train import TrainConfig

    spec = models.FieldFMSpec(
        num_features=F * BUCKET, rank=K, num_fields=F, bucket=BUCKET,
        init_std=0.05,
    )
    config = TrainConfig(learning_rate=0.1, optimizer="sgd",
                         reg_linear=1e-5, reg_factors=1e-5,
                         collective_dtype=wire)
    n = 8
    mesh = make_field_mesh(n)
    step = make_field_sharded_sgd_step(spec, config, mesh)
    stacked = stack_field_params(spec, spec.init(jax.random.key(0)), n)
    init = jax.device_get(stacked)
    params = shard_field_params(stacked, mesh)
    rng = np.random.default_rng(0)
    batch = pad_field_batch(
        (
            rng.integers(0, BUCKET, size=(B, F)).astype(np.int32),
            rng.uniform(0.5, 1.5, size=(B, F)).astype(np.float32),
            rng.integers(0, 2, B).astype(np.float32),
            np.ones((B,), np.float32),
        ),
        F, n,
    )
    t0 = time.perf_counter()
    params, loss = step(params, jnp.int32(0), *shard_field_batch(batch,
                                                                 mesh))
    loss = float(loss)
    out = jax.device_get(params)
    print(f"# {wire}: step ran in {time.perf_counter() - t0:.1f}s "
          f"loss={loss:.6f}", flush=True)
    return init, out, loss


def main():
    init, p32, l32 = run_step("float32")
    _, p16, l16 = run_step("bfloat16")
    report = {
        "shape": {"B": B, "F": F, "k": K, "bucket": BUCKET, "n": 8},
        "loss_fp32": l32,
        "loss_bf16_wire": l16,
        "loss_rel_err": abs(l16 - l32) / max(abs(l32), 1e-12),
    }
    for key in p32:
        upd = np.asarray(p32[key], np.float64) - np.asarray(init[key],
                                                           np.float64)
        diff = np.asarray(p16[key], np.float64) - np.asarray(p32[key],
                                                             np.float64)
        denom = float(np.linalg.norm(upd))
        report[f"update_rel_err_{key}"] = (
            float(np.linalg.norm(diff)) / denom if denom else 0.0
        )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
